"""CoreWorker: the in-process runtime of every driver and worker.

Role of the reference's CoreWorker (ray: src/ray/core_worker/core_worker.h:292)
— task submission (core_worker.cc:2147), actor creation (:2224), actor task
submission (:2469), Get (:1542), Put (:1242), Wait (:1735), placement groups
(:2395/:2455) — plus its transports: the lease-based normal-task submitter
with per-scheduling-key worker-lease caching
(transport/direct_task_transport.h:75) and the direct actor submitter with
sequence-number ordering (transport/direct_actor_task_submitter.h:74), the
owner-side task retry FSM (task_manager.h:208) and lineage-based object
recovery (object_recovery_manager.h:41).

One CoreWorker per process; drivers and workers differ only in how they were
started and whether an Executor serves push_task.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import hashlib
import logging
import os
import pickle
import sys
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu import exceptions as exc
from ray_tpu._private import backoff as _backoff
from ray_tpu._private import deadlines as _deadlines
from ray_tpu._private import event_log
from ray_tpu._private import fault_injection as _fi
from ray_tpu._private import serialization as ser
from ray_tpu._private import tracing as _tracing
from ray_tpu._private.config import CONFIG
from ray_tpu._private.ids import (
    ActorID,
    JobID,
    NodeID,
    ObjectID,
    PlacementGroupID,
    TaskID,
    WorkerID,
)
from ray_tpu._private.rpc import (
    ClientPool,
    ConnectionLost,
    EventLoopThread,
    RpcClient,
    RpcServer,
)
from ray_tpu._private.specs import (
    ActorCreationSpec,
    ActorInfo,
    ActorState,
    Address,
    PlacementGroupSpec,
    SchedulingStrategySpec,
    TaskArg,
    TaskSpec,
    TaskType,
    reply_from_wire,
    reply_to_wire,
    spec_from_wire,
    spec_to_wire,
)
from ray_tpu._raylet import ObjectRef, ObjectRefGenerator, global_state
from ray_tpu.gcs import pubsub as ps
from ray_tpu.worker.executor import Executor
from ray_tpu.worker.memory_store import MemoryStore, StoreEntry, _SENTINEL
from ray_tpu.worker.reference_counter import ReferenceCounter

logger = logging.getLogger(__name__)

_task_ctx = threading.local()


@dataclass
class _PendingTask:
    spec: TaskSpec
    retries_left: int
    is_actor_task: bool = False
    pushed_to: Optional[str] = None  # worker rpc address while running
    arg_ids: List[ObjectID] = field(default_factory=list)
    # Pushes that provably never reached a worker (connect refused):
    # requeued without consuming retries_left, bounded by this counter.
    undelivered_failures: int = 0
    # Latency-tracing stamps (time.monotonic, this process's clock):
    # .remote() entry / queued for a lease / push RPC written. The worker
    # returns its own durations in the reply; _on_task_reply stitches both
    # into the per-stage breakdown (_private/latency.py).
    t_submit: Optional[float] = None
    t_queued: Optional[float] = None
    t_pushed: Optional[float] = None


def _slice_segments(segments, off: int, length: int):
    """[off, off+length) across an ordered list of buffer segments without
    flattening the whole payload. A range that lands inside ONE segment
    (the common case: chunk size divides the dominant array buffer) comes
    back as a zero-copy memoryview into that segment — the RPC layer's
    out-of-band framing writes it to the socket as-is; only ranges
    straddling segment boundaries assemble into a fresh buffer."""
    pos = 0
    need_start, need_end = off, off + length
    out = None
    for seg in segments:
        m = memoryview(seg)
        seg_end = pos + m.nbytes
        if seg_end > need_start and pos < need_end:
            a = max(0, need_start - pos)
            b = min(m.nbytes, need_end - pos)
            if out is None and pos <= need_start and seg_end >= need_end:
                return m[a:b].cast("B")  # single-segment: no copy
            if out is None:
                out = bytearray()
            out += m[a:b]
        pos = seg_end
        if pos >= need_end:
            break
    return memoryview(out if out is not None else b"")


@dataclass
class _DepWait:
    """A task parked until its by-reference args materialize (reference:
    core_worker/transport/dependency_resolver.cc:83 — tasks are not
    dispatched until owned deps resolve). Without this, a worker executing
    a dependent task blocks on the arg fetch while HOLDING its CPU slot;
    enough such tasks starve the pool and deadlock the upstream producers
    (e.g. Data's shuffle reduce tasks vs map tasks at n_blocks >= n_cpus).
    """
    spec: TaskSpec
    missing: set


@dataclass
class _GeneratorState:
    total: Optional[int] = None      # known once the task completes
    reported: int = 0
    error: Optional[ser.SerializedObject] = None
    released: bool = False           # consumer closed the stream
    cv: threading.Condition = field(default_factory=threading.Condition)


@dataclass
class _Lease:
    address: Address
    busy: bool = False
    idle_since: float = 0.0


@dataclass
class _KeyState:
    # owner-side submission queue; unbounded BY DESIGN: the bound lives
    # downstream at the raylet lease queue, whose typed pushback paces
    # this queue's drain (pacer below) instead of dropping user work
    pending: deque = field(  # raylint: disable=unbounded-queue
        default_factory=deque)
    leases: Dict[str, _Lease] = field(default_factory=dict)
    inflight_lease_requests: int = 0
    # EMA of per-task wall time for this scheduling key (None = no sample
    # yet). Drives push batching: only provably-short tasks batch.
    avg_task_s: Optional[float] = None
    # AIMD resubmission pacing on typed raylet pushback (lease queue
    # full): delay doubles per retry_later, shrinks additively per grant.
    pacer: _backoff.AIMDPacer = field(default_factory=_backoff.AIMDPacer)


@dataclass
class _ActorRecord:
    actor_id: ActorID
    state: str = "PENDING"  # PENDING | ALIVE | RESTARTING | DEAD
    address: Optional[Address] = None
    seq: int = 0
    # TaskSpec waiting for an address. Bounded by `outstanding` below
    # (actor_mailbox_max, checked synchronously at submit) — the deque
    # itself can't carry the bound because submits buffer before the loop
    # drains them here.
    queue: deque = field(  # raylint: disable=unbounded-queue
        default_factory=deque)
    # Calls accepted (submit_actor_task) and not yet finalized: THE
    # mailbox bound counter, incremented on the user thread so a burst
    # can't overrun the bound while the submit buffer drains. Guarded by
    # `lock`: the increment (user thread) and decrement (loop thread,
    # _finalize_task) are read-modify-writes — unsynchronized, a lost
    # decrement would leak mailbox slots until an idle actor sheds.
    outstanding: int = 0
    lock: threading.Lock = field(default_factory=threading.Lock)
    inflight: int = 0
    death_cause: Optional[str] = None
    max_task_retries: int = 0
    incarnation: int = 0  # observed num_restarts; seq resets per incarnation
    # per-method MAX observed execution time: only provably-short methods
    # may share a batched push (see _push_actor_tasks). Max, not mean — a
    # bimodal long-poll method (usually 1ms, sometimes an hour) must never
    # re-qualify as short.
    method_time_max: Dict[str, float] = field(default_factory=dict)


class CoreWorker:
    def __init__(
        self,
        *,
        mode: str,  # "driver" | "worker"
        gcs_address: str,
        raylet_address: Optional[str],
        job_id: Optional[JobID] = None,
        namespace: str = "",
        node_id: Optional[NodeID] = None,
        host: str = "127.0.0.1",
    ):
        # RT_SPAWN_TIMING: per-phase ctor timing (burst-scale spawn
        # diagnostics; the file is appended by default_worker.py too)
        _timing = os.environ.get("RT_SPAWN_TIMING")
        _marks: List = []
        _t_prev = time.perf_counter()
        _c_prev = time.process_time()

        def _mark(name: str) -> None:
            nonlocal _t_prev, _c_prev
            if _timing:
                now, cnow = time.perf_counter(), time.process_time()
                _marks.append((name, now - _t_prev, cnow - _c_prev))
                _t_prev, _c_prev = now, cnow

        self.mode = mode
        self.namespace = namespace
        # Chaos plans normally arm at fault_injection import; zygote-forked
        # workers inherited the zygote's (possibly pre-plan) module state,
        # so re-check the env here — still free when RAY_TPU_CHAOS is unset.
        if _fi.PLAN is None:
            _fi.load_env_plan()
        self.worker_id = WorkerID.from_random()
        self.node_id = node_id
        self._elog = event_log.logger_for(mode, self.worker_id.hex()[:8])
        self._lt = EventLoopThread(f"cw-{self.worker_id.hex()[:6]}")
        self._server = RpcServer(self._lt, host, label=mode)
        self._peers = ClientPool(
            self._lt,
            peer_meta={"worker_id": self.worker_id.hex(), "label": mode},
            label=mode)
        self._gcs = RpcClient(gcs_address, self._lt, label=mode)
        self.gcs_address = gcs_address
        self._raylet = (RpcClient(raylet_address, self._lt, label=mode)
                        if raylet_address else None)
        self.raylet_address = raylet_address
        self.memory_store = MemoryStore()
        self.reference_counter = ReferenceCounter(
            free_callback=self._free_owned_object,
            notify_owner_release=self._notify_owner_release,
        )
        self.executor = Executor(self)
        self._pending_tasks: Dict[TaskID, _PendingTask] = {}
        # serializes _pending_tasks mutations across the submitting user
        # thread, the RPC loop (_finalize_task), and get()-path
        # reconstruction (user or as_future daemon threads): the
        # check-then-insert in _try_reconstruct must be atomic or two
        # concurrent readers of a lost object both re-execute its task
        self._pending_lock = threading.Lock()
        self._generators: Dict[TaskID, _GeneratorState] = {}
        self._key_states: Dict[tuple, _KeyState] = {}
        self._dep_waiters: Dict[ObjectID, List[_DepWait]] = {}
        # drained whole on every loop wakeup (_drain_submits): depth is
        # bounded by one burst between wakeups, not accumulation
        self._submit_buf: deque = deque()  # raylint: disable=unbounded-queue
        self._submit_scheduled = False
        self._submit_lock = threading.Lock()
        self._inflight_fetches: Dict[ObjectID, Any] = {}
        self._fetch_dedup_lock = threading.Lock()
        self._fetch_sem: Optional[asyncio.Semaphore] = None
        self._actors: Dict[ActorID, _ActorRecord] = {}
        self._actor_sub_started = False
        self._secondary_copies: set = set()
        self._registered_fns: set = set()
        self._fn_blobs: Dict[str, bytes] = {}  # small defs inlined in specs
        self._fn_kv_cache: Dict[bytes, bytes] = {}
        self._prepared_envs: Dict[str, dict] = {}
        self._put_index = 0
        self._put_lock = threading.Lock()
        self._subscriptions: Dict[str, list] = {}
        self._printed_errors: set = set()  # ERROR-channel dedup (task ids)
        self._node_addr_cache: Dict[NodeID, str] = {}
        self._pg_cache: Dict[PlacementGroupID, Any] = {}
        self._task_events: deque = deque(maxlen=10_000)
        # Demand wakeups for the periodic loops (created on the loop by
        # each loop coroutine): at 1k workers/host, fixed-cadence wakeups
        # in every idle worker add up to a measurable slice of the host
        # (~400us/s/worker), so idle workers must cost ~zero.
        self._task_events_wakeup = None
        self._reaper_wakeup = None
        self._shutdown = False
        self.current_actor_id: Optional[ActorID] = None
        self.is_actor_worker = False
        # Node-local shm store provider (plasma equivalent); connected after
        # raylet registration hands us the store socket.
        self.plasma = None

        _mark("fields")
        # -- connect --
        self._register_handlers()
        self.address_str = self._server.start(0)
        # chaos partitions match on endpoint addresses (fault_injection.py)
        self._peers.set_local_id(self.address_str)
        self._gcs.local_id = self.address_str
        if self._raylet is not None:
            self._raylet.local_id = self.address_str
        _mark("server_start")
        if job_id is None:
            if mode == "driver":
                job_id = self._gcs.call("get_next_job_id", {})
            else:
                # workers inherit job context from the tasks they execute
                # (current_job_id); allocating one here cost every worker
                # spawn a blocking GCS round trip and mis-attributed
                # nested submissions to a phantom job
                job_id = JobID.nil()
        self.job_id = job_id
        self._root_task_id = TaskID.for_normal_task(job_id)
        self.address = Address(
            node_id=self.node_id, worker_id=self.worker_id, rpc_address=self.address_str
        )
        # Publish the global worker BEFORE raylet registration: the raylet may
        # lease this worker and push a task the instant registration lands.
        global_state.core_worker = self
        _mark("job_id")
        if self._raylet is not None:
            payload = {
                "worker_id": self.worker_id,
                "pid": os.getpid(),
                # container workers report an in-container pid; the pool
                # matches on the spawn token instead (worker_pool.py)
                "spawn_token": os.environ.get("RT_SPAWN_TOKEN", ""),
                "address": Address(
                    node_id=None, worker_id=self.worker_id,
                    rpc_address=self.address_str),
            }
            env_socket = os.environ.get("RT_STORE_SOCKET")
            if mode == "worker" and self.node_id is not None:
                # One-way registration: everything the reply would carry is
                # already known (node_id from argv, store socket from the
                # spawn env), so the ctor skips a raylet round trip — under
                # a spawn burst that wait was the longest raylet phase.
                # Plasma connects BEFORE the announce so a task pushed the
                # instant registration lands can never observe plasma=None
                # (with the blocking call this was a narrow race).
                self._connect_plasma(env_socket)
                _mark("plasma")

                def _register_failed(e):
                    # an unregistered worker is invisible to the raylet but
                    # its pool handle would sit 'starting' forever; dying
                    # restores the blocking-call semantics (process exits,
                    # pool reaps the pid and respawns)
                    logger.error("worker registration failed: %s", e)
                    os._exit(1)

                self._post_oneway(self._raylet, "register_worker", payload,
                                  retries=2, retry_delay_s=0.5,
                                  on_failure=_register_failed)
                _mark("register")
            else:
                method = ("register_driver" if mode == "driver"
                          else "register_worker")
                reply = self._raylet.call(method, payload)
                self.node_id = reply.get("node_id", node_id)
                self.address = Address(
                    node_id=self.node_id, worker_id=self.worker_id,
                    rpc_address=self.address_str,
                )
                _mark("register")
                self._connect_plasma(reply.get("store_socket") or env_socket)
                _mark("plasma")
        self._lease_reaper = self._lt.submit(self._lease_reaper_loop())
        # Off-loop helpers are spawned NOW, at init: creating a thread
        # from the RPC loop mid-serving (lazy executors, lazy drainers)
        # stalls the loop for tens of ms on gVisor-class kernels — a
        # pure-tail latency tax on every in-flight request (ISSUE 6).
        from ray_tpu._private import latency as _latency

        _latency.start_drainer()
        # Task-event flushing lives on its own daemon thread: formatting
        # a 1s batch is thousands of dict builds at serving rates, and
        # doing it on the RPC loop stalled every in-flight reply for
        # milliseconds once per second (the r05 HTTP p99 regression).
        self._task_events_wakeup = threading.Event()
        self._event_flusher = threading.Thread(
            target=self._task_event_flush_loop,
            name=f"cw-taskev-{self.worker_id.hex()[:6]}", daemon=True)
        self._event_flusher.start()
        # Lifecycle-event flush path: batched RPC to the GCS event manager.
        # First-wins: an embedded head keeps the GCS's direct sink; pure
        # worker/driver processes ship over their existing GCS connection.
        gcs_client = self._gcs

        def _ship_events(events, stats):
            gcs_client.send("add_cluster_events",
                            {"events": events, "stats": stats})

        self._event_sink_token = event_log.set_sink(_ship_events)
        # Span flush path rides the same GCS connection (tracing.py): the
        # embedded head keeps the GCS's direct sink (first-wins).

        def _ship_spans(spans, forced, stats):
            gcs_client.send("add_spans", {"spans": spans, "forced": forced,
                                          "stats": stats})

        self._span_sink_token = _tracing.set_span_sink(_ship_spans)
        # Metric-snapshot push path (health plane): per-process registry
        # snapshots on a background cadence. First-wins, same as above —
        # serve replicas / proxy shards are worker processes, so their
        # serving histograms reach the GCS store through this.
        from ray_tpu.health import push as _health_push

        def _ship_metrics(payload):
            gcs_client.send("push_metrics", payload)

        self._metrics_push_token = _health_push.set_push_sink(
            _ship_metrics, f"{mode}:{os.getpid()}")
        if mode == "worker":
            event_log.set_default_proc_label(f"worker:{os.getpid()}")
            event_log.install_flight_recorder(on_exit=True)
        else:
            if event_log.default_proc_label().startswith("proc:"):
                event_log.set_default_proc_label(f"driver:{os.getpid()}")
            event_log.install_flight_recorder(
                on_exit=CONFIG.flight_recorder_on_exit)
        # Node-death awareness: a dead raylet's TCP connections can linger
        # (especially for in-process test raylets), so lease requests to it
        # would hang. Invalidate its clients the moment the GCS declares it
        # dead, and fail the local raylet over if it was ours.
        self.subscribe(ps.NODE_CHANNEL, self._on_node_event)
        # fire-and-forget: the reply carries nothing, and a blocking wait
        # here queued every spawned worker behind the busy GCS loop.
        # retries=-1 (capped backoff, forever): without the subscription
        # this process never learns of node deaths (stale clients to a dead
        # raylet would hang instead of failing over), and a GCS outage
        # longer than any finite budget must not leave a long-lived worker
        # permanently unsubscribed — while the GCS is down there are no
        # node events to miss, so retrying until it returns loses nothing.
        self._post_oneway(self._gcs, "subscribe", {
            "channel": ps.NODE_CHANNEL,
            "subscriber_address": self.address_str}, retries=-1)
        _mark("subscribe")
        if _timing and mode == "worker":
            from ray_tpu._private.spawn_diag import spawn_timing_write

            spawn_timing_write("phases " + " ".join(
                f"{n}={dt:.4f}/{cdt:.4f}" for n, dt, cdt in _marks))
        if self.mode == "driver" and CONFIG.log_to_driver:
            # worker stdout/stderr + error reports stream to the driver
            # console (reference: worker.py:2003 print_worker_logs /
            # :2115 listen_error_messages)
            self.subscribe(ps.LOG_CHANNEL, self._on_worker_logs)
            self.subscribe(ps.ERROR_CHANNEL, self._on_error_message)
            for chan in (ps.LOG_CHANNEL, ps.ERROR_CHANNEL):
                self._gcs.call("subscribe", {
                    "channel": chan,
                    "subscriber_address": self.address_str})

    def _post_oneway(self, client, method: str, payload, *,
                     retries: int = 0, retry_delay_s: float = 1.0,
                     on_failure=None) -> None:
        """Schedule a one-way message on the loop without waiting for the
        write to drain (ctor hot path: a cross-thread wait per message is
        pure overhead when no reply is coming). Transient connect failures
        retry with a delay; retries=-1 retries forever with backoff capped
        at 15s (for messages the process cannot function without). After a
        finite budget, `on_failure` runs (default: log) — fire-and-forget
        must not mean fail-silent."""

        async def _attempt():
            remaining, delay = retries, retry_delay_s
            while True:
                try:
                    await client.send_async(method, payload)
                    return
                except Exception as e:  # noqa: BLE001 — peer down
                    if remaining == 0:
                        if on_failure is not None:
                            on_failure(e)
                        else:
                            logger.warning("one-way %s to %s failed: %s",
                                           method, client.address, e)
                        return
                    remaining -= 1
                    await asyncio.sleep(delay)
                    delay = min(delay * 2, 15.0)

        self._lt.submit(_attempt())

    def _connect_plasma(self, store_socket: Optional[str]) -> None:
        if not store_socket or not CONFIG.enable_plasma_store:
            return
        try:
            from ray_tpu.worker.plasma_provider import PlasmaProvider

            def _raylet_call(method, payload):
                return self._raylet.call(method, payload, timeout=60)

            self.plasma = PlasmaProvider(store_socket, _raylet_call)
            if (self.mode == "driver"
                    and os.environ.get("RT_STORE_PREFAULT") == "1"):
                # Opt-in (long-lived perf contexts): warm the driver's
                # arena mapping so the first checkpoint/weights-sized put
                # runs at memcpy speed. See StoreClient.prefault for why
                # this must not be default-on.
                self.plasma.prefault()
        except Exception as e:  # noqa: BLE001 — degrade to in-memory objects
            logger.warning("plasma store unavailable: %s", e)
            self.plasma = None

    def _plasma_threshold(self) -> int:
        return CONFIG.max_direct_call_object_size

    # ---------------------------------------------------------- runtime envs
    job_runtime_env: Optional[dict] = None  # job default (init(runtime_env=))

    def set_job_runtime_env(self, env: Optional[dict]) -> None:
        """Install the job-level runtime env (client-proxy sessions set it
        over RPC after client-side packaging; api.init sets the attribute
        directly for local drivers)."""
        self.job_runtime_env = env

    def prepare_runtime_env(self, env: Optional[dict]) -> Optional[dict]:
        """Driver-side: merge over the job default, validate, and upload any
        local working_dir/py_modules to the GCS KV (packaging.py role)."""
        from ray_tpu import runtime_env as re_mod

        base = self.job_runtime_env
        if base and env:
            merged = {**base, **env}
            ev = {**(base.get("env_vars") or {}), **(env.get("env_vars") or {})}
            if ev:
                merged["env_vars"] = ev
            env = merged
        elif base:
            env = dict(base)
        env = re_mod.validate(env)
        if env is None:
            return None
        cached = self._prepared_envs.get(re_mod.env_hash(env))
        if cached is not None:
            return cached
        packaged = re_mod.package_local_dirs(
            env, lambda k, v: self.kv_put(k, v, overwrite=False))
        self._prepared_envs[re_mod.env_hash(env)] = packaged
        return packaged

    # ------------------------------------------------------------- lifecycle
    def _register_handlers(self):
        s = self._server
        s.register("push_task", self._handle_push_task)
        s.register("push_task_w", self._handle_push_task_w)
        s.register("push_task_batch", self._handle_push_task_batch)
        s.register("fetch_object", self._handle_fetch_object)
        s.register("fetch_object_chunk", self._handle_fetch_object_chunk)
        s.register("add_object_location", self._handle_add_object_location)
        s.register("drop_object_location", self._handle_drop_object_location)
        s.register("get_object", self._handle_get_object)
        s.register("free_objects", self._handle_free_objects)
        s.register("add_borrower", self._handle_add_borrower)
        s.register("remove_borrower", self._handle_remove_borrower)
        s.register("report_generator_item", self._handle_report_generator_item)
        s.register("kill_actor", self._handle_kill_actor)
        s.register("cancel_task", self._handle_cancel_task)
        s.register("exit", self._handle_exit)
        s.register("ping", self._handle_ping)
        s.register("profile_cpu", self._handle_profile_cpu)
        s.register("profile_memory", self._handle_profile_memory)
        s.register("profile_device", self._handle_profile_device)
        s.register("memory_report", self._handle_memory_report)
        s.register("pubsub_message", self._handle_pubsub_message)
        s.register("reconstruct_object", self._handle_reconstruct_object)

    def shutdown(self, mark_job_finished: bool = True):
        if self._shutdown:
            return
        self._shutdown = True
        if self.mode == "driver" and mark_job_finished:
            try:
                self._gcs.call("mark_job_finished", {"job_id": self.job_id}, timeout=5)
            except Exception:  # noqa: BLE001 — GCS reaps the job by driver death
                logger.debug("mark_job_finished failed on shutdown",
                             exc_info=True)
        self._lease_reaper.cancel()
        if self._task_events_wakeup is not None:
            self._task_events_wakeup.set()  # unpark the flusher to exit
        # Final event flush so short-lived drivers still show their tasks in
        # the state API / timeline (the daemon flusher thread sees
        # _shutdown and exits on its own).
        try:
            self._flush_task_events_sync(deadline_s=2.0)
        except Exception:  # noqa: BLE001 — best effort on teardown
            logger.debug("final task-event flush failed", exc_info=True)
        if self._event_sink_token is not None:
            event_log.flush(timeout=0.5)
            event_log.clear_sink(self._event_sink_token)
        if getattr(self, "_span_sink_token", None) is not None:
            _tracing.flush_spans(timeout=0.5)
            _tracing.clear_span_sink(self._span_sink_token)
        if getattr(self, "_metrics_push_token", None) is not None:
            from ray_tpu.health import push as _health_push
            _health_push.clear_push_sink(self._metrics_push_token)
        self.executor.shutdown()
        if self.plasma is not None:
            try:
                self.plasma.close()
            except Exception:  # noqa: BLE001 — store may already be gone
                logger.debug("plasma close failed on shutdown",
                             exc_info=True)
            self.plasma = None
        self._peers.close_all()
        self._gcs.close()
        if self._raylet is not None:
            self._raylet.close()
        self._server.stop()
        self._lt.stop()
        if global_state.core_worker is self:
            global_state.core_worker = None

    def _fire(self, coro):
        """Fire-and-forget a coroutine, swallowing connection errors."""

        async def _safe():
            try:
                await coro
            except Exception:  # noqa: BLE001 — fire-and-forget by contract
                logger.debug("fire-and-forget RPC failed", exc_info=True)

        self._lt.submit(_safe())

    # ---------------------------------------------------------- task context
    def enter_task_context(self, spec: TaskSpec):
        prev = getattr(_task_ctx, "spec", None)
        _task_ctx.spec = spec
        # process-wide fallback for threads with no task context (user
        # threads spawned inside a task): a task worker serves one job at
        # a time, so the last-entered job is the right attribution
        if (spec.job_id is not None and not spec.job_id.is_nil()
                and not self.is_actor_worker):
            self.job_id = spec.job_id
        return prev

    def exit_task_context(self, token):
        _task_ctx.spec = token

    def current_task_id(self) -> TaskID:
        spec = getattr(_task_ctx, "spec", None)
        return spec.task_id if spec is not None else self._root_task_id

    def current_job_id(self) -> JobID:
        """The job this code runs under: the executing task's job inside a
        task/actor, else the process's own (driver) job."""
        spec = getattr(_task_ctx, "spec", None)
        if spec is not None and spec.job_id is not None \
                and not spec.job_id.is_nil():
            return spec.job_id
        return self.job_id

    def current_spec(self) -> Optional[TaskSpec]:
        return getattr(_task_ctx, "spec", None)

    def _parent_deadline(self) -> Optional[float]:
        """Deadline inheritance: a child task submitted from inside a
        running task gets the parent's remaining budget (a child of
        doomed work is doomed work)."""
        spec = getattr(_task_ctx, "spec", None)
        return getattr(spec, "deadline_s", None) if spec is not None else None

    @staticmethod
    def _trace_ctx_for_submit() -> Optional[tuple]:
        """Trace-context inheritance (the tracing sibling of
        _parent_deadline): a child of the ambient context — the serve
        proxy's request scope, or the executing task's own context — or
        a head-sampled fresh root. None (the common case at default
        sample rate) costs one thread-local read."""
        ctx = _tracing.context_for_submission()
        return ctx.to_wire() if ctx is not None else None

    @staticmethod
    def _spec_trace_id(spec: TaskSpec) -> Optional[str]:
        return _tracing.trace_id_of(spec)

    def _expire_spec(self, spec: TaskSpec, layer: str = "owner",
                     record: bool = True) -> None:
        """Doomed-work elimination at an owner-side queue pop: resolve the
        task with a typed DeadlineExceededError instead of spending a
        lease/push on work whose caller already gave up. `record=False`
        when ANOTHER layer already emitted/counted the drop (the raylet's
        _expired_reply) and this call only resolves the caller's refs —
        double-recording would double every raylet-layer total."""
        if record:
            self._elog.emit("task.deadline_expired",
                            task_id=spec.task_id.hex(),
                            trace_id=self._spec_trace_id(spec),
                            layer=layer, function=spec.function_name)
            _backoff.count_deadline_expired(layer)
        _tracing.force_trace(self._spec_trace_id(spec),
                             f"task.deadline_expired:{layer}")
        self._store_error_for_task(spec, exc.DeadlineExceededError(
            f"deadline for task {spec.function_name} passed before "
            f"dispatch", layer=layer, deadline=spec.deadline_s))
        self._finalize_task(spec, "FAILED")

    # ------------------------------------------------------------------- KV
    def kv_get(self, key: bytes, namespace: Optional[str] = None) -> Optional[bytes]:
        cached = self._fn_kv_cache.get(key)
        if cached is not None:
            return cached
        value = self._gcs.call("kv_get", {"key": key, "namespace": namespace})
        if value is not None and key.startswith(b"fun:"):
            self._fn_kv_cache[key] = value
        return value

    def kv_put(self, key: bytes, value: bytes, overwrite: bool = True,
               namespace: Optional[str] = None) -> bool:
        return self._gcs.call(
            "kv_put",
            {"key": key, "value": value, "overwrite": overwrite, "namespace": namespace},
        )

    def kv_del(self, key: bytes, del_by_prefix: bool = False,
               namespace: Optional[str] = None) -> int:
        return self._gcs.call(
            "kv_del",
            {"key": key, "del_by_prefix": del_by_prefix, "namespace": namespace},
        )

    def kv_keys(self, prefix: bytes, namespace: Optional[str] = None) -> list:
        return self._gcs.call(
            "kv_keys", {"prefix": prefix, "namespace": namespace})

    def kv_exists(self, key: bytes, namespace: Optional[str] = None) -> bool:
        return self._gcs.call(
            "kv_exists", {"key": key, "namespace": namespace})

    def register_function(self, fn) -> str:
        data = ser.dumps_function(fn)
        fid = hashlib.sha1(data).hexdigest()
        if fid not in self._registered_fns:
            self.kv_put(b"fun:" + fid.encode(), data, overwrite=False)
            self._registered_fns.add(fid)
            if len(data) <= CONFIG.max_inline_function_bytes:
                self._fn_blobs[fid] = data
        return fid

    # ------------------------------------------------------------------- put
    def put(self, value: Any) -> ObjectRef:
        with self._put_lock:
            self._put_index += 1
            idx = self._put_index
        oid = ObjectID.for_put(self.current_task_id(), idx)
        s = ser.serialize(value)
        # Large payloads go to the node shm store so sibling processes read
        # them zero-copy (reference: Put > inline threshold lands in plasma,
        # core_worker.cc:1242).
        if (self.plasma is not None
                and s.total_bytes() > self._plasma_threshold()
                and self.plasma.put_serialized(oid, s, primary=True)):
            self.memory_store.put_serialized(
                oid, None, value=value, in_plasma=True,
                plasma_node=self.node_id.hex() if self.node_id else None)
        else:
            self.memory_store.put_serialized(oid, s, value=value)
        self.reference_counter.add_owned(oid, self.address)
        self.reference_counter.set_size(oid, s.total_bytes())
        for ref in s.contained_refs:
            pass  # nested refs stay alive via the stored value holding them
        return ObjectRef(oid, owner_address=self.address)

    # ------------------------------------------------------------------- get
    def get(self, refs: List[ObjectRef], timeout: Optional[float] = None) -> List[Any]:
        ids = [r.object_id() for r in refs]
        owners = [r.owner_address for r in refs]
        return self.get_objects_by_id(ids, owners, timeout)

    def get_objects_by_id(
        self, ids: List[ObjectID], owners: List[Optional[Address]],
        timeout: Optional[float] = None,
    ) -> List[Any]:
        deadline = None if timeout is None else time.monotonic() + timeout
        out: List[Any] = []
        for oid, owner in zip(ids, owners):
            out.append(self._get_one(oid, owner, deadline))
        return out

    def _remaining(self, deadline) -> Optional[float]:
        if deadline is None:
            return None
        rem = deadline - time.monotonic()
        if rem <= 0:
            raise exc.GetTimeoutError("get() timed out")
        return rem

    def _get_one(self, oid: ObjectID, owner: Optional[Address], deadline) -> Any:
        while True:
            entry = self.memory_store.get_entry(oid)
            if entry is not None:
                return self._materialize(oid, entry, deadline)
            if self.reference_counter.owns(oid) or (
                owner is not None and owner.rpc_address == self.address_str
            ):
                rem = self._remaining(deadline)
                entry = self.memory_store.wait_entry(oid, rem if rem is not None else None)
                if entry is None:
                    if deadline is not None:
                        raise exc.GetTimeoutError("get() timed out")
                    continue
                return self._materialize(oid, entry, deadline)
            if owner is None:
                raise exc.ObjectLostError(oid.hex())
            # Borrower fast path: the owner (or the executing worker) may be
            # on this node, in which case the payload is already in the node
            # shm store — read it zero-copy without owner RPC.
            if self.plasma is not None:
                s = self.plasma.get_serialized(oid, restore=False)
                if s is not None:
                    value, _ = ser.deserialize(s)
                    self.memory_store.put_serialized(
                        oid, None, value=value, in_plasma=True)
                    return value
            # Borrower path: long-poll the owner. The owner may inline the
            # full payload in the reply (e.g. a multi-GiB array whose shm
            # write fell back to the memory store), so the TRANSPORT timeout
            # must be generous — the not-ready wait is still bounded by the
            # short server-side long-poll slice, and a dead owner surfaces
            # as ConnectionLost, not a timeout.
            rem = self._remaining(deadline)
            slice_t = 2.0 if rem is None else min(2.0, rem)
            client = self._peers.get(owner.rpc_address)
            try:
                reply = client.call(
                    "get_object",
                    {"object_id": oid, "want_value": True, "timeout": slice_t},
                    timeout=slice_t + CONFIG.rpc_call_timeout_s,
                )
            except ConnectionLost:
                raise exc.OwnerDiedError(oid.hex())
            status = reply["status"]
            if status == "ready":
                if "data" in reply:
                    value, _ = ser.deserialize(reply["data"])
                    if reply.get("is_exception"):
                        self._raise_stored_error(value)
                    return value
                location = reply["location"]
                try:
                    data = self._fetch_from_location(
                        oid, location, owner, deadline,
                        replicas=reply.get("replicas"))
                except _RetryGet:
                    continue  # owner is reconstructing; re-resolve
                value, _ = ser.deserialize(data)
                return value
            if status == "freed":
                raise exc.ObjectFreedError(oid.hex())
            if status == "not_owner":
                raise exc.OwnerDiedError(oid.hex())
            # pending: loop (deadline enforced via _remaining)

    def _materialize(self, oid: ObjectID, entry: StoreEntry, deadline) -> Any:
        if entry.freed:
            raise exc.ObjectFreedError(oid.hex())
        if entry.value is not _SENTINEL:
            if entry.is_exception:
                self._raise_stored_error(entry.value)
            return entry.value
        if entry.serialized is None and entry.in_plasma:
            # Same-node shm read (zero-copy; restores from disk if spilled).
            local = (self.plasma is not None and
                     (entry.plasma_node is None or self.node_id is None or
                      entry.plasma_node == self.node_id.hex()))
            if local:
                s = self.plasma.get_serialized(oid)
                if s is not None:
                    value, _ = ser.deserialize(s)
                    self.memory_store.cache_value(oid, value)
                    if entry.is_exception:
                        self._raise_stored_error(value)
                    return value
            # Remote (or lost locally): fall through to the location fetch.
            if entry.location is None:
                raise exc.ObjectLostError(oid.hex())
        if entry.location is not None and entry.serialized is None:
            locs = self.reference_counter.get_all_locations(oid)
            data = self._fetch_from_location(
                oid, entry.location, self.address, deadline,
                replicas=[l for l in locs if l != entry.location])
            value, _ = ser.deserialize(data)
            if entry.is_exception:
                self._raise_stored_error(value)
            return value
        value, _ = ser.deserialize(entry.serialized)
        self.memory_store.cache_value(oid, value)
        if entry.is_exception:
            self._raise_stored_error(value)
        return value

    def _raise_stored_error(self, err: Any):
        if isinstance(err, exc.RayTaskError):
            raise err.as_instanceof_cause()
        if isinstance(err, BaseException):
            raise err
        raise exc.RaySystemError(f"corrupt error object: {err!r}")

    def _fetch_from_location(
        self, oid: ObjectID, location: str, owner: Optional[Address], deadline,
        replicas: Optional[list] = None,
    ) -> ser.SerializedObject:
        attempts = 0
        while True:
            attempts += 1
            client = self._peers.get(location)
            try:
                # max_inline flips the source to chunked mode for anything
                # larger than one chunk: the monolithic reply both buffers
                # the whole object in one message and serializes all
                # readers through the primary copy (VERDICT r2 missing #1).
                reply = client.call(
                    "fetch_object",
                    {"object_id": oid,
                     "max_inline": CONFIG.fetch_chunk_size_bytes},
                    timeout=60)
                if reply.get("status") == "ok":
                    return reply["data"]
                if reply.get("status") == "chunked":
                    sources = [location] + [
                        r for r in (replicas or [])
                        if r != location and r != self.address_str]
                    data = self._chunked_fetch(oid, reply["size"], sources,
                                               deadline, owner)
                    if data is not None:
                        return data
            except ConnectionLost:
                self._peers.invalidate(location)
            # Primary copy lost. Before lineage reconstruction, ask the
            # LOCAL raylet to restore from spill: with a remote spill
            # backend (file:// mount, s3://), the dead node may have
            # spilled this object to shared storage and registered the
            # URI cluster-wide — a storage read beats re-executing the
            # task tree (the preemptible-node recovery path).
            if self.plasma is not None:
                s = self.plasma.get_serialized(oid, restore=True)
                if s is not None:
                    return s
            # Try lineage reconstruction via the owner.
            if owner is not None and owner.rpc_address == self.address_str:
                if not self._try_reconstruct(oid):
                    raise exc.ObjectLostError(oid.hex())
                entry = self.memory_store.wait_entry(oid, 60)
                if entry is None:
                    raise exc.ObjectLostError(oid.hex())
                if entry.is_exception and (entry.value is not _SENTINEL
                                           or entry.serialized is not None):
                    # The re-execution itself failed (e.g. retries
                    # exhausted against a dying node): raise the stored
                    # error — returning its serialized form here would
                    # hand the caller an exception VALUE, unchecked
                    # because the caller's entry snapshot predates it.
                    # (Stored errors are always inline; anything else
                    # falls through to the location re-resolve below.)
                    value = (entry.value if entry.value is not _SENTINEL
                             else ser.deserialize(entry.serialized)[0])
                    self._raise_stored_error(value)
                if entry.location is not None and entry.serialized is None:
                    location = entry.location
                    continue
                return entry.serialized
            elif owner is not None:
                try:
                    ok = self._peers.get(owner.rpc_address).call(
                        "reconstruct_object", {"object_id": oid}, timeout=60
                    )
                except ConnectionLost:
                    raise exc.OwnerDiedError(oid.hex())
                if not ok:
                    raise exc.ObjectLostError(oid.hex())
                time.sleep(CONFIG.fetch_retry_interval_ms / 1000.0)
                raise _RetryGet()  # caller loop re-resolves via owner
            if attempts > 3:
                raise exc.ObjectLostError(oid.hex())

    # ------------------------------------------------ chunked object transfer
    def _chunked_fetch(self, oid: ObjectID, size: int, sources: list,
                       deadline, owner: Optional[Address] = None
                       ) -> Optional[ser.SerializedObject]:
        """Pull a large object as pipelined chunks, striped across every
        known copy holder, landing directly in the node shm store when
        possible (reference: pull_manager.h:52 chunked pulls + admission,
        push_manager.h:30; the broadcast tree grows organically — each
        completed receiver registers itself as a source with the owner).
        Concurrent fetches of the same object in this process coalesce
        onto one transfer. Returns None when every source failed (caller
        falls back to reconstruction)."""
        import concurrent.futures as cf

        while True:
            with self._fetch_dedup_lock:
                fut = self._inflight_fetches.get(oid)
                if fut is None:
                    fut = cf.Future()
                    self._inflight_fetches[oid] = fut
                    leader = True
                else:
                    leader = False
            if leader:
                break
            try:
                return fut.result(
                    timeout=None if deadline is None
                    else max(0.1, deadline - time.monotonic()))
            except exc.GetTimeoutError:
                # the LEADER's deadline expired, not necessarily ours: a
                # follower with time left takes over as the new leader
                # instead of inheriting a timeout it never asked for.
                # (This clause must precede TimeoutError — GetTimeoutError
                # subclasses it.)
                if (deadline is not None
                        and time.monotonic() >= deadline):
                    raise
            except TimeoutError:
                raise exc.GetTimeoutError("get() timed out")
        try:
            result = self._lt.run_coro(
                self._chunked_fetch_async(oid, size, sources, deadline,
                                          owner))
            fut.set_result(result)
            return result
        except BaseException as e:
            fut.set_exception(e)
            raise
        finally:
            with self._fetch_dedup_lock:
                self._inflight_fetches.pop(oid, None)

    async def _chunked_fetch_async(self, oid: ObjectID, size: int,
                                   sources: list, deadline,
                                   owner: Optional[Address] = None
                                   ) -> Optional[ser.SerializedObject]:
        chunk = CONFIG.fetch_chunk_size_bytes
        n_chunks = max(1, -(-size // chunk))
        view = None
        if self.plasma is not None:
            view = await asyncio.to_thread(
                self.plasma.create_for_receive, oid, size)
        buf = bytearray(size) if view is None else None
        if self._fetch_sem is None:
            # admission control: bound total in-flight fetch bytes across
            # ALL concurrent fetches in this process (chunk-granular)
            self._fetch_sem = asyncio.Semaphore(
                max(1, CONFIG.fetch_max_inflight_bytes // chunk))
        done = [False] * n_chunks
        dead_sources: set = set()

        async def pull_from(src: str, pending: deque):
            client = self._peers.get(src)
            while pending:
                if deadline is not None and time.monotonic() > deadline:
                    return
                i = pending.popleft()
                off = i * chunk
                ln = min(chunk, size - off)
                await self._fetch_sem.acquire()
                try:
                    r = await client.call_async(
                        "fetch_object_chunk",
                        {"object_id": oid, "off": off, "len": ln},
                        timeout=60)
                    if r.get("status") != "ok":
                        raise ConnectionLost("chunk unavailable")
                    data = r["data"]
                    if view is not None:
                        view[off:off + ln] = data
                    else:
                        buf[off:off + ln] = data
                    done[i] = True
                except (ConnectionLost, OSError, asyncio.TimeoutError):
                    # hand the chunk back; this source is out for THIS
                    # fetch, and a stale replica gets dropped at the owner
                    # so later fetchers don't re-try a dead address
                    pending.append(i)
                    dead_sources.add(src)
                    if src != sources[0]:
                        self._drop_replica_at_owner(oid, src, owner)
                    return
                finally:
                    self._fetch_sem.release()

        # Rounds: one slow/dead replica must NOT fail the fetch while a
        # healthy source (usually the primary) still holds the object —
        # re-spread the handed-back chunks over the surviving sources.
        # The last round re-admits the primary even after a transient
        # timeout marked it dead: reconstruction is the WRONG response to
        # a slow-but-alive primary.
        for rnd in range(3):
            # bounded by the object's own chunk count
            remaining = deque(  # raylint: disable=unbounded-queue
                i for i in range(n_chunks) if not done[i])
            if not remaining:
                break
            if deadline is not None and time.monotonic() > deadline:
                break
            live = [s for s in sources if s not in dead_sources]
            if not live:
                if rnd == 2 or not sources:
                    break
                dead_sources.discard(sources[0])
                live = [sources[0]]
            await asyncio.gather(*(
                asyncio.ensure_future(pull_from(src, remaining))
                for src in live
                for _ in range(max(1, CONFIG.fetch_pipeline_depth))))
        if not all(done):
            if view is not None:
                del view
                await asyncio.to_thread(self.plasma.abort_receive, oid)
            if deadline is not None and time.monotonic() > deadline:
                # the caller asked for a bounded get(): report the timeout,
                # never fall through to reconstruction of a healthy object
                raise exc.GetTimeoutError("get() timed out")
            return None
        if view is not None:
            del view  # drop the writable mapping before sealing
            await asyncio.to_thread(self.plasma.seal_received, oid)
            s = await asyncio.to_thread(
                self.plasma.get_serialized, oid, False)
            if s is None:  # sealed copy already evicted (store thrashing)
                return None
            # future local gets (this worker AND same-node siblings via the
            # plasma fast path) now read shm instead of re-fetching
            self.memory_store.put_serialized(
                oid, None, in_plasma=True,
                plasma_node=self.node_id.hex() if self.node_id else None)
            self._register_as_copy_holder(oid, owner)
        else:
            # heap fallback (no shm store): decode over the assembly buffer
            # directly — bytes(buf) would re-copy the whole object
            s = ser.SerializedObject.from_bytes(memoryview(buf))
        return s

    def _drop_replica_at_owner(self, oid: ObjectID, replica: str,
                               owner: Optional[Address]):
        """A replica failed to serve chunks: have the owner forget it so
        later fetchers stop striping to a dead/evicted copy."""
        try:
            if owner is None or owner.rpc_address == self.address_str:
                self.reference_counter.drop_location(oid, replica)
            else:
                self._lt.submit(
                    self._peers.get(owner.rpc_address).send_async(
                        "drop_object_location",
                        {"object_id": oid, "location": replica}))
        except Exception:  # noqa: BLE001 — healing is best-effort
            logger.debug("replica-healing notification failed",
                         exc_info=True)

    def _register_as_copy_holder(self, oid: ObjectID,
                                 owner: Optional[Address] = None):
        """Tell the owner we hold a durable full copy: later fetchers then
        stripe chunks across us too (pipelined broadcast fan-out)."""
        owner_addr = owner or self.reference_counter.get_owner_address(oid)
        if owner_addr is None or owner_addr.rpc_address == self.address_str:
            self.reference_counter.add_location(oid, self.address_str)
            return
        try:
            self._lt.submit(self._peers.get(owner_addr.rpc_address).send_async(
                "add_object_location",
                {"object_id": oid, "location": self.address_str}))
        except Exception:  # noqa: BLE001 — registration is an optimization
            logger.debug("copy-holder registration failed", exc_info=True)

    def _try_reconstruct(self, oid: ObjectID) -> bool:
        """Owner-side lineage reconstruction (object_recovery_manager.h:41)."""
        if not CONFIG.enable_lineage_reconstruction:
            return False
        spec = self.reference_counter.get_lineage(oid)
        if spec is None:
            return False
        tid = spec.task_id
        with self._pending_lock:
            # atomic check-then-insert: concurrent get()s of a lost
            # object (user thread + as_future resolver threads) race to
            # reconstruct; exactly one may insert and re-execute
            if tid in self._pending_tasks:
                return True  # already re-executing
            spec.attempt_number += 1
            self._pending_tasks[tid] = _PendingTask(
                spec=spec, retries_left=0,
                arg_ids=[a.object_id for a in spec.args if not a.is_inline]
            )
        logger.info("reconstructing %s by re-executing %s", oid.hex()[:12], spec.function_name)
        self._elog.emit("object.reconstruct", object_id=oid.hex(),
                        task_id=tid.hex(), function=spec.function_name)
        self.memory_store.delete([o for o in spec.return_ids()])
        self._normal_submit(spec)
        return True

    # ------------------------------------------------------------------ wait
    def wait(
        self,
        refs: List[ObjectRef],
        num_returns: int = 1,
        timeout: Optional[float] = None,
        fetch_local: bool = True,
    ) -> Tuple[List[ObjectRef], List[ObjectRef]]:
        deadline = None if timeout is None else time.monotonic() + timeout
        pending = list(refs)
        ready: List[ObjectRef] = []
        while True:
            still = []
            for ref in pending:
                if self._is_ready(ref):
                    ready.append(ref)
                else:
                    still.append(ref)
            pending = still
            if len(ready) >= num_returns or not pending:
                break
            if deadline is not None and time.monotonic() >= deadline:
                break
            time.sleep(0.005)
        return ready[:num_returns], ready[num_returns:] + pending

    def _is_ready(self, ref: ObjectRef) -> bool:
        oid = ref.object_id()
        if self.memory_store.contains(oid):
            return True
        if self.reference_counter.owns(oid):
            return False
        owner = ref.owner_address
        if owner is None or owner.rpc_address == self.address_str:
            return False
        try:
            reply = self._peers.get(owner.rpc_address).call(
                "get_object",
                {"object_id": oid, "want_value": False, "timeout": 0},
                timeout=10,
            )
        except ConnectionLost:
            raise exc.OwnerDiedError(oid.hex())
        return reply["status"] in ("ready", "freed")

    # ----------------------------------------------------------- submit task
    def _build_args(self, args, kwargs) -> Tuple[List[TaskArg], Dict[str, TaskArg], List[ObjectID]]:
        arg_ids: List[ObjectID] = []

        def build(value) -> TaskArg:
            if isinstance(value, ObjectRef):
                arg_ids.append(value.object_id())
                self.reference_counter.add_submitted_task_ref(value.object_id())
                return TaskArg(
                    is_inline=False,
                    object_id=value.object_id(),
                    owner_address=value.owner_address or self.address,
                )
            s = ser.serialize(value)
            if s.total_bytes() > CONFIG.max_direct_call_object_size:
                ref = self.put(value)
                arg_ids.append(ref.object_id())
                self.reference_counter.add_submitted_task_ref(ref.object_id())
                return TaskArg(
                    is_inline=False, object_id=ref.object_id(), owner_address=self.address
                )
            nested = [r.object_id() for r in s.contained_refs]
            for r in s.contained_refs:
                arg_ids.append(r.object_id())
                self.reference_counter.add_submitted_task_ref(r.object_id())
            return TaskArg(is_inline=True, data=s, nested_ids=nested)

        return (
            [build(a) for a in args],
            {k: build(v) for k, v in (kwargs or {}).items()},
            arg_ids,
        )

    def submit_task(
        self,
        fn,
        args: tuple,
        kwargs: dict,
        *,
        num_returns=1,
        resources=None,
        max_retries: int = 3,
        retry_exceptions: bool = False,
        scheduling_strategy: Optional[SchedulingStrategySpec] = None,
        name: str = "",
        function_id: Optional[str] = None,
        runtime_env: Optional[dict] = None,
        runtime_env_prepared: bool = False,
        max_calls: int = 0,
        deadline_s: Optional[float] = None,
    ):
        t_submit = time.monotonic()
        fid = function_id or self.register_function(fn)
        if not runtime_env_prepared:
            runtime_env = self.prepare_runtime_env(runtime_env)
        job_id = self.current_job_id()
        task_id = TaskID.for_normal_task(job_id)
        streaming = num_returns == "streaming" or num_returns == -1
        arg_specs, kwarg_specs, arg_ids = self._build_args(args, kwargs)
        spec = TaskSpec(
            task_id=task_id,
            job_id=job_id,
            task_type=TaskType.NORMAL_TASK,
            function_id=fid,
            function_name=name or getattr(fn, "__name__", "task"),
            args=arg_specs,
            num_returns=-1 if streaming else num_returns,
            resources=resources or {"CPU": CONFIG.default_task_num_cpus},
            owner_address=self.address,
            trace_parent=self.current_task_id().hex(),
            trace_ctx=self._trace_ctx_for_submit(),
            max_retries=max_retries,
            retry_exceptions=retry_exceptions,
            max_calls=max_calls,
            scheduling_strategy=scheduling_strategy or SchedulingStrategySpec(),
            runtime_env=runtime_env,
            deadline_s=_deadlines.effective_deadline(
                deadline_s, self._parent_deadline()),
        )
        spec.kwarg_specs = kwarg_specs
        with self._pending_lock:
            self._pending_tasks[task_id] = _PendingTask(
                spec=spec, retries_left=max_retries, arg_ids=arg_ids,
                t_submit=t_submit,
            )
        lineage = spec if CONFIG.enable_lineage_reconstruction else None
        self._record_task_event(spec, "PENDING")
        if streaming:
            # Item oids are registered as owned when each item is reported
            # (_handle_report_generator_item). Creating return refs here
            # would alias item 0's oid and free it from a discarded ref on a
            # GC-dependent schedule.
            self._generators[task_id] = _GeneratorState()
            self._normal_submit(spec)
            return ObjectRefGenerator(task_id)
        return_refs = []
        for oid in spec.return_ids():
            self.reference_counter.add_owned(oid, self.address, lineage_task=lineage)
            return_refs.append(ObjectRef(oid, owner_address=self.address))
        self._normal_submit(spec)
        return return_refs

    def _normal_submit(self, spec: TaskSpec):
        self._enqueue_submit(False, spec)

    def _enqueue_submit(self, is_actor: bool, spec: TaskSpec):
        """Coalesced cross-thread submission: burst submissions from the
        user thread fold into ONE loop wakeup + one drain pass (a
        run_coroutine_threadsafe per task costs a self-pipe write, a Task
        object, and a _pump each — the dominant submit-side overhead at
        >5k tasks/s)."""
        with self._submit_lock:
            self._submit_buf.append((is_actor, spec))
            if self._submit_scheduled:
                return
            self._submit_scheduled = True
        self._lt.loop.call_soon_threadsafe(self._drain_submits)

    def _drain_submits(self):
        with self._submit_lock:
            items = list(self._submit_buf)
            self._submit_buf.clear()
            self._submit_scheduled = False
        task_keys = set()
        actor_groups: Dict[ActorID, List[TaskSpec]] = {}
        now = time.monotonic()
        for is_actor, spec in items:
            if is_actor:
                pending = self._pending_tasks.get(spec.task_id)
                if pending is not None:
                    pending.t_queued = now
                actor_groups.setdefault(spec.actor_id, []).append(spec)
            else:
                key = self._route_or_park(spec)
                if key is not None:
                    task_keys.add(key)
        for key in task_keys:
            asyncio.ensure_future(self._pump(key))
        for actor_id, specs in actor_groups.items():
            asyncio.ensure_future(self._actor_submit_batch(actor_id, specs))

    def _route_or_park(self, spec: TaskSpec):
        """Dependency resolution: dispatching a task whose owned args are
        still pending would make the worker long-poll us for them while
        holding its CPU — park until every owned by-ref arg has an entry
        (value, error, or plasma location). Borrowed args (owner
        elsewhere) dispatch immediately: their readiness is unobservable
        locally and the producing side is another owner's pool.
        Returns the scheduling key when queued, None when parked."""
        missing = {
            a.object_id
            for a in (list(spec.args)
                      + list(getattr(spec, "kwarg_specs", {}).values()))
            if not a.is_inline
            and self.reference_counter.owns(a.object_id)
            and not self.memory_store.contains(a.object_id)
        }
        if missing:
            wait = _DepWait(spec=spec, missing=missing)
            for oid in missing:
                self._dep_waiters.setdefault(oid, []).append(wait)
            return None
        pending = self._pending_tasks.get(spec.task_id)
        if pending is not None:
            # dependency-wait time lands in the 'submit' stage by design
            pending.t_queued = time.monotonic()
        key = spec.scheduling_key()
        st = self._key_states.setdefault(key, _KeyState())
        st.pending.append(spec)
        return key

    async def _enqueue_ready(self, spec: TaskSpec):
        pending = self._pending_tasks.get(spec.task_id)
        if pending is not None:
            pending.t_queued = time.monotonic()
        key = spec.scheduling_key()
        st = self._key_states.setdefault(key, _KeyState())
        st.pending.append(spec)
        await self._pump(key)

    def _release_deps(self, oid: ObjectID):
        """An owned object materialized: unpark tasks that waited on it."""
        waiters = self._dep_waiters.pop(oid, None)
        if not waiters:
            return
        for w in waiters:
            w.missing.discard(oid)
            if not w.missing:
                self._lt.submit(self._enqueue_ready(w.spec))

    def _cancel_parked(self, task_id) -> bool:
        """Remove a dep-parked spec (cancel path). True if it was parked."""
        found = False
        for oid, waiters in list(self._dep_waiters.items()):
            kept = [w for w in waiters if w.spec.task_id != task_id]
            if len(kept) != len(waiters):
                found = True
                if kept:
                    self._dep_waiters[oid] = kept
                else:
                    del self._dep_waiters[oid]
        return found

    async def _pump(self, key):
        st = self._key_states.get(key)
        if st is None:
            return
        # Assign pending specs to idle leases — BATCHED: one push RPC can
        # carry many specs (the worker executes them serially), amortizing
        # the per-RPC round trip that otherwise caps async submission at
        # ~1/RTT per lease (VERDICT r1: async was SLOWER than sync).
        # Batching trades parallelism for overhead, so it is LATENCY-GATED:
        # only keys whose observed task time (EMA from completed pushes) is
        # under the threshold batch at all — batching long tasks onto one
        # worker would serialize them AND free their CPUs for work that
        # should have queued behind them. Unmeasured keys ship 1:1.
        idle = [lease for lease in st.leases.values() if not lease.busy]
        short = (st.avg_task_s is not None
                 and st.avg_task_s * 1e3 < CONFIG.task_batch_latency_ms)
        cap_batch = CONFIG.max_tasks_per_push if short else 1
        for i, lease in enumerate(idle):
            if not st.pending:
                break
            fair = -(-len(st.pending) // (len(idle) - i))  # ceil split
            n = min(cap_batch, fair, len(st.pending))
            # A spec with by-REFERENCE args never joins a batch: its args
            # may be returns of tasks earlier in the same batch, whose
            # values reach this owner only in the batch's single reply —
            # the executing worker would long-poll us for them and
            # deadlock the batch (chained `f.remote(f.remote(...))`).
            specs = []
            while len(specs) < n and st.pending:
                spec = st.pending[0]
                if _deadlines.expired(spec.deadline_s):
                    # queue-pop doomed-work elimination: the caller's
                    # budget ran out while this spec queued — resolve it
                    # typed instead of spending the lease on it
                    st.pending.popleft()
                    self._expire_spec(spec)
                    continue
                if not self._batchable(spec):
                    if not specs:
                        specs.append(st.pending.popleft())  # ship alone
                    break
                specs.append(st.pending.popleft())
            if not specs:
                continue
            lease.busy = True
            asyncio.ensure_future(self._push(key, lease, specs))
        # Request more leases if there is unassigned work.
        want = len(st.pending)
        cap = CONFIG.max_pending_lease_requests_per_scheduling_key
        while st.inflight_lease_requests < min(want, cap):
            st.inflight_lease_requests += 1
            spec = st.pending[0]
            asyncio.ensure_future(self._request_lease(key, spec))
            want -= 1

    async def _resolve_route(self, spec: TaskSpec) -> Optional[str]:
        strat = spec.scheduling_strategy
        if strat.kind == "PLACEMENT_GROUP":
            info = await self._get_pg_info(strat.placement_group_id)
            if info is None:
                return None
            locations = info.bundle_locations
            if strat.bundle_index >= 0:
                node = locations.get(strat.bundle_index)
            else:
                nodes = list(locations.values())
                node = nodes[spec.task_id.binary()[0] % len(nodes)] if nodes else None
            if node is None:
                return None
            return await self._node_raylet_addr(node)
        if strat.kind == "NODE_AFFINITY" and strat.node_id is not None:
            addr = await self._node_raylet_addr(strat.node_id)
            if addr is not None:
                return addr
            if not strat.soft:
                return None
        return self.raylet_address

    async def _get_pg_info(self, pg_id: PlacementGroupID):
        info = self._pg_cache.get(pg_id)
        if info is not None and len(info.bundle_locations) == len(info.spec.bundles):
            return info
        reply = await self._gcs.call_async(
            "wait_placement_group_ready",
            {"placement_group_id": pg_id, "timeout": 60},
        )
        if reply.get("status") != "ready":
            return None
        info = reply["info"]
        # raylint: disable=cross-domain-mutation — GIL-atomic dict ops on
        # a read-through cache: remove_placement_group's pop vs this
        # insert worst-cases a stale entry, which the bundle_locations
        # completeness check above re-validates on every hit
        self._pg_cache[pg_id] = info
        return info

    async def _node_raylet_addr(self, node_id: NodeID) -> Optional[str]:
        addr = self._node_addr_cache.get(node_id)
        if addr is not None:
            return addr
        nodes = await self._gcs.call_async("get_all_node_info", {})
        for n in nodes:
            if n.alive:
                self._node_addr_cache[n.node_id] = n.raylet_address
        return self._node_addr_cache.get(node_id)

    async def _request_lease(self, key, sample_spec: TaskSpec):
        st = self._key_states[key]
        try:
            await self._request_lease_inner(key, sample_spec, st)
        except ConnectionLost:
            if not self._shutdown:
                self._fail_queued(key, exc.RaySystemError(
                    "lost connection to the local raylet"))
        finally:
            st.inflight_lease_requests -= 1

    async def _request_lease_inner(self, key, sample_spec: TaskSpec, st):
        target = await self._resolve_route(sample_spec)
        spillback = 0
        warned = 0.0
        refused = blips = rejects = 0
        # retry delays come from the shared policy module (ISSUE 9): the
        # constants match the old hand-rolled sleeps at attempt 1 and grow
        # from there instead of hammering a struggling raylet at a fixed
        # cadence.
        refused_policy = _backoff.BackoffPolicy(
            base_s=0.2, multiplier=1.5, max_s=2.0, jitter=0.2)
        blip_policy = _backoff.BackoffPolicy(
            base_s=0.1, multiplier=2.0, max_s=0.5)
        reject_policy = _backoff.BackoffPolicy(
            base_s=0.2, multiplier=1.2, max_s=1.0, jitter=0.1)
        while not self._shutdown:
            while st.pending and _deadlines.expired(
                    st.pending[0].deadline_s):
                # queue-pop doomed-work elimination on the lease path: no
                # point re-asking for work whose caller already gave up
                self._expire_spec(st.pending.popleft())
            if not st.pending:
                return
            if target is None:
                self._fail_queued(key, exc.RaySystemError(
                    f"no feasible node for task {sample_spec.function_name} "
                    f"(strategy={sample_spec.scheduling_strategy.kind})"))
                return
            client = self._peers.get(target)
            try:
                reply = await client.call_async(
                    "request_worker_lease",
                    {"spec": st.pending[0] if st.pending else sample_spec,
                     "spillback_count": spillback},
                    timeout=None,
                )
                refused = blips = 0
            except ConnectionLost as e:
                # Same-target retries apply only to the LOCAL raylet,
                # where the alternative below is failing every queued
                # task; a dead REMOTE target already has a free, instant
                # fallback (re-route through the local raylet). The two
                # budgets are SEPARATE counters: refused retries during a
                # raylet restart must not consume the reset-blip budget
                # needed the moment it comes back up.
                if target == self.raylet_address:
                    if not e.maybe_delivered and refused < 25:
                        # The request provably never reached the raylet
                        # (connect refused — e.g. it is restarting, or a
                        # transient partition healed): retry after a beat
                        # instead of escalating straight to "local raylet
                        # lost". Bounded: a persistently refusing raylet
                        # still escalates below after ~5s.
                        refused += 1
                        self._peers.invalidate(target)
                        await asyncio.sleep(refused_policy.delay(refused))
                        continue
                    if e.maybe_delivered and blips < 3:
                        # Connection reset with the request possibly in
                        # flight. Leases are safe to re-ask (an orphaned
                        # grant is reclaimed by the worker idle timeout),
                        # and a reset on a healthy raylet (GCS restart
                        # ripples, chaos disconnect) must not fail every
                        # queued task; a DEAD raylet turns into connect-
                        # refused on the retry and escalates above.
                        blips += 1
                        self._peers.invalidate(target)
                        await asyncio.sleep(blip_policy.delay(blips))
                        continue
                if target == self.raylet_address:
                    new_local = await self._refresh_local_raylet()
                    if new_local is None or new_local == target:
                        raise
                    target = new_local
                else:
                    target = self.raylet_address
                spillback = 0
                continue
            if "retry_at" in reply:
                target = reply["retry_at"]
                spillback = 1
                continue
            if reply.get("deadline_expired"):
                # the raylet dropped the spec at ITS queue pop: resolve the
                # matching queued spec typed (it may no longer be the head)
                expired_hex = reply.get("task_id")
                for spec in list(st.pending):
                    if spec.task_id.hex() == expired_hex:
                        st.pending.remove(spec)
                        # the raylet already emitted + counted this drop
                        self._expire_spec(spec, layer="raylet",
                                          record=False)
                        break
                continue
            if reply.get("retry_later"):
                # typed pushback from the bounded raylet lease queue: pace
                # resubmission (AIMD — delay doubles per pushback, shrinks
                # per grant) instead of hammering a full queue at a fixed
                # cadence. The task stays queued owner-side; its deadline
                # (checked at the top of this loop) bounds total waiting.
                delay = st.pacer.on_pushback(reply.get("retry_after_s"))
                now = time.monotonic()
                if now - warned > 10:
                    warned = now
                    logger.warning(
                        "lease queue pushback for %s (retry in %.2fs): %s",
                        sample_spec.function_name, delay,
                        reply.get("reason"))
                await asyncio.sleep(delay)
                target = await self._resolve_route(sample_spec)
                spillback = 0
                continue
            if reply.get("rejected"):
                if reply.get("runtime_env_error"):
                    # permanent env misconfiguration — fail, don't retry
                    self._fail_queued(key, exc.RuntimeEnvSetupError(
                        reply["runtime_env_error"]))
                    return
                rejects += 1
                now = time.monotonic()
                if now - warned > 10:
                    warned = now
                    logger.warning(
                        "lease request for %s rejected: %s (retrying)",
                        sample_spec.function_name, reply.get("reason"),
                    )
                await asyncio.sleep(reject_policy.delay(rejects))
                target = await self._resolve_route(sample_spec)
                spillback = 0
                continue
            rejects = 0
            st.pacer.on_success()
            addr: Address = reply["worker_address"]
            st.leases[addr.rpc_address] = _Lease(address=addr, busy=False,
                                                idle_since=time.monotonic())
            self._poke_reaper()
            await self._pump(key)
            return

    def _fail_queued(self, key, error: Exception):
        st = self._key_states.get(key)
        if st is None:
            return
        while st.pending:
            spec = st.pending.popleft()
            self._store_error_for_task(spec, error)

    @staticmethod
    def _batchable(spec: TaskSpec) -> bool:
        """Inline-args-only specs may share a batched push (see _pump)."""
        if not all(a.is_inline for a in spec.args):
            return False
        kwargs = getattr(spec, "kwarg_specs", None) or {}
        return all(a.is_inline for a in kwargs.values())

    async def _push(self, key, lease: _Lease, specs: List[TaskSpec]):
        st = self._key_states[key]
        now = time.monotonic()
        for spec in specs:
            pending = self._pending_tasks.get(spec.task_id)
            if pending is not None:
                pending.pushed_to = lease.address.rpc_address
                pending.t_pushed = now
            self._record_task_event(spec, "RUNNING")
        client = self._peers.get(lease.address.rpc_address)
        push_started = time.monotonic()
        try:
            # wire codec (spec_to_wire): ~3us per spec to encode vs ~35us
            # to pickle the dataclass graph — the push frame is THE
            # per-task hot message (SURVEY §3.2 ≲100us/task bar)
            wire = await client.call_async(
                "push_task_w", [spec_to_wire(s) for s in specs],
                timeout=None)
            replies = [reply_from_wire(t) for t in wire]
        except ConnectionLost as e:
            st.leases.pop(lease.address.rpc_address, None)
            self._peers.invalidate(lease.address.rpc_address)
            if not e.maybe_delivered:
                # The push never reached the worker (connect refused —
                # cached lease to an already-dead process, e.g.
                # reconstruction right after a node death): nothing
                # executed, so requeue for a fresh lease WITHOUT
                # consuming at-most-once retry budget. Bounded: a target
                # that refuses connections persistently must still
                # terminate via the normal failure path, not spin.
                for spec in reversed(specs):
                    pending = self._pending_tasks.get(spec.task_id)
                    if pending is None:
                        continue
                    pending.undelivered_failures += 1
                    if pending.undelivered_failures > 20:
                        self._on_worker_failure(spec)
                        continue
                    pending.pushed_to = None
                    st.pending.appendleft(spec)
            else:
                for spec in specs:
                    self._on_worker_failure(spec)
            await self._pump(key)
            return
        # Per-task latency EMA for the batching gate. Prefer the WORKER's
        # own execution timings (exec_s in each reply): an RTT-inclusive
        # sample would keep remote owners above the threshold forever —
        # exactly the regime batching exists to amortize. Fall back to
        # round-trip/batch when no timing came back.
        exec_samples = [r["exec_s"] for r in replies if "exec_s" in r]
        if exec_samples:
            sample = sum(exec_samples) / len(exec_samples)
        else:
            ran = max(1, sum(1 for r in replies if not r.get("not_run")))
            sample = (time.monotonic() - push_started) / ran
        st.avg_task_s = (sample if st.avg_task_s is None
                         else 0.7 * st.avg_task_s + 0.3 * sample)
        retiring = False
        requeue: List[TaskSpec] = []
        for spec, reply in zip(specs, replies):
            if reply.get("not_run"):
                # worker retired mid-batch before reaching this spec: it
                # never executed — put it back at the FRONT of the queue
                requeue.append(spec)
                continue
            self._on_task_reply(spec, reply)
            retiring = retiring or bool(reply.get("worker_retiring"))
        for spec in reversed(requeue):
            pending = self._pending_tasks.get(spec.task_id)
            if pending is not None:
                pending.pushed_to = None
            st.pending.appendleft(spec)
        if retiring:
            # max_calls recycling: the worker exits right after this reply —
            # never reuse the lease, and don't hand it back as "idle"
            st.leases.pop(lease.address.rpc_address, None)
            self._peers.invalidate(lease.address.rpc_address)
            if st.pending:
                await self._pump(key)
            return
        lease.busy = False
        lease.idle_since = time.monotonic()
        if st.pending:
            await self._pump(key)

    def _poke_reaper(self) -> None:
        """Wake the lease reaper (new lease / queued actor call). Safe from
        any thread; no-op before the loop starts."""
        ev = self._reaper_wakeup
        if ev is not None and not ev.is_set():
            self._lt.loop.call_soon_threadsafe(ev.set)

    async def _lease_reaper_loop(self):
        timeout = CONFIG.worker_lease_idle_timeout_ms / 1000.0
        self._reaper_wakeup = ev = asyncio.Event()
        last_actor_sweep = 0.0
        while True:
            if (not any(st.leases for st in self._key_states.values())
                    and not any(
                        rec.queue and rec.state != "DEAD"
                        for rec in self._actors.values())):
                # Nothing to reap or sweep: park until a lease is taken or
                # an actor call queues behind a non-ALIVE actor.
                await ev.wait()
            ev.clear()
            await asyncio.sleep(timeout / 2)
            now = time.monotonic()
            for key, st in list(self._key_states.items()):
                for addr, lease in list(st.leases.items()):
                    if not lease.busy and now - lease.idle_since > timeout:
                        st.leases.pop(addr, None)
                        asyncio.ensure_future(self._return_lease(lease))
            if now - last_actor_sweep > 5.0:
                last_actor_sweep = now
                await self._sweep_stalled_actor_queues()

    async def _sweep_stalled_actor_queues(self):
        """Lost-pubsub backstop: an actor record stuck PENDING/RESTARTING
        with queued calls re-polls the GCS. Without this, one dropped
        ALIVE/DEAD event (subscription raced the publish) hangs every
        caller of the queued tasks forever."""
        for rec in list(self._actors.values()):
            if not rec.queue or rec.state == "DEAD":
                continue
            if rec.state == "ALIVE":
                # ALIVE with parked specs: a flush was lost to the
                # first-contact thread race — push them now.
                await self._flush_actor_queue(rec)
                continue
            try:
                info = await self._gcs.call_async(
                    "get_actor_info", {"actor_id": rec.actor_id})
            except Exception:  # noqa: BLE001 — GCS restarting; the next
                # reconcile tick retries this actor record
                logger.debug("get_actor_info failed during reconcile",
                             exc_info=True)
                continue
            self._apply_actor_info(rec, info)

    async def _return_lease(self, lease: _Lease):
        node = lease.address.node_id
        raylet_addr = self.raylet_address
        if node is not None and node != self.node_id:
            raylet_addr = await self._node_raylet_addr(node) or raylet_addr
        if raylet_addr is None:
            return
        try:
            await self._peers.get(raylet_addr).send_async(
                "return_worker", {"worker_address": lease.address}
            )
        except ConnectionLost:
            pass

    # ------------------------------------------------- task completion paths
    def _register_reply_borrows(self, reply: dict) -> None:
        """Arg-borrow retention, owner side: the executing worker's reply
        names the nested arg refs it kept (executor._attach_retained_
        borrows). Register it as borrower NOW — before _finalize_task
        releases the submitted-task pins — because its own eager
        add_borrower rides a separate (possibly first-contact) peer
        connection and can lose the race against this owner's frame-exit
        free. Double-adds are harmless (borrowers is a set); a borrow
        retained here and dropped later is released by the worker's
        normal remove_borrower / death sweep."""
        borrower = reply.get("borrower_address")
        if not borrower:
            return
        for oid in reply.get("retained_borrows") or ():
            if self.reference_counter.owns(oid):
                self.reference_counter.add_borrower(oid, borrower)

    def _on_task_reply(self, spec: TaskSpec, reply: dict):
        t_reply = time.monotonic()
        pending = self._pending_tasks.get(spec.task_id)
        if pending is None or pending.spec.attempt_number != spec.attempt_number:
            return
        self._register_reply_borrows(reply)
        status = reply.get("status")
        if status == "ok":
            for oid, payload in reply.get("returns", []):
                self._store_return(oid, payload)
            if spec.is_streaming_generator():
                self._finish_generator(spec.task_id, reply.get("streaming_num_items", 0))
            stages = self._task_breakdown(spec, pending, reply, t_reply)
            self._finalize_task(spec, "FINISHED", stages)
        elif status == "cancelled":
            err = exc.TaskCancelledError(spec.task_id)
            self._store_error_for_task(spec, err)
            if spec.is_streaming_generator():
                # wake any consumer still parked in next_generator_item —
                # the error entry alone never signals the stream's cv
                self._finish_generator(spec.task_id, 0,
                                       error=ser.serialize(err))
            self._finalize_task(spec, "CANCELLED")
        else:  # application error
            error_obj = None
            if spec.retry_exceptions and pending.retries_left > 0:
                # A worker-side deadline drop rides the error reply shape,
                # but DeadlineExceededError is "never retried: a deadline
                # is a promise to the caller" (exceptions.py) — and the
                # requeued spec would keep its already-expired absolute
                # deadline, so every retry is a guaranteed futile
                # lease+push round trip (the exact doomed-work
                # amplification ISSUE 9 eliminates at the other layers).
                error_obj, _ = ser.deserialize(reply["error"])
                if not isinstance(error_obj, exc.DeadlineExceededError):
                    pending.retries_left -= 1
                    self._resubmit(spec, reason="application error")
                    return
            if error_obj is None:
                error_obj, _ = ser.deserialize(reply["error"])
            self._store_error_for_task(spec, error_obj)
            if spec.is_streaming_generator():
                self._finish_generator(spec.task_id, 0, error=reply["error"])
            stages = self._task_breakdown(spec, pending, reply, t_reply)
            self._finalize_task(spec, "FAILED", stages)

    def _task_breakdown(self, spec: TaskSpec, pending: _PendingTask,
                        reply: dict, t_reply: float) -> Optional[dict]:
        """Stitch owner stamps + worker durations into the six-stage
        latency breakdown; record it into metrics/trace/ring buffer."""
        from ray_tpu._private import latency

        stages = latency.owner_breakdown(
            pending.t_submit, pending.t_queued, pending.t_pushed,
            t_reply, time.monotonic(), reply.get("stages"))
        if stages is not None:
            latency.record_breakdown(
                spec.task_id.hex(), spec.function_name,
                spec.task_type.name, stages,
                trace_id=self._spec_trace_id(spec))
            if spec.trace_ctx is not None:
                self._record_owner_trace_spans(spec, stages)
        return stages

    def _record_owner_trace_spans(self, spec: TaskSpec,
                                  stages: dict) -> None:
        """Owner-side spans of a traced task: the task's OWN span (its
        id is the spec's trace_ctx span id, so children recorded by the
        worker/raylet parent correctly) plus the owner-observed stages.
        dispatch/execute are the worker's to record (its wall clock is
        the honest one there); the stage layout mirrors latency.py's
        back-to-back reconstruction ending at the reply-processed
        instant."""
        from ray_tpu._private.latency import STAGES

        end = time.time()
        total = sum(stages.get(s, 0.0) or 0.0 for s in STAGES)
        ctx = spec.trace_ctx
        _tracing.record_span(
            f"task:{spec.function_name}", ctx, end - total, end,
            span_id=ctx[1],
            attrs={"task_id": spec.task_id.hex(),
                   "type": spec.task_type.name})
        t = end - total
        for stage in STAGES:
            dur = stages.get(stage, 0.0) or 0.0
            if stage in ("submit", "queue", "rpc", "reply"):
                _tracing.record_span(f"task.{stage}", ctx, t, t + dur,
                                     attrs={"task_id": spec.task_id.hex()})
            t += dur

    def _on_worker_failure(self, spec: TaskSpec):
        pending = self._pending_tasks.get(spec.task_id)
        if pending is None:
            return
        if pending.retries_left > 0:
            pending.retries_left -= 1
            logger.info("retrying task %s after worker failure (%d retries left)",
                        spec.function_name, pending.retries_left)
            self._resubmit(spec, reason="worker failure")
            return
        # the other half of the retry FSM: budget exhausted, fail for good
        self._elog.emit("task.giveup", task_id=spec.task_id.hex(),
                        trace_id=self._spec_trace_id(spec),
                        reason="worker failure, no retries left")
        err = exc.WorkerCrashedError(
            f"The worker executing task {spec.function_name} died unexpectedly."
        )
        self._store_error_for_task(spec, err)
        self._finalize_task(spec, "FAILED")

    def _resubmit(self, spec: TaskSpec, reason: str = "resubmit"):
        spec.attempt_number += 1
        pending = self._pending_tasks.get(spec.task_id)
        self._elog.emit(
            "task.retry", task_id=spec.task_id.hex(), reason=reason,
            trace_id=self._spec_trace_id(spec),
            attempt=spec.attempt_number,
            retries_left=pending.retries_left if pending else 0)
        if pending is not None:
            pending.spec = spec
            # fresh queue/push stamps for the retry; t_submit stays, so the
            # final breakdown's total covers every attempt
            pending.t_queued = None
            pending.t_pushed = None
        if spec.task_type == TaskType.NORMAL_TASK:
            self._normal_submit(spec)
        else:
            self._actor_submit(spec)

    def _store_return(self, oid: ObjectID, payload: dict):
        if "inline" in payload:
            self.memory_store.put_serialized(oid, payload["inline"])
            if payload["inline"] is not None:
                self.reference_counter.set_size(
                    oid, payload["inline"].total_bytes())
        else:
            self.memory_store.put_serialized(
                oid, None, location=payload["location"],
                in_plasma=payload.get("plasma_node") is not None,
                plasma_node=payload.get("plasma_node"))
            self.reference_counter.set_location(oid, payload["location"])
            if payload.get("size"):
                self.reference_counter.set_size(oid, payload["size"])
        self._release_deps(oid)

    def _store_error_for_task(self, spec: TaskSpec, error: BaseException):
        # tail-based keep: a trace that contains a task FAILURE is
        # interesting regardless of the head-sampling verdict. Consumer-
        # initiated cancels are routine (every abandoned stream ends in
        # one) — promoting them would flood the durable store.
        if not isinstance(error, exc.TaskCancelledError):
            _tracing.force_trace(self._spec_trace_id(spec),
                                 f"task_error:{type(error).__name__}")
        s = ser.serialize(error)
        for oid in spec.return_ids():
            self.memory_store.put_serialized(oid, s, value=error, is_exception=True)
            self._release_deps(oid)
        if spec.is_streaming_generator():
            # Wake consumers parked in next_generator_item: the error
            # entries above never signal the stream's cv, so every
            # terminal failure path that forgot an explicit
            # _finish_generator (actor push failure, _fail_actor_queue)
            # hung streaming consumers forever — chaos-harness find.
            # Idempotent with the call sites that already finish.
            self._finish_generator(spec.task_id, 0, error=s)

    def _finalize_task(self, spec: TaskSpec, state: str,
                       stages: Optional[dict] = None):
        with self._pending_lock:
            pending = self._pending_tasks.pop(spec.task_id, None)
        if pending is not None:
            for oid in pending.arg_ids:
                self.reference_counter.remove_submitted_task_ref(oid)
            if (spec.task_type == TaskType.ACTOR_TASK
                    and spec.actor_id is not None):
                rec = self._actors.get(spec.actor_id)
                if rec is not None:
                    with rec.lock:  # mailbox slot freed
                        if rec.outstanding > 0:
                            rec.outstanding -= 1
        self._record_task_event(spec, state, stages)

    # ------------------------------------------------------- actor submission
    def create_actor(
        self,
        cls,
        args: tuple,
        kwargs: dict,
        *,
        resources=None,
        placement_resources=None,
        max_restarts: int = 0,
        max_task_retries: int = 0,
        max_concurrency: Optional[int] = None,
        name: Optional[str] = None,
        namespace: Optional[str] = None,
        lifetime: Optional[str] = None,
        get_if_exists: bool = False,
        scheduling_strategy: Optional[SchedulingStrategySpec] = None,
        is_asyncio: bool = False,
        runtime_env: Optional[dict] = None,
    ) -> ActorID:
        job_id = self.current_job_id()
        actor_id = ActorID.of(job_id)
        fid = self.register_function(cls)
        runtime_env = self.prepare_runtime_env(runtime_env)
        if max_concurrency is None:
            max_concurrency = 1000 if is_asyncio else 1
        creation = ActorCreationSpec(
            actor_id=actor_id,
            max_restarts=max_restarts,
            max_task_retries=max_task_retries,
            max_concurrency=max_concurrency,
            name=name,
            namespace=namespace if namespace is not None else self.namespace,
            is_detached=lifetime == "detached",
            is_asyncio=is_asyncio,
        )
        arg_specs, kwarg_specs, arg_ids = self._build_args(args, kwargs)
        spec = TaskSpec(
            task_id=TaskID.for_actor_creation_task(actor_id),
            job_id=job_id,
            task_type=TaskType.ACTOR_CREATION_TASK,
            function_id=fid,
            function_name=getattr(cls, "__name__", "Actor") + ".__init__",
            args=arg_specs,
            num_returns=0,
            resources=resources if resources is not None
            else {"CPU": CONFIG.default_actor_num_cpus},
            placement_resources=placement_resources,
            owner_address=self.address,
            trace_parent=self.current_task_id().hex(),
            trace_ctx=self._trace_ctx_for_submit(),
            scheduling_strategy=scheduling_strategy or SchedulingStrategySpec(),
            actor_creation=creation,
            runtime_env=runtime_env,
            function_blob=self._fn_blobs.get(fid),
        )
        spec.kwarg_specs = kwarg_specs
        if name or get_if_exists:
            # named path stays synchronous: the reply decides between
            # "use the existing actor" and a name-conflict error
            reply = self._gcs.call(
                "register_actor",
                {"spec": spec, "get_if_exists": get_if_exists})
            if reply["status"] == "retry_later":
                # bounded GCS creation queue: typed pushback to the caller
                raise exc.RetryLaterError(
                    "GCS actor-creation queue is full",
                    retry_after_s=reply.get("retry_after_s", 1.0),
                    layer="gcs_actor_creation")
            if reply["status"] == "error":
                raise ValueError(reply["message"])
            registered_id = reply["info"].actor_id
        else:
            # Unnamed actors register PIPELINED (reference: CreateActor's
            # GCS registration is async, core_worker.cc:2224): the
            # request is enqueued and .remote() returns immediately, so a
            # burst of N creations pays one round trip of latency, not N.
            # A lost registration (GCS blip) retries once; a retry_later
            # pushback from the bounded creation queue re-registers with
            # paced backoff (the creation-burst analogue of AIMD lease
            # pacing). Both paths eventually mark the local record DEAD
            # so queued method calls fail typed instead of hanging.
            pushback_policy = _backoff.BackoffPolicy(
                base_s=0.25, multiplier=2.0, max_s=5.0, jitter=0.25)

            def _register(attempt: int = 0, pushbacks: int = 0):
                fut = self._gcs.call_future(
                    "register_actor",
                    {"spec": spec, "get_if_exists": False})

                def _mark_dead(aid, cause):
                    dead = ActorInfo(
                        actor_id=aid, state=ActorState.DEAD,
                        death_cause=cause)
                    asyncio.ensure_future(self._on_actor_event_async(dead))

                def _on_reply(f, aid=actor_id):
                    err = f.exception()
                    if err is None:
                        reply = f.result()
                        if (isinstance(reply, dict)
                                and reply.get("status") == "retry_later"):
                            if pushbacks >= 6:
                                logger.warning(
                                    "actor %s shed by the GCS creation "
                                    "queue %d times; giving up", aid,
                                    pushbacks + 1)
                                _mark_dead(
                                    aid,
                                    "GCS actor-creation queue stayed full"
                                    " (typed RetryLaterError pushback)")
                                return
                            delay = max(
                                reply.get("retry_after_s", 0.0),
                                pushback_policy.delay(pushbacks + 1))
                            self._gcs._lt.loop.call_later(
                                delay, lambda: _register(
                                    attempt, pushbacks + 1))
                        return
                    if attempt == 0:
                        logger.warning(
                            "actor %s registration failed (%s); retrying",
                            aid, err)
                        self._gcs._lt.loop.call_later(
                            0.5, lambda: _register(1, pushbacks))
                        return
                    logger.warning(
                        "actor %s registration failed permanently: %s",
                        aid, err)
                    _mark_dead(aid, f"actor registration failed: {err}")

                fut.add_done_callback(_on_reply)

            _register()
            registered_id = actor_id
        rec = self._actors.setdefault(
            registered_id, _ActorRecord(actor_id=registered_id)
        )
        rec.max_task_retries = max_task_retries
        self._ensure_actor_subscription()
        return registered_id

    def _on_worker_logs(self, key, batch):
        """LOG channel: print worker output on the driver console (only
        lines attributed to THIS driver's job — multi-job clusters must
        not interleave consoles)."""
        batch_job = batch.get("job_id")
        startup_crash = batch.get("unattributed", False)
        if batch_job is None and not startup_crash:
            # Unattributed output: broadcasting it would leak lines onto
            # every connected driver's console on multi-job clusters — drop.
            # (The raylet attributes normal startup output to the worker's
            # first lease; only marked startup-CRASH batches pass through.)
            return
        if (not startup_crash and self.job_id
                and batch_job != self.job_id.hex()):
            return
        pid = batch.get("pid")
        node = (batch.get("node") or "")[:8]
        tag = ", startup-crash" if startup_crash else ""
        prefix = f"(worker pid={pid}, node={node}{tag})"
        out = sys.stderr
        for line in batch.get("lines", []):
            print(f"{prefix} {line}", file=out)

    def _on_error_message(self, key, err):
        """ERROR channel: print this job's task errors once per task (the
        same error also surfaces at ray.get — dedup keeps retries quiet)."""
        if self.job_id and err.get("job_id") != self.job_id.hex():
            return
        task_id = err.get("task_id")
        if task_id in self._printed_errors:
            return
        self._printed_errors.add(task_id)
        if len(self._printed_errors) > 10_000:
            self._printed_errors.clear()
        print(f"(task error) {err.get('name')}: {err.get('message')}",
              file=sys.stderr)

    def report_error(self, spec, err: BaseException) -> None:
        """Fire-and-forget error publication to the GCS ERROR channel."""
        try:
            self._lt.submit(self._gcs.send_async("report_error", {
                "job_id": spec.job_id.hex() if spec.job_id else None,
                "task_id": spec.task_id.hex(),
                "name": spec.function_name,
                "message": str(err),
            }))
        except Exception:  # noqa: BLE001 — reporting must not mask the error
            logger.debug("error-report publication failed", exc_info=True)

    def _on_node_event(self, key, info):
        if info.alive:
            self._node_addr_cache[info.node_id] = info.raylet_address
            return
        self._node_addr_cache.pop(info.node_id, None)
        self._peers.invalidate(info.raylet_address)
        if info.raylet_address == self.raylet_address and self.mode == "driver":
            self._lt.submit(self._refresh_local_raylet())

    async def _refresh_local_raylet(self):
        try:
            nodes = await self._gcs.call_async("get_all_node_info", {})
        except (ConnectionLost, OSError):
            return None
        alive = [n for n in nodes if n.alive]
        if not alive:
            return None
        head = next((n for n in alive if n.is_head), alive[0])
        if head.raylet_address != self.raylet_address:
            logger.warning(
                "local raylet died; failing over to %s", head.raylet_address
            )
            self.raylet_address = head.raylet_address
        return self.raylet_address

    def _ensure_actor_subscription(self):
        if self._actor_sub_started:
            return
        self._actor_sub_started = True
        self.subscribe(ps.ACTOR_CHANNEL, self._on_actor_event)
        self._gcs.call(
            "subscribe",
            {"channel": ps.ACTOR_CHANNEL, "subscriber_address": self.address_str},
        )

    def _on_actor_event(self, key, info):
        self._lt.submit(self._on_actor_event_async(info))

    async def _on_actor_event_async(self, info):
        rec = self._actors.get(info.actor_id)
        if rec is None:
            return
        if info.state == ActorState.ALIVE:
            rec.state = "ALIVE"
            self._emit_actor_state(rec, "pubsub event")
            self._note_incarnation(rec, info)
            rec.address = info.address
            await self._flush_actor_queue(rec)
        elif info.state == ActorState.RESTARTING:
            rec.state = "RESTARTING"
            self._emit_actor_state(rec, "pubsub event")
            # the incarnation behind rec.address is DEAD (that is why it is
            # restarting): drop its borrows NOW, before the address is
            # nulled here / overwritten by the next ALIVE — afterwards
            # nothing remembers which worker held them
            self._drop_dead_borrower(rec.address)
            rec.address = None
            if rec.queue:
                # The reaper may have parked while this actor looked
                # ALIVE; queued calls now depend on the lost-ALIVE sweep
                # backstop, so make sure it is running.
                self._poke_reaper()
        elif info.state == ActorState.DEAD:
            rec.state = "DEAD"
            self._emit_actor_state(rec, "pubsub event")
            rec.death_cause = info.death_cause
            self._drop_dead_borrower(rec.address)
            rec.address = None
            self._fail_actor_queue(rec)

    def _drop_dead_borrower(self, address) -> None:
        """A dead actor can never send its borrow releases: drop its
        worker from every owned ref's borrower set, or each object it
        borrowed stays pinned on this owner forever (reference: the owner
        prunes borrowers on worker-failure notifications)."""
        if address is not None:
            self.reference_counter.remove_borrower_everywhere(
                address.rpc_address)

    def _note_incarnation(self, rec: "_ActorRecord", info) -> None:
        """An ALIVE at a higher num_restarts means the PREVIOUS
        incarnation died: reset the sequencing gate for the new worker
        and drop the dead incarnation's borrows (a missed RESTARTING
        pubsub event would otherwise overwrite the only record of which
        address held them). Call BEFORE rec.address is updated."""
        if info.num_restarts > rec.incarnation:
            new_addr = (info.address.rpc_address
                        if info.address is not None else None)
            if (rec.address is not None
                    and rec.address.rpc_address != new_addr):
                self._drop_dead_borrower(rec.address)
            rec.incarnation = info.num_restarts
            rec.seq = 0

    def _emit_actor_state(self, rec: "_ActorRecord", reason: str) -> None:
        """Owner-side actor record FSM transition -> lifecycle event log
        (the client's view can disagree with the GCS FSM during races —
        post-mortems need both sides)."""
        self._elog.emit("actor.client_state", actor_id=rec.actor_id.hex(),
                        state=rec.state, reason=reason)

    def _fail_actor_queue(self, rec: _ActorRecord) -> None:
        """Fail every task queued on a DEAD actor. Callable from any point
        that discovers the death — queueing a spec after the DEAD pubsub
        event already drained the queue would otherwise strand it (and the
        caller's ray.get) forever."""
        while rec.queue:
            spec = rec.queue.popleft()
            self._store_error_for_task(
                spec,
                exc.ActorDiedError(rec.actor_id, error_message=(
                    f"Actor {rec.actor_id.hex()[:12]} is dead: "
                    f"{rec.death_cause}")),
            )
            self._finalize_task(spec, "FAILED")

    def submit_actor_task(
        self, actor_id: ActorID, method_name: str, args: tuple, kwargs: dict,
        *, num_returns=1, deadline_s: Optional[float] = None,
    ):
        t_submit = time.monotonic()
        rec = self._actors.get(actor_id)
        if rec is None:
            rec = _ActorRecord(actor_id=actor_id)
            self._actors[actor_id] = rec
            self._ensure_actor_subscription()
            info = self._gcs.call("get_actor_info", {"actor_id": actor_id})
            if info is not None:
                if info.state == ActorState.ALIVE:
                    rec.state = "ALIVE"
                    self._emit_actor_state(rec, "first contact")
                    rec.address = info.address
                    # First-contact race: a CONCURRENT submit from another
                    # thread can find this record while the GCS call above
                    # was in flight, see a non-ALIVE state, and park its
                    # spec on rec.queue — and its own async poll then
                    # no-ops because the state is ALIVE by the time it
                    # lands. Whoever completes the first-contact poll owns
                    # flushing the queue, or those parked calls hang
                    # forever (observed: concurrent streaming calls from
                    # serve.llm's router threads).
                    self._lt.submit(self._flush_actor_queue(rec))
                elif info.state == ActorState.DEAD:
                    rec.state = "DEAD"
                    self._emit_actor_state(rec, "first contact")
                    rec.death_cause = info.death_cause
        if rec.state == "DEAD":
            raise exc.ActorDiedError(
                actor_id, error_message=f"Actor is dead: {rec.death_cause}"
            )
        mailbox_max = CONFIG.actor_mailbox_max
        if mailbox_max > 0 and rec.outstanding >= mailbox_max:
            # Bounded owner-side mailbox: typed pushback at submit instead
            # of parking an unbounded backlog behind a non-ALIVE (or
            # slow-flushing) actor. The caller retries after the hint —
            # shed, never lost.
            ambient = _tracing.current_trace()
            self._elog.emit("task.shed", actor_id=actor_id.hex(),
                            trace_id=ambient.trace_id if ambient else None,
                            layer="actor_mailbox", reason="mailbox full",
                            method=method_name)
            _backoff.count_shed("actor_mailbox")
            if ambient is not None:
                _tracing.force_trace(ambient.trace_id,
                                     "task.shed:actor_mailbox")
            raise exc.RetryLaterError(
                f"actor {actor_id.hex()[:12]} mailbox is full "
                f"({rec.outstanding} outstanding calls)",
                retry_after_s=_backoff.retry_after_hint(rec.outstanding),
                layer="actor_mailbox")
        streaming = num_returns == "streaming" or num_returns == -1
        task_id = TaskID.for_actor_task(actor_id)
        arg_specs, kwarg_specs, arg_ids = self._build_args(args, kwargs)
        spec = TaskSpec(
            task_id=task_id,
            job_id=self.current_job_id(),
            task_type=TaskType.ACTOR_TASK,
            function_id="",
            function_name=method_name,
            method_name=method_name,
            args=arg_specs,
            num_returns=-1 if streaming else num_returns,
            owner_address=self.address,
            trace_parent=self.current_task_id().hex(),
            trace_ctx=self._trace_ctx_for_submit(),
            actor_id=actor_id,
            deadline_s=_deadlines.effective_deadline(
                deadline_s, self._parent_deadline()),
        )
        spec.kwarg_specs = kwarg_specs
        with self._pending_lock:
            self._pending_tasks[task_id] = _PendingTask(
                spec=spec, retries_left=rec.max_task_retries,
                is_actor_task=True, arg_ids=arg_ids, t_submit=t_submit,
            )
        # mailbox slot held from here until _finalize_task releases it
        # (incremented on the user thread, AFTER every raise-able step,
        # paired with the _pending_tasks entry the decrement keys off)
        with rec.lock:
            rec.outstanding += 1
        if streaming:
            # See submit_task: item oids are owned at report time, not here.
            self._generators[task_id] = _GeneratorState()
            self._actor_submit(spec)
            return ObjectRefGenerator(task_id)
        return_refs = []
        for oid in spec.return_ids():
            self.reference_counter.add_owned(oid, self.address)
            return_refs.append(ObjectRef(oid, owner_address=self.address))
        self._actor_submit(spec)
        return return_refs

    def _actor_submit(self, spec: TaskSpec):
        self._enqueue_submit(True, spec)

    async def _actor_submit_batch(self, actor_id: ActorID,
                                  specs: List[TaskSpec]):
        rec = self._actors[actor_id]
        if rec.state == "ALIVE" and rec.address is not None:
            await self._push_actor_tasks(rec, specs)
            return
        if rec.state == "DEAD":
            for spec in specs:
                self._store_error_for_task(
                    spec, exc.ActorDiedError(
                        rec.actor_id,
                        error_message=f"Actor is dead: {rec.death_cause}"))
                self._finalize_task(spec, "FAILED")
            return
        rec.queue.extend(specs)
        self._poke_reaper()  # sweep backstop for a lost ALIVE event
        # Poll GCS once in case we missed the ALIVE (or DEAD) event.
        info = await self._gcs.call_async(
            "get_actor_info", {"actor_id": actor_id})
        self._apply_actor_info(rec, info)

    def _apply_actor_info(self, rec: _ActorRecord, info) -> None:
        """Reconcile a GCS-polled ActorInfo into the record — the polled
        twin of _on_actor_event_async, for when the pubsub event was lost
        or raced the subscription. A missed DEAD here left queued specs
        (and their callers' ray.get) hanging forever."""
        if info is None:
            return
        if (info.state == ActorState.ALIVE
                and rec.state not in ("ALIVE", "DEAD")):
            # DEAD is terminal: a stale poll reply racing the DEAD pubsub
            # event must not resurrect the record (new submits would stop
            # raising ActorDiedError and push to a dead address)
            rec.state = "ALIVE"
            self._emit_actor_state(rec, "GCS reconcile")
            self._note_incarnation(rec, info)
            rec.address = info.address
            asyncio.ensure_future(self._flush_actor_queue(rec))
        elif (info.state == ActorState.ALIVE and rec.state == "ALIVE"
              and rec.queue):
            # Already ALIVE but specs are parked (another thread queued
            # them while the first-contact poll was in flight): flush.
            asyncio.ensure_future(self._flush_actor_queue(rec))
        elif info.state == ActorState.DEAD and rec.state != "DEAD":
            rec.state = "DEAD"
            self._emit_actor_state(rec, "GCS reconcile")
            rec.death_cause = info.death_cause
            self._drop_dead_borrower(rec.address)
            rec.address = None
            self._fail_actor_queue(rec)

    async def _flush_actor_queue(self, rec: _ActorRecord):
        if rec.queue and rec.state == "ALIVE" and rec.address is not None:
            specs = list(rec.queue)
            rec.queue.clear()
            asyncio.ensure_future(self._push_actor_tasks(rec, specs))

    async def _push_actor_tasks(self, rec: _ActorRecord,
                                specs: List[TaskSpec]):
        """Push a burst of calls to one actor as ONE RPC. Sequence numbers
        are assigned here (on the loop, in FIFO order) so that a restarted
        actor incarnation starts again from 0. The worker preserves
        concurrency semantics per batch (ordered actors run the batch
        serially in seq order; async/threaded actors dispatch every spec
        concurrently — see _handle_push_task_w)."""
        # Assign ALL sequence numbers up-front, before any await: a later
        # batch's coroutine can interleave at the chunk-push awaits below,
        # and taking rec.seq there would hand later-submitted calls
        # earlier sequence numbers (the worker's sequencing gate executes
        # strictly by seq — ordered actors would run calls out of order).
        # A spec requeued after a failed push to THIS incarnation keeps
        # its number: its slot was already burned, and a fresh one would
        # leave a permanent gap the worker's gate waits 60s on for every
        # later call. Specs carried across an incarnation bump re-stamp
        # from the reset counter (the new worker's gate starts at 0).
        #
        # Queue-pop doomed-work elimination: expired specs that were
        # never sequence-stamped are dropped HERE (before a number is
        # burned); already-stamped requeues must still ride to the worker
        # — it drops them at its own pop and advances the gate, so no
        # permanent seq gap can form.
        now = time.time()
        alive = []
        for spec in specs:
            if (spec.sequence_number < 0
                    and _deadlines.expired(spec.deadline_s, now)):
                self._expire_spec(spec)
                continue
            alive.append(spec)
        specs = alive
        if not specs:
            return
        for spec in specs:
            if (spec.sequence_number < 0
                    or getattr(spec, "_seq_incarnation", None)
                    != rec.incarnation):
                spec.sequence_number = rec.seq
                rec.seq += 1
                spec._seq_incarnation = rec.incarnation
            self._record_task_event(spec, "RUNNING")
        cap = max(1, CONFIG.max_tasks_per_push)
        # Chunking: a batched RPC replies once, AFTER every call in it
        # completed — so a long-running call in the batch holds every
        # batch-mate's reply hostage (observed deadlock: tune's quick
        # start_training batched with the hour-long next_result long-poll;
        # tune needed start_training's error to cancel next_result).
        # Only methods MEASURED short may share a chunk; unknown or slow
        # methods ride their own pipelined RPC. Chunks are all in flight
        # concurrently on the multiplexed connection (frames written in
        # seq order; the worker's sequencing gate orders execution), so
        # splitting costs framing bytes, not round trips.
        # at least 50ms: scheduler preemption on a loaded host shows up as
        # tens-of-ms execution blips, and one blip must not permanently
        # unbatch a microsecond method
        threshold = max(0.05, CONFIG.task_batch_latency_ms / 1000.0)
        chunks: List[List[TaskSpec]] = []
        cur: List[TaskSpec] = []
        for spec in specs:
            worst = rec.method_time_max.get(spec.method_name)
            short = worst is not None and worst < threshold
            if short and len(cur) < cap:
                cur.append(spec)
                continue
            if cur:
                chunks.append(cur)
                cur = []
            if short:
                cur.append(spec)
            else:
                chunks.append([spec])
        if cur:
            chunks.append(cur)
        client = self._peers.get(rec.address.rpc_address)

        async def _push_chunk(chunk: List[TaskSpec]):
            t0 = time.monotonic()
            for spec in chunk:
                p = self._pending_tasks.get(spec.task_id)
                if p is not None:
                    p.t_pushed = t0
            try:
                wire = await client.call_async(
                    "push_task_w", [spec_to_wire(s) for s in chunk],
                    timeout=None)
                replies = [reply_from_wire(t) for t in wire]
            except ConnectionLost as e:
                # maybe_delivered=False (connect refused: the actor worker
                # process is already gone) means NOTHING in this chunk
                # executed — the failure path may requeue without burning
                # at-most-once retry budget.
                logger.debug("actor push chunk failed", exc_info=True)
                return [(s, not e.maybe_delivered) for s in chunk]
            except Exception:  # noqa: BLE001 — remote handler error,
                # reply decode failure: these specs got no usable reply.
                # Route them ALL through the push-failure path; letting
                # any exception escape would blow up the gather and
                # strand the OTHER chunks' specs.
                logger.debug("actor push chunk failed", exc_info=True)
                return [(s, False) for s in chunk]
            per_call = (time.monotonic() - t0) / max(1, len(chunk))
            for spec, reply in zip(chunk, replies):
                # prefer the worker-measured execution time: the round
                # trip includes sequencing-gate queueing behind earlier
                # calls, which would inflate fast methods into
                # "long" and permanently defeat batching
                dur = (reply.get("exec_s", per_call)
                       if isinstance(reply, dict) else per_call)
                prev = rec.method_time_max.get(spec.method_name, 0.0)
                if prev >= 1.0 or dur >= 1.0:
                    # a method that ever blocked a full second is a
                    # long-poller: sticky, never re-batches
                    rec.method_time_max[spec.method_name] = max(prev, dur)
                else:
                    # sub-second worst decays, so one preemption blip
                    # doesn't permanently defeat batching
                    rec.method_time_max[spec.method_name] = max(
                        dur, prev * 0.8)
                self._on_task_reply(spec, reply)
            return None

        if len(chunks) == 1:  # hot path: no gather/task machinery
            failed = await _push_chunk(chunks[0]) or []
        else:
            results = await asyncio.gather(*(map(_push_chunk, chunks)))
            failed = [s for chunk in results if chunk for s in chunk]
        if failed:
            await self._on_actor_push_failure(rec, failed)

    async def _on_actor_push_failure(self, rec: _ActorRecord,
                                     failures: List[Tuple[TaskSpec, bool]]):
        """`failures`: (spec, undelivered) pairs. undelivered=True means
        the push provably never reached the worker (ConnectionLost with
        maybe_delivered=False): the call did not execute, so requeueing it
        is safe for ANY method and must not consume the at-most-once
        retry budget (bounded by undelivered_failures so a persistently
        refusing address still terminates)."""
        peer = (rec.address.rpc_address if rec.address is not None
                else rec.actor_id.hex())
        budget = _backoff.default_retry_budget()
        retry_specs = []
        for spec, undelivered in failures:
            pending = self._pending_tasks.get(spec.task_id)
            if pending is not None and undelivered:
                pending.undelivered_failures += 1
                if pending.undelivered_failures <= 20:
                    retry_specs.append(spec)
                    continue
                # persistent refusals: fall through to the budgeted path
            # At-most-once retries spend the (peer, method) token bucket
            # BEFORE spending per-task retries_left: during a brownout
            # every in-flight call fails at once, and N tasks x M retries
            # of un-budgeted resubmission is the retry storm that turns a
            # brownout into a blackout. An empty bucket fails fast with
            # the underlying error (counted in
            # ray_tpu_retry_budget_exhausted_total).
            if (pending is not None and pending.retries_left > 0
                    and budget.try_spend(peer, spec.method_name)):
                pending.retries_left -= 1
                retry_specs.append(spec)
            else:
                self._store_error_for_task(
                    spec,
                    exc.ActorUnavailableError(
                        rec.actor_id,
                        error_message="Lost connection to actor "
                        f"{rec.actor_id.hex()[:12]} while task "
                        f"{spec.method_name} was in flight.",
                    ),
                )
                self._finalize_task(spec, "FAILED")
        if not retry_specs:
            return
        rec.queue.extend(retry_specs)
        self._poke_reaper()  # sweep backstop while the actor restarts
        if rec.state == "DEAD":
            # the DEAD pubsub event already drained the queue before we
            # re-queued these specs — fail them now or they hang forever
            self._fail_actor_queue(rec)
            return
        if rec.state == "ALIVE":
            rec.state = "RESTARTING"  # wait for pubsub to re-resolve
            self._emit_actor_state(rec, "push failure")
        # The address may simply be stale (actor already restarted):
        # re-resolve once from the GCS.
        info = await self._gcs.call_async(
            "get_actor_info", {"actor_id": rec.actor_id}
        )
        if (
            info is not None
            and info.state == ActorState.ALIVE
            and info.address is not None
            and (rec.address is None
                 or info.address.rpc_address != rec.address.rpc_address
                 or info.num_restarts > rec.incarnation)
        ):
            rec.state = "ALIVE"
            self._emit_actor_state(rec, "re-resolved after push failure")
            self._note_incarnation(rec, info)
            rec.address = info.address
            await self._flush_actor_queue(rec)
            return
        if info is not None and info.state == ActorState.DEAD:
            # no restart coming (pubsub DEAD may have been processed before
            # our specs were queued, or the subscription raced creation)
            rec.state = "DEAD"
            self._emit_actor_state(rec, "re-resolved after push failure")
            rec.death_cause = info.death_cause
            self._drop_dead_borrower(rec.address)
            rec.address = None
            self._fail_actor_queue(rec)

    # -------------------------------------------------------- actor controls
    def kill_actor(self, actor_id: ActorID, no_restart: bool = True):
        self._gcs.call("kill_actor", {"actor_id": actor_id, "no_restart": no_restart})

    def get_actor_info(self, actor_id: ActorID):
        return self._gcs.call("get_actor_info", {"actor_id": actor_id})

    def get_named_actor(self, name: str, namespace: Optional[str] = None):
        return self._gcs.call(
            "get_named_actor",
            {"name": name, "namespace": namespace if namespace is not None else self.namespace},
        )

    def cancel_task(self, ref: ObjectRef, force: bool = False):
        self.cancel_task_by_id(ref.object_id().task_id(), force)

    def cancel_task_by_id(self, task_id, force: bool = False):
        """Cancel by TaskID directly — used by ObjectRefGenerator.close(),
        where the consumer holds a generator (task) rather than a ref."""
        pending = self._pending_tasks.get(task_id)
        if pending is None:
            return
        target = pending.pushed_to
        if target is None and pending.spec.task_type == TaskType.ACTOR_TASK:
            # Actor pushes never set pushed_to (that is the normal-task
            # lease field): resolve the actor's CURRENT worker address so
            # a running actor stream actually receives the cancel — the
            # silent local no-op here left abandoned serving streams
            # decoding whole token budgets into the void (ISSUE 6 find).
            rec = self._actors.get(pending.spec.actor_id)
            if rec is not None:
                if rec.queue and any(
                        s.task_id == task_id for s in rec.queue):
                    # still parked owner-side waiting for an address:
                    # cancel it locally, nothing to RPC
                    async def _drop_queued():
                        r = self._actors.get(pending.spec.actor_id)
                        if r is None:
                            return
                        for s in list(r.queue):
                            if s.task_id == task_id:
                                r.queue.remove(s)
                                self._cancel_queued_spec(s, task_id)
                                return

                    try:
                        self._lt.submit(_drop_queued()).result(timeout=10)
                    except (TimeoutError, concurrent.futures.TimeoutError):
                        pass
                    return
                if rec.address is not None:
                    target = rec.address.rpc_address
        if target is not None:
            try:
                self._peers.get(target).call(
                    "cancel_task", {"task_id": task_id, "force": force}, timeout=10
                )
            except ConnectionLost:
                pass
        else:
            # Still queued locally (or parked on unresolved deps): drop it.
            # Marshaled onto the event loop — _dep_waiters and _key_states
            # are loop-owned; mutating them from the caller's thread races
            # _drain_submits registration (lost waiters -> hung gets).
            async def _cancel_local():
                if self._cancel_parked(task_id):
                    self._cancel_queued_spec(pending.spec, task_id)
                    return
                key = pending.spec.scheduling_key()
                st = self._key_states.get(key)
                if st is not None:
                    try:
                        st.pending.remove(pending.spec)
                    except ValueError:
                        pass
                    else:
                        self._cancel_queued_spec(pending.spec, task_id)

            try:
                self._lt.submit(_cancel_local()).result(timeout=10)
            except (TimeoutError, concurrent.futures.TimeoutError):
                pass

    def _cancel_queued_spec(self, spec: TaskSpec, task_id):
        """Finalize a spec cancelled before dispatch (loop thread only)."""
        self._store_error_for_task(spec, exc.TaskCancelledError(task_id))
        if spec.is_streaming_generator():
            # wake consumers blocked in next_generator_item — the error
            # entry alone never signals the generator's condition variable
            self._finish_generator(
                task_id, 0,
                error=ser.serialize(exc.TaskCancelledError(task_id)))
        self._finalize_task(spec, "CANCELLED")

    # ------------------------------------------------------ placement groups
    def create_placement_group(
        self, bundles, strategy="PACK", name="", lifetime=None
    ) -> PlacementGroupID:
        job_id = self.current_job_id()
        pg_id = PlacementGroupID.of(job_id)
        spec = PlacementGroupSpec(
            placement_group_id=pg_id,
            bundles=[dict(b) for b in bundles],
            strategy=strategy,
            name=name,
            lifetime=lifetime,
            job_id=job_id,
        )
        reply = self._gcs.call("create_placement_group", {"spec": spec})
        if reply["status"] != "ok":
            raise ValueError(reply.get("message", "placement group creation failed"))
        return pg_id

    def remove_placement_group(self, pg_id: PlacementGroupID):
        self._pg_cache.pop(pg_id, None)
        self._gcs.call("remove_placement_group", {"placement_group_id": pg_id})

    def wait_placement_group_ready(self, pg_id: PlacementGroupID, timeout=None) -> bool:
        reply = self._gcs.call(
            "wait_placement_group_ready",
            {"placement_group_id": pg_id, "timeout": timeout},
            timeout=(timeout + 5) if timeout and timeout > 0 else None,
        )
        return reply.get("status") == "ready"

    # -------------------------------------------------------------- pubsub
    def subscribe(self, channel: str, callback):
        self._subscriptions.setdefault(channel, []).append(callback)

    async def _handle_pubsub_message(self, payload):
        channel, key, message = payload
        for cb in self._subscriptions.get(channel, []):
            try:
                cb(key, message)
            except Exception:
                logger.exception("pubsub callback failed")
        return True

    # ------------------------------------------------------ owner services
    async def _handle_get_object(self, payload):
        oid: ObjectID = payload["object_id"]
        want_value = payload.get("want_value", True)
        timeout = payload.get("timeout", 0)
        entry = self.memory_store.get_entry(oid)
        if entry is None and timeout and timeout > 0:
            loop = asyncio.get_event_loop()
            fut = loop.create_future()

            def _cb(e):
                loop.call_soon_threadsafe(
                    lambda: fut.set_result(e) if not fut.done() else None
                )

            self.memory_store.add_callback(oid, _cb)
            try:
                entry = await asyncio.wait_for(fut, timeout)
            except asyncio.TimeoutError:
                entry = self.memory_store.get_entry(oid)
        if entry is None:
            if self.reference_counter.owns(oid) or oid.task_id() in self._pending_tasks:
                return {"status": "pending"}
            return {"status": "not_owner"}
        if entry.freed:
            return {"status": "freed"}
        if entry.location is not None and entry.serialized is None:
            locs = self.reference_counter.get_all_locations(oid)
            return {"status": "ready", "location": entry.location,
                    "replicas": [l for l in locs if l != entry.location]}
        if entry.in_plasma and entry.serialized is None:
            # Owner holds the payload in its node shm store: serve it from
            # there (borrower is remote — same-node borrowers hit shm
            # directly and never reach this RPC).
            if want_value:
                s = await asyncio.to_thread(self._read_local_plasma, oid)
                if s is None:
                    return {"status": "freed"}
                if (s.wire_size() > CONFIG.fetch_chunk_size_bytes
                        and not entry.is_exception):
                    # don't inline multi-chunk payloads in one reply:
                    # point the borrower at our fetch/chunk service
                    return {"status": "ready",
                            "location": self.address_str,
                            "replicas": self.reference_counter
                            .get_all_locations(oid)}
                return {"status": "ready", "data": s,
                        "is_exception": entry.is_exception}
            return {"status": "ready"}
        if want_value:
            if (entry.serialized is not None
                    and not entry.is_exception
                    and entry.serialized.wire_size()
                    > CONFIG.fetch_chunk_size_bytes):
                return {"status": "ready", "location": self.address_str,
                        "replicas": self.reference_counter
                        .get_all_locations(oid)}
            return {
                "status": "ready",
                "data": entry.serialized,
                "is_exception": entry.is_exception,
            }
        return {"status": "ready"}

    def _read_local_plasma(self, oid: ObjectID):
        if self.plasma is None:
            return None
        return self.plasma.get_serialized(oid)

    async def _handle_fetch_object(self, payload):
        """Serve a whole object — or, when the caller sets max_inline and
        the object is bigger, announce its flat wire size so the caller
        switches to chunked pulls (fetch_object_chunk). Reference:
        object_manager.cc Pull/chunked reads + object_buffer_pool.cc."""
        oid: ObjectID = payload["object_id"]
        max_inline = payload.get("max_inline")
        entry = self.memory_store.get_entry(oid)
        if entry is None:
            return {"status": "not_found"}
        if entry.serialized is None and entry.in_plasma:
            if max_inline is not None:
                view = await asyncio.to_thread(
                    self.plasma.get_raw_view, oid) if self.plasma else None
                if view is None:
                    return {"status": "not_found"}
                if view.nbytes > max_inline:
                    return {"status": "chunked", "size": view.nbytes}
                # serve from the already-pinned view (a second
                # get_serialized would redo the store lookup + pin)
                return {"status": "ok",
                        "data": ser.SerializedObject.from_bytes(view)}
            s = await asyncio.to_thread(self._read_local_plasma, oid)
            if s is None:
                return {"status": "not_found"}
            return {"status": "ok", "data": s}
        if entry.serialized is None:
            return {"status": "not_found"}
        s = entry.serialized
        if max_inline is not None:
            size = sum(seg.nbytes if hasattr(seg, "nbytes") else len(seg)
                       for seg in s.wire_segments())
            if size > max_inline:
                return {"status": "chunked", "size": size}
        return {"status": "ok", "data": s}

    async def _handle_fetch_object_chunk(self, payload):
        """One [off, off+length) range of the flat wire payload.

        Copy-free serving: chunks go back as PickleBuffer views — a pinned
        slice of the shm arena, or a zero-copy slice of a memory-store
        resident's wire segments — which the RPC layer's out-of-band
        framing scatters straight to the socket. The arena slice keeps the
        parent view (and through it the GC-tied store ref) alive until the
        reply frame is written."""
        oid: ObjectID = payload["object_id"]
        off, length = payload["off"], payload["len"]
        entry = self.memory_store.get_entry(oid)
        if entry is None:
            return {"status": "not_found"}
        if entry.serialized is None and entry.in_plasma:
            if self.plasma is None:
                return {"status": "not_found"}
            view = await asyncio.to_thread(self.plasma.get_raw_view, oid)
            if view is None:
                return {"status": "not_found"}
            return {"status": "ok",
                    "data": pickle.PickleBuffer(view[off:off + length])}
        if entry.serialized is None:
            return {"status": "not_found"}
        return {"status": "ok",
                "data": pickle.PickleBuffer(_slice_segments(
                    entry.serialized.wire_segments(), off, length))}

    async def _handle_add_object_location(self, payload):
        self.reference_counter.add_location(
            payload["object_id"], payload["location"])
        return True

    async def _handle_drop_object_location(self, payload):
        self.reference_counter.drop_location(
            payload["object_id"], payload["location"])
        return True

    async def _handle_free_objects(self, payload):
        plasma_frees = []
        for oid in payload["object_ids"]:
            entry = self.memory_store.get_entry(oid)
            if entry is not None and entry.in_plasma and self.plasma is not None:
                plasma_frees.append(oid)
        self.memory_store.delete(payload["object_ids"])
        for oid in payload["object_ids"]:
            # raylint: disable=cross-domain-mutation — GIL-atomic set
            # add/discard with no compound invariant across the two
            # sites: free-vs-hold of the same oid is ordered by the
            # owner's ref protocol (free only after all refs dropped)
            self._secondary_copies.discard(oid)
        if plasma_frees:
            def _free():
                for oid in plasma_frees:
                    self.plasma.free(oid)
            await asyncio.to_thread(_free)
        return True

    async def _handle_add_borrower(self, payload):
        self.reference_counter.add_borrower(payload["object_id"], payload["borrower"])
        return True

    async def _handle_remove_borrower(self, payload):
        self.reference_counter.remove_borrower(payload["object_id"], payload["borrower"])
        return True

    async def _handle_reconstruct_object(self, payload):
        return self._try_reconstruct(payload["object_id"])

    @staticmethod
    def _attach_worker_stages(replies, recv: float, shared: bool) -> None:
        """Turn the executor's raw stamps into the reply's `stages` dict
        (worker's own clock — durations only, so the owner can stitch
        them against its stamps with no cross-process clock sync).
        `shared`: the replies share one receive stamp (a batched push), so
        per-reply pack time can't be isolated — later batchmates' waiting
        shows up in their dispatch stage instead."""
        wall = time.monotonic() - recv
        for r in replies:
            if not isinstance(r, dict):
                continue
            started = r.pop("_rt_exec_started", None)
            fn_s = r.pop("_rt_fn_s", None)
            if started is None:
                continue
            dispatch = max(0.0, started - recv)
            execute = fn_s if fn_s is not None else (r.get("exec_s") or 0.0)
            if shared:
                pack = 0.0
            else:
                pack = max(0.0, wall - dispatch - execute)
            r["stages"] = {"dispatch": dispatch, "exec": execute,
                           "pack": pack,
                           "wall": dispatch + execute + pack}

    @staticmethod
    def _record_worker_trace_spans(specs, replies) -> None:
        """Worker-side spans of traced tasks (dispatch + execute), laid
        out on THIS process's wall clock ending at reply time — the
        owner records submit/queue/rpc/reply from its own stamps, so the
        pair covers the whole round trip without clock sync. One `is
        None` check per untraced spec."""
        now = time.time()
        for spec, reply in zip(specs, replies):
            ctx = getattr(spec, "trace_ctx", None)
            if ctx is None or not isinstance(reply, dict):
                continue
            stages = reply.get("stages")
            if not stages:
                continue
            end_exec = now - (stages.get("pack", 0.0) or 0.0)
            execute = stages.get("exec", 0.0) or 0.0
            dispatch = stages.get("dispatch", 0.0) or 0.0
            _tracing.record_span(
                "task.execute", ctx, end_exec - execute, end_exec,
                attrs={"task_id": spec.task_id.hex(),
                       "function": spec.function_name,
                       "status": reply.get("status", "?")})
            _tracing.record_span(
                "task.dispatch", ctx, end_exec - execute - dispatch,
                end_exec - execute,
                attrs={"task_id": spec.task_id.hex()})

    async def _handle_push_task(self, payload):
        recv = time.monotonic()
        spec: TaskSpec = payload["spec"]
        self._record_task_event(spec, "EXECUTING")
        reply = await self.executor.execute(spec)
        self._attach_worker_stages([reply], recv, shared=False)
        self._record_worker_trace_spans([spec], [reply])
        if spec.task_type == TaskType.ACTOR_CREATION_TASK:
            # creation tasks have no owner-side _finalize_task (the GCS
            # pushes them); record completion here or the timeline shows
            # every __init__ as never finishing
            ok = (reply.get("status") == "ok" if isinstance(reply, dict)
                  else True)
            self._record_task_event(spec, "FINISHED" if ok else "FAILED")
        return reply

    async def _handle_push_task_batch(self, payload):
        """Owner-batched normal-task pushes (see _pump): execute serially in
        arrival order, in ONE thread-pool job. If a task retires the worker
        (max_calls), the rest of the batch is returned not_run so the owner
        re-queues it."""
        recv = time.monotonic()
        specs = payload["specs"]
        for spec in specs:
            self._record_task_event(spec, "EXECUTING")
        loop = asyncio.get_event_loop()
        replies = await loop.run_in_executor(
            self.executor._pool, self.executor.execute_batch_sync, specs)
        self._attach_worker_stages(replies, recv, shared=len(specs) > 1)
        self._record_worker_trace_spans(specs, replies)
        return {"replies": replies}

    async def _handle_push_task_w(self, payload):
        """Wire-codec push (hot path): payload is a list of spec tuples,
        the reply a list of wire reply tuples. One spec executes through
        the normal async path; a batch of normal tasks runs serially in
        one thread-pool job; a batch of actor calls dispatches every spec
        concurrently so async/threaded actor semantics hold (ordered
        actors still serialize on the executor's sequencing gate)."""
        recv = time.monotonic()
        specs = [spec_from_wire(t) for t in payload]
        for spec in specs:
            self._record_task_event(spec, "EXECUTING")
        if len(specs) == 1:
            reply = await self.executor.execute(specs[0])
            self._attach_worker_stages([reply], recv, shared=False)
            self._record_worker_trace_spans(specs, [reply])
            return [reply_to_wire(reply)]
        if all(s.task_type == TaskType.NORMAL_TASK for s in specs):
            loop = asyncio.get_event_loop()
            replies = await loop.run_in_executor(
                self.executor._pool, self.executor.execute_batch_sync,
                specs)
            self._attach_worker_stages(replies, recv, shared=True)
            self._record_worker_trace_spans(specs, replies)
            return [reply_to_wire(r) for r in replies]
        creation = self.executor._actor_spec
        if creation is None or (creation.max_concurrency <= 1
                                and not creation.is_asyncio):
            # plain ordered actor: the calls would serialize on the seq
            # gate anyway — run them in ONE pool job (a loop hop per call
            # costs more than a trivial method body)
            loop = asyncio.get_event_loop()
            replies = await loop.run_in_executor(
                self.executor._pool, self.executor.execute_actor_batch_sync,
                specs)
            self._attach_worker_stages(replies, recv, shared=True)
            self._record_worker_trace_spans(specs, replies)
            return [reply_to_wire(r) for r in replies]
        replies = await asyncio.gather(
            *(self.executor.execute(s) for s in specs))
        self._attach_worker_stages(replies, recv, shared=True)
        self._record_worker_trace_spans(specs, replies)
        return [reply_to_wire(r) for r in replies]

    async def _handle_kill_actor(self, payload):
        # kill(no_restart=False) is a crash-style kill: exit NONZERO so
        # the raylet's death report reads unintended and the GCS restart
        # FSM reschedules the actor (max_restarts permitting). A clean
        # exit(0) here would read as intended and strand the actor dead
        # regardless of its restart budget.
        code = 0 if payload.get("no_restart", True) else 1
        threading.Thread(
            target=lambda: (time.sleep(0.05), os._exit(code)), daemon=True
        ).start()
        return True

    async def _handle_cancel_task(self, payload):
        return self.executor.cancel(payload["task_id"], payload.get("force", False))

    async def _handle_exit(self, payload):
        threading.Thread(target=lambda: (time.sleep(0.05), os._exit(0)), daemon=True).start()
        return True

    async def _handle_ping(self, payload):
        return {"status": "ok", "worker_id": self.worker_id.hex(), "pid": os.getpid()}

    async def _handle_profile_cpu(self, payload):
        """Live CPU flamegraph sampling (reference: dashboard py-spy,
        profile_manager.py:83). Runs in a thread so the worker keeps
        serving RPCs while being sampled."""
        from ray_tpu.util.profiling import sample_cpu_profile

        return await asyncio.to_thread(
            sample_cpu_profile,
            float(payload.get("duration_s", 5.0)),
            float(payload.get("interval_ms", 10.0)))

    async def _handle_profile_memory(self, payload):
        from ray_tpu.util.profiling import heap_snapshot

        return await asyncio.to_thread(
            heap_snapshot, int(payload.get("top", 30)),
            bool(payload.get("stop", False)),
            float(payload.get("duration_s", 0.0)))

    async def _handle_profile_device(self, payload):
        """Device-plane phase reports (ISSUE 15): every DeviceStepProfiler
        registered in this worker (train step, decode wave) plus process
        compile/HBM telemetry — fanned out by the raylet for `ray-tpu
        profile --device` and merged with task-stage spans driver-side."""
        from ray_tpu._private import device_profiler

        # to_thread like the cpu/memory handlers: hbm_stats may import
        # jax (seconds on first touch) — never on the RPC loop
        return await asyncio.to_thread(
            device_profiler.snapshot_all,
            int(payload.get("recent", 64)))

    async def _handle_memory_report(self, payload):
        """Cluster memory observability (ISSUE 16): this worker's full
        reference-table snapshot plus memory-store and paged-KV pool
        occupancy — fanned out by the raylet's node_memory_report for
        `ray-tpu memory` / get_cluster_memory. to_thread like the profile
        handlers: the snapshots take component locks and size whole
        payload tables, never on the RPC loop."""
        return await asyncio.to_thread(
            self.memory_report, bool((payload or {}).get("refs", True)))

    def memory_report(self, include_refs: bool = True) -> dict:
        """Memory-observability snapshot of THIS worker. include_refs=False
        is the cheap summary form (counts + store/KV occupancy only) for
        periodic samplers like the dashboard head."""
        from ray_tpu._private import kv_registry

        now = time.time()
        report = {
            "worker_id": self.worker_id.hex(),
            "pid": os.getpid(),
            "mode": self.mode,
            "address": self.address_str,
            "node_id": self.node_id.hex() if self.node_id else None,
            "actor_id": (self.current_actor_id.hex()
                         if self.current_actor_id else None),
            "counts": self.reference_counter.summary(),
            "memory_store": {"objects": self.memory_store.size(),
                             "bytes": self.memory_store.total_bytes()},
            "kv": kv_registry.report_all(),
        }
        if not include_refs:
            return report
        snap = self.reference_counter.snapshot()
        refs = []
        for oid, ref in snap.items():
            entry = self.memory_store.get_entry(oid)
            size = ref.size_bytes
            if not size and entry is not None and entry.serialized is not None:
                size = entry.serialized.total_bytes()
            refs.append({
                "object_id": oid.hex(),
                "kind": "owned" if ref.owned else "borrowed",
                "local_refs": ref.local_refs,
                "submitted_task_refs": ref.submitted_task_refs,
                "pinned": ref.pinned,
                "borrowers": sorted(ref.borrowers),
                "owner_address": getattr(ref.owner_address, "rpc_address",
                                         None),
                "size_bytes": int(size),
                "age_s": max(0.0, now - ref.created_at),
                "location": ref.location,
                "in_plasma": bool(entry is not None and entry.in_plasma),
            })
        report["refs"] = refs
        # Store-resident entries with NO ref in this worker's table. The
        # memory store is process-private, so nothing can ever free an
        # unreferenced entry — the leak detector's orphan candidates.
        # Secondary/primary copies held for remote owners (the executor's
        # hold_secondary_copy) are tracked by the OWNER's ref table, not
        # ours: marked so the sweep checks them against the cluster union
        # instead of flagging them outright.
        unref = []
        for (oid, nbytes, created, in_plasma, freed,
             _is_exc) in self.memory_store.entries_snapshot():
            if freed or oid in snap:
                continue
            unref.append({
                "object_id": oid.hex(),
                "size_bytes": int(nbytes),
                "age_s": max(0.0, now - created),
                "in_plasma": bool(in_plasma),
                "secondary": oid in self._secondary_copies,
            })
        report["unreferenced_entries"] = unref
        return report

    # ---------------------------------------------- generator streaming (owner)
    async def _handle_report_generator_item(self, payload):
        task_id: TaskID = payload["task_id"]
        state = self._generators.get(task_id)
        if state is None:
            return False
        if payload.get("error"):
            with state.cv:
                state.error = payload["item"]["inline"] if payload.get("item") else None
                state.total = state.reported
                state.cv.notify_all()
            return True
        if payload.get("done"):
            self._finish_generator(task_id, payload["index"])
            return True
        index = payload["index"]
        oid = ObjectID.for_task_return(task_id, index + 1)
        # Own/store and publish under the stream's cv: release_generator
        # marks `released` under the same lock before freeing, so an item
        # report racing close() either lands before the release snapshot
        # (and is freed by it) or sees the flag and drops the item —
        # never an owned-but-orphaned object.
        with state.cv:
            if state.released:
                return False
            self.reference_counter.add_owned(oid, self.address)
            self._store_return(oid, payload["item"])
            state.reported = max(state.reported, index + 1)
            state.cv.notify_all()
        return True

    def _finish_generator(self, task_id: TaskID, total: int, error=None):
        state = self._generators.get(task_id)
        if state is None:
            return
        with state.cv:
            state.total = total
            if error is not None:
                state.error = error
            state.cv.notify_all()

    def next_generator_item(self, task_id: TaskID, consumed: int, timeout=None):
        """Blocking: returns the ObjectRef for item `consumed`, or None at end."""
        state = self._generators.get(task_id)
        if state is None:
            return None
        with state.cv:
            # End-of-stream requires total set AND all items reported: the
            # task-completion reply (which carries total) travels on a
            # different channel than item reports and may arrive first.
            state.cv.wait_for(
                lambda: state.reported > consumed
                or state.error is not None
                or (state.total is not None and state.reported >= state.total),
                timeout,
            )
            if state.reported > consumed:
                oid = ObjectID.for_task_return(task_id, consumed + 1)
                return ObjectRef(oid, owner_address=self.address)
            if state.error is not None:
                err, _ = ser.deserialize(state.error)
                state.released = True
                reported = state.reported
                self._generators.pop(task_id, None)
                # items reported past the consumer's cursor were owned at
                # report time and have no other holder — free them, or an
                # errored/cancelled stream leaks them (same cleanup as
                # release_generator, for the next()-observes-error path)
                self._free_unconsumed_generator_items(
                    task_id, consumed, reported)
                self._raise_stored_error(err)
            self._generators.pop(task_id, None)
            return None

    def _free_unconsumed_generator_items(self, task_id: TaskID,
                                         consumed: int,
                                         reported: int) -> None:
        for index in range(consumed, reported):
            oid = ObjectID.for_task_return(task_id, index + 1)
            if self.reference_counter.owns(oid):
                self.reference_counter.add_local_ref(oid)
                self.reference_counter.remove_local_ref(oid)

    def release_generator(self, task_id: TaskID, consumed: int) -> None:
        """Drop an abandoned stream's owner-side state
        (ObjectRefGenerator.close): the _generators entry, plus the
        reported-but-unconsumed return objects — they were add_owned with
        zero local refs when the executor reported them, so nothing else
        will ever free them. A ref-pair bump routes through the reference
        counter's normal zero-count path (which also clears the memory
        store / plasma copy); items the consumer DID take stay alive
        through the consumer's own ObjectRef."""
        state = self._generators.pop(task_id, None)
        if state is None:
            return
        with state.cv:
            state.released = True  # in-flight item reports drop their item
            reported = state.reported
            if state.total is None:
                state.total = reported  # unblock any parked consumer
            state.cv.notify_all()
        self._free_unconsumed_generator_items(task_id, consumed, reported)

    def report_generator_item(self, spec: TaskSpec, index: int, item, done: bool,
                              error: bool = False):
        """Executor-side: stream one yielded item to the owner."""
        owner = spec.owner_address
        client = self._peers.get(owner.rpc_address)
        try:
            if _fi.PLAN is not None:
                # `mid_stream` lifecycle point: a chaos plan can kill/drop/
                # delay this worker between generator items — the replica-
                # dies-mid-decode scenario serve.llm failover is tested
                # against. Inside the try: an injected ConnectionLost must
                # take the SAME OwnerDiedError translation as a real
                # owner-connection failure, not surface as a novel
                # application error no production path can produce.
                act = _fi.intercept_sync(
                    _fi.SITE_MID_STREAM, method=spec.function_name,
                    label=self.mode, peer=owner.rpc_address)
                if act == "drop":
                    return  # this item report is lost in flight
            client.send(
                "report_generator_item",
                {"task_id": spec.task_id, "index": index, "item": item,
                 "done": done, "error": error},
            )
        except ConnectionLost:
            raise exc.OwnerDiedError(spec.task_id.hex())

    # --------------------------------------------------------- ref counting
    def register_deserialized_ref(self, ref: ObjectRef):
        oid = ref.object_id()
        owner = ref.owner_address
        first = self.reference_counter.add_borrowed(oid, owner)
        self.reference_counter.add_local_ref(oid)
        if first and owner is not None and owner.rpc_address != self.address_str:
            # Fire-and-forget: may run on the RPC loop thread mid-decode, so
            # it must never block on the loop.
            client = self._peers.get(owner.rpc_address)
            self._fire(
                client.send_async(
                    "add_borrower", {"object_id": oid, "borrower": self.address_str}
                )
            )

    def _notify_owner_release(self, oid: ObjectID, owner_address):
        self.memory_store.delete([oid])
        if owner_address is None or owner_address.rpc_address == self.address_str:
            return
        client = self._peers.get(owner_address.rpc_address)
        self._fire(
            client.send_async(
                "remove_borrower", {"object_id": oid, "borrower": self.address_str}
            )
        )

    def _free_owned_object(self, oid: ObjectID, locations):
        # Runs from arbitrary contexts — including ON the submission event
        # loop (ref drops in _on_task_reply when a task's last plasma arg
        # dies). Every outbound notification here must therefore be
        # fire-and-forget: one blocking raylet/peer RPC from the loop
        # thread wedges the entire actor-task transport (the long-poll
        # starvation bug this replaced).
        entry = self.memory_store.get_entry(oid)
        self.memory_store.delete([oid])
        if (entry is not None and entry.in_plasma and self.plasma is not None
                and (entry.plasma_node is None or self.node_id is None
                     or entry.plasma_node == self.node_id.hex())):
            self.plasma.free_local(oid)
            if self._raylet is not None:
                self._fire(self._raylet.send_async(
                    "free_spilled", {"object_ids": [oid]}))
        if isinstance(locations, str):  # tolerate old single-location form
            locations = [locations]
        for location in locations or []:
            if location == self.address_str:
                continue
            try:
                self._fire(self._peers.get(location).send_async(
                    "free_objects", {"object_ids": [oid]}))
            except ConnectionLost:
                pass

    def free_objects(self, refs: List[ObjectRef]):
        """Manual eviction (reference: internal_api.free)."""
        for ref in refs:
            oid = ref.object_id()
            locs = self.reference_counter.get_all_locations(oid)
            entry = self.memory_store.get_entry(oid)
            self.memory_store.mark_freed(oid)
            if (entry is not None and entry.in_plasma
                    and self.plasma is not None
                    and (entry.plasma_node is None or self.node_id is None
                         or entry.plasma_node == self.node_id.hex())):
                self.plasma.free(oid)
            for loc in locs:
                try:
                    self._peers.get(loc).send("free_objects", {"object_ids": [oid]})
                except ConnectionLost:
                    pass

    def hold_secondary_copy(self, oid: ObjectID):
        self._secondary_copies.add(oid)

    # ------------------------------------------------------------- executor glue
    def become_actor(self, creation: ActorCreationSpec):
        self.is_actor_worker = True
        self.current_actor_id = creation.actor_id
        # pin the actor's job as this process's own: submissions from
        # async-actor coroutines / user threads have no _task_ctx, and the
        # nil fallback would mis-attribute them (and escape job cleanup)
        self.job_id = creation.actor_id.job_id()
        self._gcs.call(
            "report_actor_alive",
            {"actor_id": creation.actor_id, "address": self.address, "pid": os.getpid()},
        )

    def exit_actor_process(self, intended: bool = True):
        # 1s margin so the terminating call's reply flushes before the hard
        # exit even on a loaded worker (matches max_calls retirement).
        threading.Thread(
            target=lambda: (time.sleep(1.0), os._exit(0 if intended else 1)),
            daemon=True,
        ).start()

    # ------------------------------------------------------------ futures API
    def as_future(self, ref: ObjectRef):
        import concurrent.futures

        fut: concurrent.futures.Future = concurrent.futures.Future()

        def _resolve():
            try:
                fut.set_result(self.get([ref])[0])
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)

        threading.Thread(target=_resolve, daemon=True).start()
        return fut

    def as_asyncio_future(self, ref: ObjectRef):
        loop = asyncio.get_event_loop()
        afut = loop.create_future()

        def _resolve():
            try:
                value = self.get([ref])[0]
                loop.call_soon_threadsafe(
                    lambda: afut.set_result(value) if not afut.done() else None
                )
            except BaseException as e:  # noqa: BLE001
                loop.call_soon_threadsafe(
                    lambda: afut.set_exception(e) if not afut.done() else None
                )

        threading.Thread(target=_resolve, daemon=True).start()
        return afut

    def on_completed(self, ref: ObjectRef, callback):
        def _cb(entry):
            callback(ref)

        self.memory_store.add_callback(ref.object_id(), _cb)

    # ------------------------------------------------------------ task events
    def _record_task_event(self, spec: TaskSpec, state: str,
                           stages: Optional[dict] = None):
        # Hot path (2+ calls per task): append a small tuple of scalars —
        # NOT the spec itself, which pins inline arg payloads (up to 100KB
        # each) for the life of the bounded deque. Dict formatting happens
        # once per flush batch in _flush_task_events. `stages` rides only
        # on terminal events (the per-stage latency breakdown).
        # raylint: disable=cross-domain-mutation — lock-free SPSC deque:
        # deque.append/popleft are atomic, producers append here from any
        # thread, and the flusher daemon is the ONLY consumer (popleft in
        # _format_task_events) — the documented threading-free pattern
        self._task_events.append(
            (spec.task_id, spec.function_name, spec.task_type.name,
             spec.job_id, state, time.time(), spec.trace_parent, stages,
             self._spec_trace_id(spec)))
        ev = self._task_events_wakeup
        if ev is not None:
            ev.set()  # plain threading.Event: no loop interaction here

    def _task_event_flush_loop(self):
        """Daemon flusher thread: the RPC loop's only involvement is the
        actual send coroutine — formatting a 1s batch (thousands of dict
        builds at serving rates) happens HERE, off the loop, where it
        used to stall every in-flight reply for milliseconds once per
        second (the r05 HTTP p99 regression)."""
        ev = self._task_events_wakeup
        while not self._shutdown:
            if not self._task_events:
                ev.wait()  # idle workers: zero periodic wakeups
            ev.clear()
            if self._shutdown:
                return
            time.sleep(1.0)  # batch window (same flush latency)
            self._flush_task_events_sync()

    def _format_task_events(self, limit: int = 5000) -> list:
        """Drain up to `limit` raw task-event tuples into wire dicts
        (flusher thread / teardown only — never the RPC loop)."""
        node = self.node_id.hex() if self.node_id else None
        worker = self.worker_id.hex()
        events = []
        while self._task_events and len(events) < limit:
            task_id, name, type_name, job_id, state, ts, parent, \
                stages, trace_id = self._task_events.popleft()
            ev = {
                "task_id": task_id.hex(),
                "name": name,
                "type": type_name,
                "state": state,
                "parent": parent,
                "job_id": job_id.hex() if job_id else None,
                "node": node,
                "worker_id": worker,
                "time": ts,
                "trace_id": trace_id,
            }
            if stages is not None:
                ev["stages"] = stages
            events.append(ev)
        return events

    def _flush_task_events_sync(self, deadline_s: float = 10.0):
        # Drain FULLY in 5000-event sends: a single capped send per second
        # falls behind batched submission rates (>5k events/s) and the
        # bounded deque would silently drop the overflow.
        deadline = time.monotonic() + deadline_s
        while self._task_events:
            events = self._format_task_events()
            if not events:
                return
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return
            coro = self._gcs.send_async(
                "add_task_events", {"events": events})
            try:
                self._lt.submit(coro).result(timeout=remaining)
            # NB: Future.result raises concurrent.futures.TimeoutError,
            # which is NOT the builtin TimeoutError until Python 3.11 —
            # catching only the builtin would kill the flusher thread on
            # the first slow GCS send
            except (ConnectionLost, OSError, TimeoutError,
                    concurrent.futures.TimeoutError):
                return
            except RuntimeError:  # loop closed mid-teardown
                coro.close()  # suppress the never-awaited warning
                return


class _RetryGet(Exception):
    pass
