"""Task/actor execution inside a worker process.

Role of the reference's execution half of CoreWorker
(ray: src/ray/core_worker/core_worker.cc:2883 ExecuteTask, :3455
HandlePushTask; Python callback _raylet.pyx:2253 task_execution_handler) plus
the server-side actor scheduling queues
(transport/actor_scheduling_queue.cc — per-caller sequence-number ordering —
and concurrency_group_manager.cc for async/threaded actors).

Returns policy (matches the reference): small results are inlined in the
PushTaskReply back to the owner; large results stay in this worker's store and
the reply carries a location marker. Streaming-generator items are reported to
the owner one by one (report_generator_item) as they are yielded.
"""

from __future__ import annotations

import asyncio
import ctypes
import logging
import os
import threading
import time
import traceback
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private import backoff as _backoff
from ray_tpu._private import deadlines as _deadlines
from ray_tpu._private import tracing as _tracing
from ray_tpu._private.config import CONFIG
from ray_tpu._private.ids import ActorID, ObjectID, TaskID
from ray_tpu._private import serialization as ser
from ray_tpu._private.specs import Address, TaskArg, TaskSpec, TaskType
from ray_tpu.exceptions import (
    AsyncioActorExit,
    DeadlineExceededError,
    RayTaskError,
    TaskCancelledError,
)

logger = logging.getLogger(__name__)


def _async_raise(thread_id: int, exc_type) -> bool:
    """Inject an exception into a running thread (cancellation support,
    mirrors the reference's cancellation-by-KeyboardInterrupt in
    _raylet.pyx execute_task_with_cancellation_handler)."""
    res = ctypes.pythonapi.PyThreadState_SetAsyncExc(
        ctypes.c_ulong(thread_id), ctypes.py_object(exc_type)
    )
    return res == 1


class _SequencingGate:
    """Starts actor tasks in per-caller sequence order."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._next_seq: Dict[bytes, int] = {}

    def wait_turn(self, caller: bytes, seq: int):
        with self._cv:
            expected = self._next_seq.setdefault(caller, 0)
            if seq < expected:
                return  # replay after restart; let it run
            self._cv.wait_for(lambda: self._next_seq.get(caller, 0) >= seq, timeout=60)

    def advance(self, caller: bytes, seq: int):
        with self._cv:
            cur = self._next_seq.setdefault(caller, 0)
            if seq + 1 > cur:
                self._next_seq[caller] = seq + 1
            self._cv.notify_all()


class Executor:
    def __init__(self, core_worker):
        self.cw = core_worker
        self._fn_cache: Dict[str, Any] = {}
        self._pool = ThreadPoolExecutor(max_workers=256, thread_name_prefix="rt-exec")
        self.actor_instance: Any = None
        self.actor_id: Optional[ActorID] = None
        self._actor_spec = None
        self._seq_gate = _SequencingGate()
        self._actor_semaphore: Optional[threading.Semaphore] = None
        self._async_loop: Optional[asyncio.AbstractEventLoop] = None
        self._running_threads: Dict[TaskID, int] = {}  # task -> thread ident
        self._cancelled: set = set()
        self._env_context = None  # applied RuntimeEnvContext (sticky)
        self._calls_by_function: Dict[str, int] = {}  # max_calls counting
        self._recycle_lock = threading.Lock()  # guards the 2 fields above/below:
        # the executor pool has many threads; even though leases serialize
        # tasks one-at-a-time today, the retire bookkeeping must not depend
        # on that implicit invariant.
        self._retiring = False  # set when max_calls is reached
        # Per-TASK retire flag: thread-local, NOT an instance field — with a
        # shared field a concurrent max_calls=0 task could clobber the flag
        # between this task's pre-execution set and its return packaging.
        self._task_tls = threading.local()

    def _apply_runtime_env(self, env: dict) -> None:
        from ray_tpu import runtime_env as re_mod

        self._env_context = (
            re_mod.setup_runtime_env(env, self.cw.kv_get) or True)

    # ------------------------------------------------------------------ entry
    async def execute(self, spec: TaskSpec) -> dict:
        """Run on the worker's RPC loop; dispatches to a thread and returns
        the PushTaskReply payload."""
        loop = asyncio.get_event_loop()
        if spec.runtime_env and self._env_context is None:
            # Apply once; workers are dedicated per env hash (the scheduling
            # key includes it), so env state never mixes across tasks.
            try:
                await loop.run_in_executor(
                    self._pool, self._apply_runtime_env, spec.runtime_env)
            except Exception as e:  # noqa: BLE001 — surface as task error
                from ray_tpu.exceptions import RuntimeEnvSetupError

                err = (e if isinstance(e, RuntimeEnvSetupError)
                       else RuntimeEnvSetupError(str(e)))
                return self._error_reply(spec, err)
        if spec.task_type == TaskType.ACTOR_TASK:
            return await loop.run_in_executor(self._pool, self._run_actor_task, spec)
        if spec.task_type == TaskType.ACTOR_CREATION_TASK:
            return await loop.run_in_executor(self._pool, self._run_actor_creation, spec)
        reply = await loop.run_in_executor(
            self._pool, self._run_normal_task, spec)
        if self._retiring:
            # tell the owner to drop this lease (max_calls recycling)
            reply["worker_retiring"] = True
        return reply

    def execute_batch_sync(self, specs) -> list:
        """Blocking batch execution for owner-batched normal-task pushes —
        runs in ONE thread-pool job (an event-loop hop per task would cost
        more than a noop task itself). Returns one reply per spec; specs
        after a worker-retiring task are returned {"not_run": True}."""
        replies = []
        retired = False
        for spec in specs:
            if retired:
                replies.append({"not_run": True})
                continue
            if spec.runtime_env and self._env_context is None:
                try:
                    self._apply_runtime_env(spec.runtime_env)
                except Exception as e:  # noqa: BLE001 — surface as task error
                    from ray_tpu.exceptions import RuntimeEnvSetupError

                    err = (e if isinstance(e, RuntimeEnvSetupError)
                           else RuntimeEnvSetupError(str(e)))
                    replies.append(self._error_reply(spec, err))
                    continue
            reply = self._run_normal_task(spec)
            if self._retiring:
                reply["worker_retiring"] = True
                retired = True
            replies.append(reply)
        return replies

    def execute_actor_batch_sync(self, specs) -> list:
        """Blocking batch execution for owner-batched ORDERED actor calls:
        they serialize on the sequencing gate regardless, so one pool job
        running them in seq order avoids a loop+thread hop per call."""
        return [self._run_actor_task(spec) for spec in specs]

    def cancel(self, task_id: TaskID, force: bool) -> bool:
        self._cancelled.add(task_id)
        ident = self._running_threads.get(task_id)
        if ident is not None:
            return _async_raise(ident, TaskCancelledError)
        return True

    # ---------------------------------------------------------------- helpers
    def _load_function(self, function_id: str, blob=None):
        fn = self._fn_cache.get(function_id)
        if fn is None:
            data = (blob if blob is not None
                    else self.cw.kv_get(b"fun:" + function_id.encode()))
            if data is None:
                raise RuntimeError(f"function {function_id} not found in GCS")
            fn = ser.loads_function(data)
            self._fn_cache[function_id] = fn
        return fn

    def _resolve_args(
        self, args: List[TaskArg], kwargs: Dict[str, TaskArg]
    ) -> Tuple[list, dict]:
        # Gather by-reference args and fetch them in one batch.
        ref_ids, ref_owners = [], []
        for a in list(args) + list(kwargs.values()):
            if not a.is_inline:
                ref_ids.append(a.object_id)
                ref_owners.append(a.owner_address)
        fetched = {}
        if ref_ids:
            values = self.cw.get_objects_by_id(ref_ids, ref_owners, timeout=None)
            fetched = dict(zip(ref_ids, values))

        def materialize(a: TaskArg):
            if a.is_inline:
                value, _refs = ser.deserialize(a.data)
                return value
            return fetched[a.object_id]

        return [materialize(a) for a in args], {
            k: materialize(a) for k, a in kwargs.items()
        }

    def _package_returns(
        self, spec: TaskSpec, result: Any
    ) -> List[Tuple[ObjectID, dict]]:
        return_ids = spec.return_ids()
        if spec.num_returns <= 1:
            values = [result]
        else:
            values = list(result)
            if len(values) != spec.num_returns:
                raise ValueError(
                    f"task {spec.function_name} declared num_returns="
                    f"{spec.num_returns} but returned {len(values)} values"
                )
        out = []
        for oid, value in zip(return_ids, values):
            out.append((oid, self._package_value(
                oid, value, recipient=spec.owner_address)))
        return out

    def _pre_register_return_borrows(self, s, recipient) -> None:
        """Close the return-borrow race: an ObjectRef serialized into a
        RETURN value loses its last local ref the moment the task frame
        exits, and the recipient's eager one-way add_borrower may arrive
        AFTER this (owner) worker already freed the object — a borrowed
        ref from a task return would then be flaky by design. Registering
        the recipient as a borrower HERE, synchronously, before the value
        leaves the process, keeps every contained owned ref alive until
        the recipient releases it (remove_borrower on its last local ref)
        or dies (the owner drops dead borrowers wholesale). A recipient
        that never deserializes the value holds the borrow until death —
        the price of not piggybacking registration on replies like the
        reference does.

        Refs this worker merely BORROWS (forwarding: a queue actor
        handing an owned-elsewhere ref onward) have the same race one
        hop removed — this worker's own borrow is released by GC the
        moment the value leaves its heap, and the owner may free before
        the recipient's async registration lands. For those this worker
        PINS the ref with an extra local ref (extending its own borrow,
        which the owner already honors) and registers the recipient
        asynchronously; the pin is released only when that registration
        completes, so the owner always sees add(recipient) strictly
        before remove(this worker) — without ever blocking reply
        packaging on a possibly-hung owner (a partitioned owner must
        not stall every reply this actor sends)."""
        if recipient is None or not s.contained_refs:
            return
        addr = getattr(recipient, "rpc_address", None)
        if addr is None or addr == self.cw.address.rpc_address:
            return  # self-call: local refcounts already cover it
        for ref in s.contained_refs:
            oid = ref.object_id()
            if self.cw.reference_counter.owns(oid):
                self.cw.reference_counter.add_borrower(oid, addr)
                continue
            owner = ref.owner_address
            owner_addr = getattr(owner, "rpc_address", None)
            if owner_addr is None or owner_addr in (
                    addr, self.cw.address.rpc_address):
                # unknown owner (the ref is doomed regardless), the
                # recipient IS the owner (its local counts cover it), or
                # a self-owned ref already handled above
                continue
            self._register_forward_borrow(oid, owner_addr, addr)

    def _register_forward_borrow(self, oid: ObjectID, owner_addr: str,
                                 borrower_addr: str) -> None:
        """Pin `oid` locally, register `borrower_addr` with the owner
        async, release the pin when the registration settles (success or
        failure — a dead owner means the ref is already doomed)."""
        rc = self.cw.reference_counter
        rc.add_local_ref(oid)

        def _release(fut):
            try:
                fut.result()
            except Exception:  # noqa: BLE001 — owner gone: ref doomed
                logger.debug("forward-borrow registration with %s failed "
                             "for %s", owner_addr, oid.hex(), exc_info=True)
            rc.remove_local_ref(oid)

        try:
            client = self.cw._peers.get(owner_addr)
            fut = asyncio.run_coroutine_threadsafe(
                client.call_async("add_borrower",
                                  {"object_id": oid,
                                   "borrower": borrower_addr},
                                  timeout=30.0),
                self.cw._lt.loop)
            fut.add_done_callback(_release)
        except Exception:  # noqa: BLE001 — loop shutting down
            rc.remove_local_ref(oid)
            logger.debug("forward-borrow registration submit failed "
                         "for %s", oid.hex(), exc_info=True)

    def _attach_retained_borrows(self, spec: TaskSpec, reply: dict) -> None:
        """The other half of the borrow protocol, for ARGS: a ref nested
        in a task argument whose owner is the SUBMITTER races the same
        way returns do — the submitter's frame-exit free (its local ref
        plus the submitted-task pin both drop when this reply lands) can
        beat this worker's eager first-contact add_borrower. The reply
        therefore reports every nested arg ref this worker RETAINED
        (e.g. a sample-queue actor that stored the entry), and the owner
        registers the borrow synchronously BEFORE releasing its pins
        (core_worker._register_reply_borrows). A ref retained here but
        dropped later is cleaned by the normal remove_borrower path."""
        kwarg_specs = getattr(spec, "kwarg_specs", {}) or {}
        nested = [nid
                  for a in list(spec.args) + list(kwarg_specs.values())
                  for nid in a.nested_ids]
        if not nested:
            return
        owner_addr = getattr(spec.owner_address, "rpc_address", None)
        if owner_addr is None or owner_addr == self.cw.address.rpc_address:
            return  # self-call: local refcounts already cover it
        held = [oid for oid in nested
                if self.cw.reference_counter.holds_borrow(oid)]
        if held:
            reply["retained_borrows"] = held
            reply["borrower_address"] = self.cw.address.rpc_address

    def _package_value(self, oid: ObjectID, value: Any,
                       recipient=None) -> dict:
        s = ser.serialize(value)
        self._pre_register_return_borrows(s, recipient)
        if s.total_bytes() <= CONFIG.max_direct_call_object_size:
            return {"inline": s}
        # Keep the primary copy on this node; the owner records the location.
        # Preferred home is the node shm store (same-node readers map it
        # zero-copy; the raylet can spill it); fall back to this worker's
        # memory store when the shm store is absent/full.
        plasma_node = None
        if (self.cw.plasma is not None
                and self.cw.plasma.put_serialized(oid, s, primary=True)):
            plasma_node = self.cw.node_id.hex() if self.cw.node_id else ""
            self.cw.memory_store.put_serialized(
                oid, None, value=value, in_plasma=True,
                plasma_node=plasma_node)
        elif getattr(self._task_tls, "will_retire", False):
            # max_calls: this worker exits right after the reply — a
            # memory-store primary copy would die with it, so ship the
            # value inline (the shm store, when available above, survives
            # the worker: it lives in the raylet).
            return {"inline": s}
        else:
            self.cw.memory_store.put_serialized(oid, s, value=value)
        self.cw.hold_secondary_copy(oid)
        return {"location": self.cw.address.rpc_address,
                "plasma_node": plasma_node, "size": s.total_bytes()}

    def _deadline_reply(self, spec: TaskSpec) -> dict:
        """Queue-pop doomed-work elimination on the worker: the spec's
        deadline passed while it waited for this thread (sequencing gate,
        concurrency semaphore, pool backlog). The caller gets a typed
        DeadlineExceededError; no ERROR-channel broadcast — an expired
        deadline is the caller's own budget, not an application fault."""
        trace_id = _tracing.trace_id_of(spec)
        self.cw._elog.emit(
            "task.deadline_expired", task_id=spec.task_id.hex(),
            trace_id=trace_id, layer="worker",
            function=spec.function_name)
        _backoff.count_deadline_expired("worker")
        _tracing.force_trace(trace_id, "task.deadline_expired:worker")
        err = DeadlineExceededError(
            f"deadline for {spec.function_name} passed before execution "
            "started", layer="worker", deadline=spec.deadline_s)
        return {
            "status": "error",
            "error_str": str(err),
            "is_application_error": True,
            "error": ser.serialize(err),
            "return_ids": spec.return_ids(),
        }

    def _error_reply(self, spec: TaskSpec, exc: BaseException) -> dict:
        if spec.trace_ctx is not None:
            # tail-keep from the failing side too: generator errors reach
            # the owner via item reports, not this reply
            _tracing.force_trace(spec.trace_ctx[0],
                                 f"task_error:{type(exc).__name__}")
        if isinstance(exc, RayTaskError):
            err = exc
        else:
            err = RayTaskError.from_exception(spec.function_name, exc)
        # stream the failure to subscribed drivers (ERROR pubsub channel) —
        # fire-and-forget, the reply below is the authoritative path
        self.cw.report_error(spec, exc)
        s = ser.serialize(err)
        return {
            "status": "error",
            "error_str": str(exc),
            "is_application_error": True,
            "error": s,
            "return_ids": spec.return_ids(),
        }

    # ---------------------------------------------------------- normal tasks
    def _run_normal_task(self, spec: TaskSpec) -> dict:
        t0 = time.monotonic()
        reply = self._run_normal_task_inner(spec)
        self._attach_retained_borrows(spec, reply)
        # worker-measured execution time: the owner's push-batching gate
        # needs task duration EXCLUDING network RTT (an RTT-inclusive
        # sample would lock remote owners out of batching forever)
        reply["exec_s"] = time.monotonic() - t0
        # when this pool thread picked the task up — the push handler
        # turns it into the 'dispatch' stage of the latency breakdown
        reply.setdefault("_rt_exec_started", t0)
        return reply

    def _run_normal_task_inner(self, spec: TaskSpec) -> dict:
        if spec.task_id in self._cancelled:
            return {
                "status": "cancelled",
                "return_ids": spec.return_ids(),
            }
        if _deadlines.expired(spec.deadline_s):
            return self._deadline_reply(spec)
        token = self.cw.enter_task_context(spec)
        self._running_threads[spec.task_id] = threading.get_ident()
        limit = getattr(spec, "max_calls", 0)
        with self._recycle_lock:
            # CLAIM the call slot now (not at completion): two concurrent
            # tasks of a max_calls=N function must not both read the same
            # pre-increment count, or the one that actually reaches the
            # limit would skip the inline-return path below and lose its
            # result when the worker exits. Packaging uses the flag to avoid
            # leaving a primary copy in the about-to-exit memory store.
            n = self._calls_by_function.get(spec.function_id, 0) + 1
            self._calls_by_function[spec.function_id] = n
            self._task_tls.will_retire = bool(limit) and n >= limit
        try:
            fn = self._load_function(spec.function_id)
            args, kwargs = self._resolve_args(spec.args, getattr(spec, "kwarg_specs", {}) or {})
            if spec.is_streaming_generator():
                return self._run_generator(spec, fn, args, kwargs)
            fn_t0 = time.monotonic()
            result = fn(*args, **kwargs)
            fn_s = time.monotonic() - fn_t0
            return {"status": "ok", "_rt_fn_s": fn_s,
                    "returns": self._package_returns(spec, result)}
        except TaskCancelledError:
            return {"status": "cancelled", "return_ids": spec.return_ids()}
        except BaseException as e:  # noqa: BLE001 — errors are data here
            return self._error_reply(spec, e)
        finally:
            self._running_threads.pop(spec.task_id, None)
            self.cw.exit_task_context(token)
            self._maybe_recycle_worker(spec)

    def _maybe_recycle_worker(self, spec: TaskSpec) -> None:
        """max_calls worker recycling (reference: @ray.remote(max_calls=) —
        the worker exits after N executions of the function, e.g. to release
        leaked memory/accelerator state; the raylet spawns a fresh one)."""
        limit = getattr(spec, "max_calls", 0)
        if not limit:
            return
        with self._recycle_lock:
            # the call slot was already claimed pre-execution; the tls flag
            # says whether THIS task was the one that reached the limit
            if not getattr(self._task_tls, "will_retire", False) \
                    or self._retiring:
                return
            self._retiring = True  # reply carries worker_retiring (execute)
        logger.info("worker reached max_calls=%d for %s; exiting",
                    limit, spec.function_name)
        # Delayed exit so the in-flight task reply flushes first (the
        # reply is small — large returns go to the shm store, see
        # _package_value — so 1s is orders of magnitude above local
        # socket flush time). The owner drops the lease on seeing the
        # flag, so no new task races the exit.
        threading.Thread(
            target=lambda: (time.sleep(1.0), os._exit(0)),
            daemon=True,
        ).start()

    def _run_generator(self, spec: TaskSpec, fn, args, kwargs) -> dict:
        """Streaming generator: report each item to the owner as produced."""
        gen = None
        trace_ctx = spec.trace_ctx
        span_cap = CONFIG.trace_max_stream_spans if trace_ctx is not None \
            else 0
        try:
            gen = fn(*args, **kwargs)
            index = 0
            t_prev = time.time() if trace_ctx is not None else 0.0
            for item in gen:
                oid = ObjectID.for_task_return(spec.task_id, index + 1)
                payload = self._package_value(
                    oid, item, recipient=spec.owner_address)
                self.cw.report_generator_item(spec, index, payload, done=False)
                if index < span_cap:
                    # per-chunk spans (decode steps for serve.llm): each
                    # covers produce->reported; capped — a long stream's
                    # tail adds volume, not shape
                    t_now = time.time()
                    _tracing.record_span(
                        "task.stream_item", trace_ctx, t_prev, t_now,
                        attrs={"task_id": spec.task_id.hex(),
                               "index": index})
                    t_prev = t_now
                index += 1
            self.cw.report_generator_item(spec, index, None, done=True)
            return {"status": "ok", "returns": [], "streaming_num_items": index}
        except TaskCancelledError:
            # consumer-initiated close (ObjectRefGenerator.close →
            # cancel_task): not an application error — no ERROR-channel
            # broadcast, a plain cancelled reply. Still finish the stream
            # so any racing next_generator_item waiter wakes.
            err = RayTaskError.from_exception(
                spec.function_name, TaskCancelledError(spec.task_id))
            self.cw.report_generator_item(
                spec, -1, {"inline": ser.serialize(err)}, done=True, error=True
            )
            return {"status": "cancelled", "return_ids": spec.return_ids()}
        except BaseException as e:  # noqa: BLE001
            err = RayTaskError.from_exception(spec.function_name, e)
            oid = ObjectID.for_task_return(spec.task_id, 1)
            self.cw.report_generator_item(
                spec, -1, {"inline": ser.serialize(err)}, done=True, error=True
            )
            return self._error_reply(spec, e)
        finally:
            # Cancellation can land between yields (the injected
            # TaskCancelledError hits report_generator_item, not the user
            # frame): close the user generator EXPLICITLY so its cleanup
            # (e.g. an LLM engine releasing the request's slot) runs now,
            # not at a GC of unknown timing.
            close = getattr(gen, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:  # noqa: BLE001 — teardown must not mask
                    logger.debug("generator close failed", exc_info=True)

    # ---------------------------------------------------------------- actors
    def _run_actor_creation(self, spec: TaskSpec) -> dict:
        # companion lines to the ctor phases (core_worker.__init__): the
        # cpu delta start→created is the creation-task execution cost; the
        # ctor-phases→start gap is main-loop bring-up + task receive
        from ray_tpu._private.spawn_diag import spawn_timing_write

        spawn_timing_write("creation_start")
        token = self.cw.enter_task_context(spec)
        try:
            creation = spec.actor_creation
            cls = self._load_function(spec.function_id,
                                      getattr(spec, 'function_blob', None))
            args, kwargs = self._resolve_args(spec.args, getattr(spec, "kwarg_specs", {}) or {})
            self.actor_instance = cls(*args, **kwargs)
            self.actor_id = creation.actor_id
            self._actor_spec = creation
            if creation.max_concurrency > 1 or creation.is_asyncio:
                self._actor_semaphore = threading.Semaphore(creation.max_concurrency)
            if creation.is_asyncio:
                self._start_async_loop()
            self.cw.become_actor(creation)
            spawn_timing_write("created")
            return {"status": "ok", "returns": []}
        except BaseException as e:  # noqa: BLE001
            return self._error_reply(spec, e)
        finally:
            self.cw.exit_task_context(token)

    def _start_async_loop(self):
        loop = asyncio.new_event_loop()
        self._async_loop = loop
        t = threading.Thread(target=loop.run_forever, name="rt-actor-asyncio", daemon=True)
        t.start()

    def _run_actor_task(self, spec: TaskSpec) -> dict:
        if spec.method_name == "__ray_terminate__":
            self.cw.exit_actor_process(intended=True)
            return {"status": "ok", "returns": []}
        if spec.method_name == "__rt_pipeline_loop__":
            # Compiled-DAG stage loop (dag/compiled_channels.py): args are
            # (loop_fn, *loop_args); the loop gets the LIVE actor instance
            # and runs until its channels close. It occupies the actor's
            # ordered queue on purpose — a compiled pipeline dedicates its
            # actors (reference: compiled_dag_node.py actor loops).
            token = self.cw.enter_task_context(spec)
            try:
                if self.actor_instance is None:
                    raise RuntimeError("actor instance not initialized")
                args, kwargs = self._resolve_args(
                    spec.args, getattr(spec, "kwarg_specs", {}) or {})
                result = args[0](self.actor_instance, *args[1:], **kwargs)
                return {"status": "ok",
                        "returns": self._package_returns(spec, result)}
            except BaseException as e:  # noqa: BLE001
                return self._error_reply(spec, e)
            finally:
                self.cw.exit_task_context(token)
        caller = spec.owner_address.worker_id.binary() if spec.owner_address else b""
        creation = self._actor_spec
        ordered = creation is None or (
            creation.max_concurrency <= 1 and not creation.is_asyncio
        )
        if ordered:
            self._seq_gate.wait_turn(caller, spec.sequence_number)
        # execution time EXCLUDING the gate wait, reported to the owner:
        # its push batcher must classify methods by what they actually
        # cost, not by how long they queued behind earlier calls
        exec_started = time.monotonic()
        reply = self._run_actor_body(spec, caller, ordered)
        if isinstance(reply, dict):
            self._attach_retained_borrows(spec, reply)
            reply["exec_s"] = time.monotonic() - exec_started
            # dispatch stage = recv -> here; for ordered actors that
            # includes the sequencing-gate wait, which IS dispatch queueing
            reply.setdefault("_rt_exec_started", exec_started)
        return reply

    def _run_actor_body(self, spec: TaskSpec, caller: bytes,
                        ordered: bool) -> dict:
        try:
            if self.actor_instance is None:
                raise RuntimeError("actor instance not initialized")
            method = getattr(self.actor_instance, spec.method_name)
            token = self.cw.enter_task_context(spec)
            self._running_threads[spec.task_id] = threading.get_ident()
            if self._actor_semaphore is not None:
                self._actor_semaphore.acquire()
            try:
                if _deadlines.expired(spec.deadline_s):
                    # queue-pop drop AFTER the sequencing-gate/semaphore
                    # wait (that wait IS the actor's dispatch queue); the
                    # finally blocks still advance the gate, so a dropped
                    # call can't wedge later sequence numbers
                    return self._deadline_reply(spec)
                args, kwargs = self._resolve_args(
                    spec.args, getattr(spec, "kwarg_specs", {}) or {}
                )
                if spec.is_streaming_generator():
                    return self._run_generator(spec, method, args, kwargs)
                if self._async_loop is not None and asyncio.iscoroutinefunction(method):
                    fut = asyncio.run_coroutine_threadsafe(
                        method(*args, **kwargs), self._async_loop
                    )
                    result = fut.result()
                else:
                    result = method(*args, **kwargs)
                return {"status": "ok", "returns": self._package_returns(spec, result)}
            finally:
                if self._actor_semaphore is not None:
                    self._actor_semaphore.release()
                self._running_threads.pop(spec.task_id, None)
                self.cw.exit_task_context(token)
        except (AsyncioActorExit, SystemExit):
            self.cw.exit_actor_process(intended=True)
            # resolve the terminating call's ref(s) with None — empty
            # returns would leave the caller's get() hanging forever
            if spec.is_streaming_generator():
                return {"status": "ok", "returns": [],
                        "streaming_num_items": 0}
            n = max(spec.num_returns, 1)
            value = None if spec.num_returns <= 1 else tuple([None] * n)
            return {"status": "ok",
                    "returns": self._package_returns(spec, value)}
        except TaskCancelledError:
            return {"status": "cancelled", "return_ids": spec.return_ids()}
        except BaseException as e:  # noqa: BLE001
            return self._error_reply(spec, e)
        finally:
            if ordered:
                self._seq_gate.advance(caller, spec.sequence_number)

    def shutdown(self):
        self._pool.shutdown(wait=False, cancel_futures=True)
        if self._async_loop is not None:
            self._async_loop.call_soon_threadsafe(self._async_loop.stop)
