"""Per-worker in-memory object store.

Role of the reference's CoreWorkerMemoryStore
(ray: src/ray/core_worker/store_provider/memory_store/memory_store.h:43):
holds inlined task returns, `put` values and borrower-side caches, with
blocking and async waiters. Entries are serialized payloads plus a lazily
cached deserialized value (zero-copy buffers preserved end to end).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Set

from ray_tpu._private.ids import ObjectID
from ray_tpu._private.serialization import SerializedObject


_SENTINEL = object()


@dataclass
class StoreEntry:
    serialized: Optional[SerializedObject] = None
    value: Any = _SENTINEL          # cached deserialized value
    is_exception: bool = False
    # object is not here; it lives at this worker address (secondary copy holder)
    location: Optional[str] = None
    freed: bool = False
    # payload lives in a node-local shm store (plasma); when the holder is a
    # remote node, plasma_node says which node's store has the primary copy.
    in_plasma: bool = False
    plasma_node: Optional[str] = None
    # wall time the entry landed — ages memory-report rows and lets the
    # leak detector skip freshly-stored entries mid-registration
    created_at: float = field(default_factory=time.time)


class MemoryStore:
    def __init__(self):
        self._entries: Dict[ObjectID, StoreEntry] = {}
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        # async waiters: object_id -> list of callbacks (called off-lock)
        self._callbacks: Dict[ObjectID, List[Callable[[StoreEntry], None]]] = {}

    def put_serialized(
        self,
        object_id: ObjectID,
        serialized: Optional[SerializedObject],
        *,
        value: Any = _SENTINEL,
        is_exception: bool = False,
        location: Optional[str] = None,
        in_plasma: bool = False,
        plasma_node: Optional[str] = None,
    ) -> None:
        entry = StoreEntry(
            serialized=serialized,
            value=value,
            is_exception=is_exception,
            location=location,
            in_plasma=in_plasma,
            plasma_node=plasma_node,
        )
        with self._lock:
            self._entries[object_id] = entry
            cbs = self._callbacks.pop(object_id, [])
            self._cv.notify_all()
        for cb in cbs:
            cb(entry)

    def mark_freed(self, object_id: ObjectID) -> None:
        entry = StoreEntry(freed=True)
        with self._lock:
            self._entries[object_id] = entry
            cbs = self._callbacks.pop(object_id, [])
            self._cv.notify_all()
        for cb in cbs:
            cb(entry)

    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            return object_id in self._entries

    def get_entry(self, object_id: ObjectID) -> Optional[StoreEntry]:
        with self._lock:
            return self._entries.get(object_id)

    def cache_value(self, object_id: ObjectID, value: Any) -> None:
        with self._lock:
            entry = self._entries.get(object_id)
            if entry is not None:
                entry.value = value

    def wait_entry(self, object_id: ObjectID, timeout: Optional[float]) -> Optional[StoreEntry]:
        """Block until the object is present (or timeout). Returns the entry."""
        with self._lock:
            if object_id in self._entries:
                return self._entries[object_id]
            self._cv.wait_for(lambda: object_id in self._entries, timeout)
            return self._entries.get(object_id)

    def add_callback(self, object_id: ObjectID, cb: Callable[[StoreEntry], None]) -> bool:
        """Invoke cb(entry) when the object arrives. Returns True if already
        present (cb invoked synchronously)."""
        with self._lock:
            entry = self._entries.get(object_id)
            if entry is None:
                self._callbacks.setdefault(object_id, []).append(cb)
                return False
        cb(entry)
        return True

    def delete(self, object_ids) -> None:
        with self._lock:
            for oid in object_ids:
                self._entries.pop(oid, None)

    def ready_ids(self, object_ids) -> Set[ObjectID]:
        with self._lock:
            return {oid for oid in object_ids if oid in self._entries}

    def size(self) -> int:
        with self._lock:
            return len(self._entries)

    def total_bytes(self) -> int:
        with self._lock:
            return sum(
                e.serialized.total_bytes()
                for e in self._entries.values()
                if e.serialized is not None
            )

    def entries_snapshot(self) -> List[tuple]:
        """(object_id, bytes, created_at, in_plasma, freed, is_exception)
        per entry — the memory_report RPC's store-resident view (sizes
        computed under the lock; the caller formats off-lock)."""
        with self._lock:
            return [
                (oid,
                 e.serialized.total_bytes() if e.serialized is not None else 0,
                 e.created_at, e.in_plasma, e.freed, e.is_exception)
                for oid, e in self._entries.items()
            ]
