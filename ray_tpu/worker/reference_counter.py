"""Distributed reference counting (ownership model).

Role of the reference's ReferenceCounter
(ray: src/ray/core_worker/reference_count.h:59-61, .cc ~1.7k LoC): every
object has exactly one owner — the worker that created it (task submitter for
returns, putter for puts). The owner tracks:
  - its own local Python refcount (ObjectRef __init__/__del__ hooks),
  - the number of pending submitted tasks using the ref as an argument,
  - the set of remote borrowers (workers that deserialized the ref),
  - lineage: the TaskSpec that produced the object (for reconstruction).
Borrowers track local counts and notify the owner on first borrow / last
release. When all counts reach zero the owner frees the object everywhere.

Simplification vs the reference: borrower registration is an eager one-way
message at first deserialization instead of being piggybacked on task replies;
nested-borrow forwarding (a borrower passing the ref onward) is handled by the
new holder registering with the owner directly.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional, Set

from ray_tpu._private.ids import ObjectID

logger = logging.getLogger(__name__)


@dataclass
class Reference:
    owned: bool = False
    owner_address: Optional[object] = None  # Address
    local_refs: int = 0
    submitted_task_refs: int = 0
    borrowers: Set[str] = field(default_factory=set)  # worker rpc addresses
    # Where the primary (large-object) copy lives, if not inline at the owner.
    location: Optional[str] = None
    # Additional full-copy holders (chunked-fetch receivers that registered
    # back) — extra pull sources and broadcast fan-out points.
    locations: Set[str] = field(default_factory=set)
    lineage_task = None     # TaskSpec that produces this object (owned only)
    pinned: bool = False    # e.g. detached-actor handles, named refs
    freed: bool = False
    # Memory observability (`ray-tpu memory` / memory_report RPC): payload
    # size when the tracker saw it (0 = unknown, e.g. a remote return not
    # yet fetched) and the wall time the entry was created — age drives
    # the leak detector's over-age pin/borrow verdicts.
    size_bytes: int = 0
    created_at: float = field(default_factory=time.time)


class ReferenceCounter:
    def __init__(
        self,
        free_callback: Callable[[ObjectID, Optional[str]], None],
        notify_owner_release: Callable[[ObjectID, object], None],
    ):
        """free_callback(object_id, locations: list): owner-side, actually
        frees the primary and every registered replica.
        notify_owner_release(object_id, owner_address): borrower-side."""
        self._refs: Dict[ObjectID, Reference] = {}
        self._lock = threading.RLock()
        self._free_cb = free_callback
        self._notify_release = notify_owner_release

    # ---- registration -------------------------------------------------------

    def add_owned(self, object_id: ObjectID, owner_address, *, lineage_task=None,
                  location: Optional[str] = None, initial_local_refs: int = 0):
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is None:
                ref = Reference(owned=True, owner_address=owner_address)
                self._refs[object_id] = ref
            ref.owned = True
            ref.owner_address = owner_address
            if lineage_task is not None:
                ref.lineage_task = lineage_task
            if location is not None:
                ref.location = location
            ref.local_refs += initial_local_refs

    def add_borrowed(self, object_id: ObjectID, owner_address) -> bool:
        """Register knowledge of a non-owned ref. Returns True if this is the
        first time (caller should notify the owner)."""
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is None:
                self._refs[object_id] = Reference(owned=False, owner_address=owner_address)
                return True
            if ref.owner_address is None:
                ref.owner_address = owner_address
            return False

    def set_location(self, object_id: ObjectID, location: Optional[str]):
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is not None:
                ref.location = location

    def get_location(self, object_id: ObjectID) -> Optional[str]:
        with self._lock:
            ref = self._refs.get(object_id)
            return ref.location if ref else None

    def add_location(self, object_id: ObjectID, location: str):
        """A chunked-fetch receiver now holds a full copy."""
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is not None and location != ref.location:
                ref.locations.add(location)

    def drop_location(self, object_id: ObjectID, location: str):
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is not None:
                ref.locations.discard(location)

    def get_all_locations(self, object_id: ObjectID) -> list:
        """Primary first, then replicas (pull sources, in preference order)."""
        with self._lock:
            ref = self._refs.get(object_id)
            return [] if ref is None else self._locations_of(ref)

    def get_lineage(self, object_id: ObjectID):
        with self._lock:
            ref = self._refs.get(object_id)
            return ref.lineage_task if ref else None

    def pin(self, object_id: ObjectID):
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is not None:
                ref.pinned = True

    def set_size(self, object_id: ObjectID, size_bytes: int):
        """Record the payload size for the memory report (put / stored
        return paths — borrowers learn it from their fetched copy)."""
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is not None:
                ref.size_bytes = int(size_bytes)

    # ---- local count hooks (from ObjectRef lifecycle) -----------------------

    def add_local_ref(self, object_id: ObjectID):
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is None:
                ref = Reference()
                self._refs[object_id] = ref
            ref.local_refs += 1

    def remove_local_ref(self, object_id: ObjectID):
        self._decrement(object_id, "local_refs")

    def add_submitted_task_ref(self, object_id: ObjectID):
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is None:
                ref = Reference()
                self._refs[object_id] = ref
            ref.submitted_task_refs += 1

    def remove_submitted_task_ref(self, object_id: ObjectID):
        self._decrement(object_id, "submitted_task_refs")

    def _decrement(self, object_id: ObjectID, attr: str):
        to_free = None
        notify = None
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is None:
                return
            setattr(ref, attr, max(0, getattr(ref, attr) - 1))
            if ref.local_refs == 0 and ref.submitted_task_refs == 0 and not ref.pinned:
                if ref.owned:
                    if not ref.borrowers and not ref.freed:
                        ref.freed = True
                        to_free = (object_id, self._locations_of(ref))
                        del self._refs[object_id]
                else:
                    notify = (object_id, ref.owner_address)
                    del self._refs[object_id]
        if to_free is not None:
            try:
                self._free_cb(*to_free)
            except Exception:
                logger.exception("free callback failed")
        if notify is not None and notify[1] is not None:
            try:
                self._notify_release(*notify)
            except Exception:  # noqa: BLE001 — owner may already be gone
                logger.debug("borrow-release notification failed",
                             exc_info=True)

    @staticmethod
    def _locations_of(ref: Reference) -> list:
        out = [] if ref.location is None else [ref.location]
        out.extend(sorted(ref.locations - {ref.location}))
        return out

    # ---- borrower bookkeeping (owner side) ----------------------------------

    def add_borrower(self, object_id: ObjectID, borrower_address: str):
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is None or not ref.owned:
                return
            ref.borrowers.add(borrower_address)

    def remove_borrower(self, object_id: ObjectID, borrower_address: str):
        to_free = None
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is None or not ref.owned:
                return
            ref.borrowers.discard(borrower_address)
            if (
                ref.local_refs == 0
                and ref.submitted_task_refs == 0
                and not ref.borrowers
                and not ref.pinned
                and not ref.freed
            ):
                ref.freed = True
                to_free = (object_id, self._locations_of(ref))
                del self._refs[object_id]
        if to_free is not None:
            try:
                self._free_cb(*to_free)
            except Exception:
                logger.exception("free callback failed")

    def remove_borrower_everywhere(self, borrower_address: str):
        """A borrower process died: drop it from every owned ref."""
        with self._lock:
            ids = [oid for oid, r in self._refs.items() if borrower_address in r.borrowers]
        for oid in ids:
            self.remove_borrower(oid, borrower_address)

    # ---- introspection ------------------------------------------------------

    def get_owner_address(self, object_id: ObjectID):
        with self._lock:
            ref = self._refs.get(object_id)
            return ref.owner_address if ref else None

    def holds_borrow(self, object_id: ObjectID) -> bool:
        """True when this worker currently BORROWS the object (not owner)
        and still pins it with a local or submitted-task ref — i.e. the
        executor retained a nested arg ref past the task body and must
        report it in the reply (executor._attach_retained_borrows)."""
        with self._lock:
            ref = self._refs.get(object_id)
            return (ref is not None and not ref.owned
                    and (ref.local_refs > 0 or ref.submitted_task_refs > 0))

    def owns(self, object_id: ObjectID) -> bool:
        with self._lock:
            ref = self._refs.get(object_id)
            return bool(ref and ref.owned)

    def num_tracked(self) -> int:
        with self._lock:
            return len(self._refs)

    def summary(self) -> dict:
        with self._lock:
            return {
                "num_refs": len(self._refs),
                "num_owned": sum(1 for r in self._refs.values() if r.owned),
                "num_borrowed": sum(1 for r in self._refs.values() if not r.owned),
                "num_pinned": sum(1 for r in self._refs.values() if r.pinned),
                "tracked_bytes": sum(r.size_bytes for r in self._refs.values()),
            }

    def snapshot(self) -> dict:
        """object_id -> Reference copy (for `ray memory` / state API)."""
        import copy

        with self._lock:
            return {oid: copy.copy(ref) for oid, ref in self._refs.items()}
