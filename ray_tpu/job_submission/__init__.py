"""Job submission: run driver scripts on the cluster and track them.

Reference: ray dashboard/modules/job — `JobSubmissionClient`
(dashboard/modules/job/sdk.py:39: submit_job/stop_job/get_job_status/
get_job_info/list_jobs/get_job_logs/tail_job_logs), `JobManager`
(job_manager.py:56) running each driver as a subprocess under a
`JobSupervisor` actor (job_supervisor.py:49) with log capture.

Design here: one detached named JobManager actor per cluster (created
lazily, get_if_exists) hosts the supervisors; each submitted job is a
subprocess of that actor's worker with RT_ADDRESS injected so the
entrypoint's ray_tpu.init() joins the cluster. Logs stream to a per-job
file served back through the actor.
"""

from __future__ import annotations

import enum
import os
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional

JOB_MANAGER_NAME = "_rt_job_manager"
JOB_MANAGER_NAMESPACE = "_rt_internal"
_JOB_ID_ENV = "RT_JOB_SUBMISSION_ID"


class JobStatus(str, enum.Enum):
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    STOPPED = "STOPPED"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"

    def is_terminal(self) -> bool:
        return self in (JobStatus.STOPPED, JobStatus.SUCCEEDED,
                        JobStatus.FAILED)


@dataclass
class JobDetails:
    submission_id: str
    entrypoint: str
    status: JobStatus = JobStatus.PENDING
    message: str = ""
    metadata: Dict[str, str] = field(default_factory=dict)
    runtime_env: Dict[str, Any] = field(default_factory=dict)
    start_time: Optional[float] = None
    end_time: Optional[float] = None
    driver_exit_code: Optional[int] = None


class _JobManager:
    """Actor body. Runs driver subprocesses and tracks their lifecycle."""

    def __init__(self, log_dir: str):
        import subprocess  # noqa: F401  (bound at call time)

        self._log_dir = log_dir
        os.makedirs(log_dir, exist_ok=True)
        self._jobs: Dict[str, JobDetails] = {}
        self._procs: Dict[str, Any] = {}

    def submit(self, entrypoint: str, submission_id: str,
               runtime_env: Optional[dict], metadata: Optional[dict]) -> str:
        import subprocess

        if submission_id in self._jobs:
            raise ValueError(f"job {submission_id} already exists")
        details = JobDetails(
            submission_id=submission_id,
            entrypoint=entrypoint,
            runtime_env=runtime_env or {},
            metadata=metadata or {},
        )
        env = dict(os.environ)
        import ray_tpu

        cw = ray_tpu._raylet.get_core_worker()
        env["RT_ADDRESS"] = cw.gcs_address
        env[_JOB_ID_ENV] = submission_id
        renv = runtime_env or {}
        env.update({str(k): str(v)
                    for k, v in (renv.get("env_vars") or {}).items()})
        cwd = None
        if renv.get("working_dir"):
            cwd = renv["working_dir"]
        logpath = self._log_path(submission_id)
        logfile = open(logpath, "ab")
        try:
            proc = subprocess.Popen(
                entrypoint, shell=True, stdout=logfile,
                stderr=subprocess.STDOUT, env=env, cwd=cwd,
                start_new_session=True,
            )
        except OSError as e:
            details.status = JobStatus.FAILED
            details.message = str(e)
            self._jobs[submission_id] = details
            return submission_id
        details.status = JobStatus.RUNNING
        details.start_time = time.time()
        details.message = "Job is currently running."
        self._jobs[submission_id] = details
        self._procs[submission_id] = proc
        return submission_id

    def _log_path(self, submission_id: str) -> str:
        return os.path.join(self._log_dir, f"job-{submission_id}.log")

    def _refresh(self, submission_id: str) -> None:
        details = self._jobs.get(submission_id)
        proc = self._procs.get(submission_id)
        if details is None or proc is None or details.status.is_terminal():
            return
        code = proc.poll()
        if code is None:
            return
        details.end_time = time.time()
        details.driver_exit_code = code
        if code == 0:
            details.status = JobStatus.SUCCEEDED
            details.message = "Job finished successfully."
        elif details.status != JobStatus.STOPPED:
            details.status = JobStatus.FAILED
            details.message = f"Driver exited with code {code}."
        self._procs.pop(submission_id, None)

    def status(self, submission_id: str) -> Optional[JobDetails]:
        self._refresh(submission_id)
        return self._jobs.get(submission_id)

    def list(self) -> List[JobDetails]:
        for sid in list(self._jobs):
            self._refresh(sid)
        return list(self._jobs.values())

    def stop(self, submission_id: str) -> bool:
        self._refresh(submission_id)
        details = self._jobs.get(submission_id)
        proc = self._procs.get(submission_id)
        if details is None or details.status.is_terminal() or proc is None:
            return False
        details.status = JobStatus.STOPPED
        details.message = "Job was intentionally stopped."
        details.end_time = time.time()
        try:
            import signal

            os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            pass
        return True

    def logs(self, submission_id: str, offset: int = 0) -> str:
        try:
            with open(self._log_path(submission_id), "rb") as f:
                if offset:
                    f.seek(offset)
                return f.read().decode(errors="replace")
        except OSError:
            return ""

    def logs_from(self, submission_id: str, offset: int = 0):
        """-> (text, end_byte_offset) from ONE read, so the end offset is
        exactly where this read stopped (no lost bytes between calls, no
        decode-length skew)."""
        try:
            with open(self._log_path(submission_id), "rb") as f:
                if offset:
                    f.seek(offset)
                raw = f.read()
            return raw.decode(errors="replace"), offset + len(raw)
        except OSError:
            return "", offset


def _manager_handle():
    import ray_tpu
    from ray_tpu._private.config import CONFIG

    cls = ray_tpu.remote(_JobManager)
    return cls.options(
        name=JOB_MANAGER_NAME,
        namespace=JOB_MANAGER_NAMESPACE,
        lifetime="detached",
        get_if_exists=True,
    ).remote(os.path.join(CONFIG.log_dir, "jobs"))


class JobSubmissionClient:
    """SDK + CLI face (reference: dashboard/modules/job/sdk.py:39).

    `address` is either the cluster GCS address (or None for the ambient
    connection) — actor-backed mode — or an `http(s)://` dashboard URL,
    which talks to the dashboard's REST job API without joining the
    cluster (the reference's only mode)."""

    def __init__(self, address: Optional[str] = None):
        self._http: Optional[str] = None
        if address and address.startswith(("http://", "https://")):
            self._http = address.rstrip("/")
            self._mgr = None
            return
        import ray_tpu

        if not ray_tpu.is_initialized():
            ray_tpu.init(address=address or os.environ.get("RT_ADDRESS"))
        self._mgr = _manager_handle()

    # -- REST transport ------------------------------------------------------

    def _rest(self, method: str, path: str, body: Optional[dict] = None):
        import json as _json
        import urllib.error
        import urllib.request

        data = _json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            self._http + path, data=data, method=method,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=60) as r:
                return _json.loads(r.read() or b"null")
        except urllib.error.HTTPError as e:
            if e.code == 404:
                raise RuntimeError(
                    f"Job not found ({path}).") from None
            raise

    @staticmethod
    def _details_from_json(d: dict) -> JobDetails:
        return JobDetails(
            submission_id=d["submission_id"], entrypoint=d["entrypoint"],
            status=JobStatus(d["status"]), message=d.get("message", ""),
            metadata=d.get("metadata") or {},
            runtime_env=d.get("runtime_env") or {},
            start_time=d.get("start_time"), end_time=d.get("end_time"),
            driver_exit_code=d.get("driver_exit_code"))

    # -- API -----------------------------------------------------------------

    def submit_job(self, *, entrypoint: str,
                   submission_id: Optional[str] = None,
                   runtime_env: Optional[dict] = None,
                   metadata: Optional[dict] = None) -> str:
        sid = submission_id or f"raysubmit_{uuid.uuid4().hex[:12]}"
        if self._http:
            return self._rest("POST", "/api/jobs", {
                "entrypoint": entrypoint, "submission_id": sid,
                "runtime_env": runtime_env, "metadata": metadata,
            })["submission_id"]
        import ray_tpu

        return ray_tpu.get(self._mgr.submit.remote(
            entrypoint, sid, runtime_env, metadata))

    def get_job_status(self, submission_id: str) -> JobStatus:
        details = self.get_job_info(submission_id)
        return details.status

    def get_job_info(self, submission_id: str) -> JobDetails:
        if self._http:
            return self._details_from_json(
                self._rest("GET", f"/api/jobs/{submission_id}"))
        import ray_tpu

        details = ray_tpu.get(self._mgr.status.remote(submission_id))
        if details is None:
            raise RuntimeError(f"Job {submission_id} does not exist.")
        return details

    def list_jobs(self) -> List[JobDetails]:
        if self._http:
            return [self._details_from_json(d)
                    for d in self._rest("GET", "/api/jobs/")]
        import ray_tpu

        return ray_tpu.get(self._mgr.list.remote())

    def stop_job(self, submission_id: str) -> bool:
        if self._http:
            return self._rest(
                "POST", f"/api/jobs/{submission_id}/stop")["stopped"]
        import ray_tpu

        return ray_tpu.get(self._mgr.stop.remote(submission_id))

    def get_job_logs(self, submission_id: str, offset: int = 0) -> str:
        if self._http:
            return self._rest(
                "GET",
                f"/api/jobs/{submission_id}/logs?offset={offset}")["logs"]
        import ray_tpu

        return ray_tpu.get(self._mgr.logs.remote(submission_id, offset))

    def _logs_from(self, submission_id: str, offset: int):
        """-> (new_text, end_byte_offset); both modes fetch only the tail
        and the offset comes from the same single read that produced the
        text (no window for lost bytes)."""
        if self._http:
            out = self._rest(
                "GET", f"/api/jobs/{submission_id}/logs?offset={offset}")
            return out["logs"], out.get(
                "total_len", offset + len(out["logs"]))
        import ray_tpu

        return ray_tpu.get(self._mgr.logs_from.remote(submission_id, offset))

    def get_job_logs_from(self, submission_id: str, offset: int = 0):
        """Public tail API: -> (text, end_byte_offset)."""
        return self._logs_from(submission_id, offset)

    def tail_job_logs(self, submission_id: str,
                      poll_interval_s: float = 0.5) -> Iterator[str]:
        """Yield log increments until the job reaches a terminal state."""
        offset = 0
        while True:
            new, offset_new = self._logs_from(submission_id, offset)
            if new:
                yield new
            offset = offset_new
            status = self.get_job_status(submission_id)
            if status.is_terminal():
                new, _ = self._logs_from(submission_id, offset)
                if new:
                    yield new
                return
            time.sleep(poll_interval_s)
