"""Public exception taxonomy.

Mirrors the reference's taxonomy (ray: python/ray/exceptions.py — RayTaskError
:96, RayActorError :287, ActorDiedError :326, ActorUnavailableError :402,
ObjectStoreFullError :446, OutOfDiskError :463, OutOfMemoryError :483,
NodeDiedError :499, ObjectLostError :511, ObjectFetchTimedOutError,
OwnerDiedError :624, ObjectReconstructionFailed* :663-705, GetTimeoutError
:727, RuntimeEnvSetupError :748, placement-group errors :767-775) so user code
can migrate by renaming the import.
"""

from __future__ import annotations

import traceback
from typing import Optional


class RayTpuError(Exception):
    """Base class for all framework errors."""


class CrossLanguageError(RayTpuError):
    pass


class TaskCancelledError(RayTpuError):
    def __init__(self, task_id=None, error_message: str = ""):
        self.task_id = task_id
        super().__init__(error_message or f"Task {task_id} was cancelled")


class RayTaskError(RayTpuError):
    """Wraps an exception raised inside a remote task.

    Re-raised on `get` at the caller with the remote traceback attached; the
    `cause` is the original user exception (reference: exceptions.py:96
    as_instanceof_cause behavior is approximated by exposing `.cause`).
    """

    def __init__(
        self,
        function_name: str = "",
        traceback_str: str = "",
        cause: Optional[BaseException] = None,
        *,
        label: str = "task",
    ):
        self.function_name = function_name
        self.traceback_str = traceback_str
        self.cause = cause
        super().__init__(self._format())

    def _format(self) -> str:
        msg = f"{type(self).__name__}: error in remote {self.function_name}"
        if self.traceback_str:
            msg += "\n\nRemote traceback:\n" + self.traceback_str
        return msg

    @classmethod
    def from_exception(cls, function_name: str, exc: BaseException) -> "RayTaskError":
        tb = "".join(traceback.format_exception(type(exc), exc, exc.__traceback__))
        return cls(function_name=function_name, traceback_str=tb, cause=exc)

    def as_instanceof_cause(self) -> BaseException:
        """Return an exception that isinstance-checks as the cause's type."""
        cause = self.cause
        if cause is None or isinstance(cause, RayTaskError):
            return self
        try:
            cls = type(
                "RayTaskError(" + type(cause).__name__ + ")",
                (RayTaskError, type(cause)),
                {},
            )
            err = cls(self.function_name, self.traceback_str, cause)
            return err
        except TypeError:
            return self


class RayActorError(RayTpuError):
    """The actor died or is unreachable (reference: exceptions.py:287)."""

    def __init__(self, actor_id=None, error_message: str = ""):
        self.actor_id = actor_id
        super().__init__(error_message or f"Actor {actor_id} is dead or unreachable")


class ActorDiedError(RayActorError):
    """The actor died — tasks to it will never succeed (reference :326)."""


class ActorUnavailableError(RayActorError):
    """The actor is temporarily unreachable (restarting); retry may succeed
    (reference :402)."""


class ActorPlacementGroupRemoved(RayActorError):
    """The placement group the actor was scheduled in was removed (ref :767)."""


class TaskPlacementGroupRemoved(RayTpuError):
    """The placement group the task was scheduled in was removed (ref :775)."""


class ObjectStoreFullError(RayTpuError):
    """The local object store is out of memory (reference :446)."""


class OutOfDiskError(RayTpuError):
    """Spilling failed: local disk is full (reference :463)."""


class OutOfMemoryError(RayTpuError):
    """A worker was killed by the memory monitor (reference :483)."""


class NodeDiedError(RayTpuError):
    """The node running the task died (reference :499)."""


class ObjectLostError(RayTpuError):
    """An object is unavailable: all copies were lost (reference :511)."""

    def __init__(self, object_ref_hex: str = "", owner_address=None, call_site: str = ""):
        self.object_ref_hex = object_ref_hex
        self.owner_address = owner_address
        super().__init__(f"Object {object_ref_hex} is lost: all copies unavailable.")


class ObjectFetchTimedOutError(ObjectLostError):
    pass


class ObjectFreedError(ObjectLostError):
    """The object was manually freed (reference :604)."""

    def __init__(self, object_ref_hex: str = ""):
        self.object_ref_hex = object_ref_hex
        Exception.__init__(self, f"Object {object_ref_hex} was manually freed.")


class OwnerDiedError(ObjectLostError):
    """The owner process died, so the object's metadata is gone (reference :624)."""

    def __init__(self, object_ref_hex: str = ""):
        self.object_ref_hex = object_ref_hex
        Exception.__init__(
            self, f"Owner of object {object_ref_hex} died; object cannot be retrieved."
        )


class ObjectReconstructionFailedError(ObjectLostError):
    """Lineage reconstruction failed (reference :663)."""


class ObjectReconstructionFailedMaxAttemptsExceededError(ObjectReconstructionFailedError):
    """Reconstruction exceeded max task retries (reference :683)."""


class ObjectReconstructionFailedLineageEvictedError(ObjectReconstructionFailedError):
    """Lineage needed for reconstruction was evicted (reference :705)."""


class GetTimeoutError(RayTpuError, TimeoutError):
    """`get` timed out (reference :727)."""


class DeadlineExceededError(RayTpuError, TimeoutError):
    """The task's deadline passed before it produced a result.

    Raised for work dropped at queue-pop (doomed-work elimination: the
    raylet lease queue, the worker executor, and the owner's submit pump
    all drop already-expired specs) and for work whose caller-supplied
    budget (`.options(deadline_s=...)`, serve's `X-Request-Deadline`
    header) ran out. Maps to HTTP 504 at the serve proxy. Never
    retried: a deadline is a promise to the caller, not a transient."""

    status_code = 504

    def __init__(self, error_message: str = "", *, layer: str = "",
                 deadline: Optional[float] = None):
        self.layer = layer
        self.deadline = deadline
        super().__init__(
            error_message
            or f"Task deadline exceeded (dropped at layer={layer or '?'})")


class RetryLaterError(RayTpuError):
    """Typed pushback from a bounded queue: the request was refused (not
    queued, not executed) and may be retried after `retry_after_s`.

    Raised by the raylet lease queue, the GCS actor-creation queue and
    the per-actor owner-side mailbox when full. Internal submitters pace
    resubmission with AIMD (_private/backoff.AIMDPacer); user-facing
    surfaces translate it to HTTP 503 + Retry-After. The work is
    accounted SHED (`ray_tpu_shed_total{layer=...}`), never lost."""

    status_code = 503

    def __init__(self, error_message: str = "", *,
                 retry_after_s: float = 1.0, layer: str = ""):
        self.retry_after_s = retry_after_s
        self.layer = layer
        super().__init__(
            error_message
            or f"Queue full at layer={layer or '?'}; "
               f"retry after {retry_after_s:.2f}s")


class RuntimeEnvSetupError(RayTpuError):
    """Creating the runtime environment failed (reference :748)."""

    def __init__(self, error_message: str = ""):
        super().__init__(f"Failed to set up runtime environment: {error_message}")


class RaySystemError(RayTpuError):
    """Internal system error."""


class WorkerCrashedError(RayTpuError):
    """The worker executing a task died unexpectedly (reference:
    exceptions.py WorkerCrashedError)."""


class PendingCallsLimitExceeded(RayTpuError):
    """Actor's pending call queue is full (max_pending_calls exceeded)."""


class AsyncioActorExit(RayTpuError):
    """Internal: raised by exit_actor() inside an async actor."""


__all__ = [
    "RayTpuError",
    "RayTaskError",
    "TaskCancelledError",
    "RayActorError",
    "ActorDiedError",
    "ActorUnavailableError",
    "ActorPlacementGroupRemoved",
    "TaskPlacementGroupRemoved",
    "ObjectStoreFullError",
    "OutOfDiskError",
    "OutOfMemoryError",
    "NodeDiedError",
    "ObjectLostError",
    "ObjectFetchTimedOutError",
    "ObjectFreedError",
    "OwnerDiedError",
    "ObjectReconstructionFailedError",
    "ObjectReconstructionFailedMaxAttemptsExceededError",
    "ObjectReconstructionFailedLineageEvictedError",
    "GetTimeoutError",
    "DeadlineExceededError",
    "RetryLaterError",
    "RuntimeEnvSetupError",
    "RaySystemError",
    "WorkerCrashedError",
    "PendingCallsLimitExceeded",
]
