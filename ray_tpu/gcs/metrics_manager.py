"""GCS metrics manager: hosts the cluster health plane (ISSUE 20).

Assembles the health store + SLO engine behind three RPCs
(``push_metrics`` / ``query_metrics`` / ``get_demand_signals``) plus
the scorecard reads (``get_health`` / ``get_alerts``), and runs the
evaluation loop on the gcs-io event loop.

Ingest paths:

* workers/raylets/dashboard push cumulative registry snapshots (or ad-
  hoc gauge points) via ``push_metrics`` — batched + bounded sender in
  ``health/push.py``;
* the GCS process itself installs a DIRECT push sink (first-wins, so in
  an embedded head the one process-wide pusher is GCS-labeled and ships
  the shared registry exactly once);
* the eval loop self-samples control-plane state that lives outside any
  registry: nodes-alive, the event manager's per-type totals (as the
  ``ray_tpu_events_by_type_total{type}`` counter family the shed /
  deadline / rl-starvation rules watch), and pending placement-group
  bundles. Those series are excluded from this process's registry push
  so they enter the store exactly once, with counter semantics.
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time
from typing import Any, Dict, Optional

from ray_tpu._private.config import CONFIG
from ray_tpu.health import MetricsStore, SloEngine
from ray_tpu.health import demand as health_demand
from ray_tpu.health import push as health_push
from ray_tpu.util import metrics as um

logger = logging.getLogger(__name__)

# control-plane families the eval loop feeds into the store directly;
# they must not ALSO arrive via this process's registry pusher
_SELF_SAMPLED = (
    "ray_tpu_events_by_type_total",
    "ray_tpu_cluster_nodes_alive",
    "ray_tpu_pending_pg_bundles",
)


class GcsMetricsManager:
    """Thread-safe like GcsEventManager: the embedded deployment's
    direct push sink appends from the pusher THREAD while handlers and
    the eval loop run on the gcs-io loop (the store carries the lock)."""

    def __init__(self, node_manager, event_manager):
        self._node_manager = node_manager
        self._event_manager = event_manager
        self.store = MetricsStore()
        self.engine = SloEngine(self.store)
        # "<source>#<pid>" -> last push stats (pushed / dropped / time);
        # written from the pusher thread AND the gcs-io loop
        self._sources: Dict[Any, dict] = {}
        self._sources_lock = threading.Lock()
        # event types whose counter series got a zero-baseline primer
        # (only touched by sample_control_plane on the gcs-io loop)
        self._primed_types: set = set()
        # exposition mirrors of the self-sampled control-plane series, so
        # the health plane's own inputs appear in prometheus_text()
        self._nodes_gauge = um.get_or_create_gauge(
            "ray_tpu_cluster_nodes_alive",
            "Alive raylets registered with the GCS.")
        self._pending_pg_gauge = um.get_or_create_gauge(
            "ray_tpu_pending_pg_bundles",
            "Placement-group bundles waiting for feasible nodes.")
        self._events_gauge = um.get_or_create_gauge(
            "ray_tpu_events_by_type_total",
            "Cluster lifecycle events received by the GCS, by type "
            "(cumulative; exposed as a gauge mirror of the event "
            "manager's counts).", ("type",))
        for name in _SELF_SAMPLED:
            health_push.exclude_prefix(name)
        # first-wins: in an embedded head this makes the GCS the process's
        # single registry pusher; standalone worker/raylet processes
        # install their RPC sinks instead (raylet.py / core_worker.py)
        self._push_token = health_push.set_push_sink(
            self.add_local, "gcs")

    # -- ingest ---------------------------------------------------------------

    def add_local(self, payload: Dict) -> None:
        """Direct sink for the in-process pusher: same path the RPC
        handler takes, minus the wire."""
        source = str(payload.get("source") or "?")
        pid = payload.get("pid")
        t = float(payload.get("time") or time.time())
        snapshot = payload.get("snapshot")
        if snapshot:
            self.store.ingest_snapshot(f"{source}#{pid}", t, snapshot)
        points = payload.get("points")
        if points:
            self.store.ingest_points(f"{source}#{pid}", t, points)
        stats = payload.get("stats")
        if stats is not None:
            with self._sources_lock:
                self._sources[pid] = {"source": source,
                                      "received": time.time(), **stats}
                if len(self._sources) > 512:
                    for p, _ in sorted(
                            self._sources.items(),
                            key=lambda kv: kv[1].get("received", 0.0)
                    )[:len(self._sources) - 512]:
                        self._sources.pop(p, None)

    async def handle_push_metrics(self, payload):
        self.add_local(payload)
        return True

    # -- control-plane self-sampling ------------------------------------------

    def sample_control_plane(self, now: Optional[float] = None) -> None:
        now = now if now is not None else time.time()
        alive = sum(1 for info in self._node_manager._nodes.values()
                    if info.alive)
        self.store.ingest_gauge(now, "ray_tpu_cluster_nodes_alive",
                                None, float(alive))
        self._nodes_gauge.set(float(alive))
        locator = getattr(self._node_manager, "pg_locator", None)
        if locator is not None:
            try:
                pending = len(locator.pending_bundle_shapes())
            except Exception:  # noqa: BLE001 — sampling never breaks eval
                pending = 0
            self.store.ingest_gauge(now, "ray_tpu_pending_pg_bundles",
                                    None, float(pending))
            self._pending_pg_gauge.set(float(pending))
        with self._event_manager._lock:
            counts = dict(self._event_manager._type_counts)
        for etype, count in counts.items():
            # the event manager and this store share the GCS's lifetime,
            # so a type's true pre-history is ZERO — prime the watermark
            # so the FIRST event of a type registers as a delta of 1
            # (the generic baseline rule would swallow it, and a drill's
            # single injected kill would be invisible to rate rules)
            if etype not in self._primed_types:
                # raylint: disable=cross-domain-mutation — only the
                # gcs-io loop's eval_loop calls sample_control_plane;
                # the pusher thread never reaches it
                self._primed_types.add(etype)
                self.store.ingest_counter_absolute(
                    "gcs", now, "ray_tpu_events_by_type_total",
                    {"type": etype}, 0.0)
            self.store.ingest_counter_absolute(
                "gcs", now, "ray_tpu_events_by_type_total",
                {"type": etype}, float(count))
            self._events_gauge.set(float(count), tags={"type": etype})

    async def eval_loop(self) -> None:
        """Runs on the gcs-io loop for the GCS's lifetime (cancelled in
        GcsServer.stop)."""
        while True:
            await asyncio.sleep(max(0.1, CONFIG.health_eval_interval_s))
            try:
                self.sample_control_plane()
                self.engine.evaluate()
            except Exception:  # noqa: BLE001 — the evaluator must never die
                logger.debug("health eval pass failed", exc_info=True)

    # -- queries --------------------------------------------------------------

    async def handle_query_metrics(self, payload):
        return self.store.query(
            name=payload.get("name"),
            tags=payload.get("tags"),
            since=payload.get("since"),
            until=payload.get("until"),
            resolution=payload.get("resolution", "raw"),
            limit_series=int(payload.get("limit_series", 200)))

    async def handle_get_demand_signals(self, payload):
        load = await self._node_manager.handle_get_cluster_load({})
        return health_demand.compute_demand_signals(
            self.store, load, len(self.engine.active_alerts()))

    async def handle_get_alerts(self, payload):
        return {"active": self.engine.active_alerts(),
                "history": self.engine.history()}

    async def handle_get_health(self, payload):
        now = time.time()
        load = await self._node_manager.handle_get_cluster_load({})
        with self._sources_lock:
            sources = {pid: dict(st) for pid, st in self._sources.items()}
        return {
            "time": round(now, 3),
            "scorecard": self.engine.scorecard(now),
            "alerts": self.engine.active_alerts(),
            "demand": health_demand.compute_demand_signals(
                self.store, load, len(self.engine.active_alerts()), now),
            "store": self.store.stats(),
            "push_sources": {
                f"{st.get('source')}#{pid}": {
                    "pushed": st.get("pushed", 0),
                    "dropped": st.get("dropped", 0),
                    "lag_s": max(0.0, now - st.get("received", now)),
                }
                for pid, st in sources.items()
            },
        }

    def stop(self) -> None:
        health_push.clear_push_sink(self._push_token)
