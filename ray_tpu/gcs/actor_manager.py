"""GCS actor lifecycle management.

Role of the reference's GcsActorManager + GcsActorScheduler
(ray: src/ray/gcs/gcs_server/gcs_actor_manager.h:251-281 — the lifecycle FSM
DEPENDENCIES_UNREADY -> PENDING_CREATION -> ALIVE -> (RESTARTING ->
PENDING_CREATION)* -> DEAD — and gcs_actor_scheduler.cc, which leases a
worker from a raylet and pushes the creation task).

Creation is asynchronous: `register_actor` returns immediately; callers learn
the address via the ACTOR pubsub channel or `get_actor_info`.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Dict, List, Optional, Tuple

from ray_tpu._private import backoff as _backoff
from ray_tpu._private import tracing as _tracing
from ray_tpu._private.config import CONFIG
from ray_tpu._private.ids import ActorID, NodeID
from ray_tpu._private.rpc import ClientPool, ConnectionLost
from ray_tpu._private.specs import (
    ActorInfo,
    ActorState,
    Address,
    TaskSpec,
)
from ray_tpu.gcs import pubsub as ps
from ray_tpu._private import event_log

logger = logging.getLogger(__name__)
_elog = event_log.logger_for("gcs")


class GcsActorManager:
    def __init__(self, node_view, publisher: ps.Publisher,
                 client_pool: ClientPool, store=None):
        # node_view: GcsNodeManager (cluster resource view + raylet addresses)
        self._nodes = node_view
        self._pub = publisher
        self._pool = client_pool
        self._store = store
        self._actors: Dict[ActorID, ActorInfo] = {}
        self._creation_specs: Dict[ActorID, TaskSpec] = {}
        # Actors awaiting their FIRST creation (the bounded registration
        # queue; restarts bypass it — they already hold capacity budget).
        self._pending_creation: set = set()
        # traced creations: registration wall time, closed into a
        # gcs.actor_admission span when the worker reports ALIVE (popped
        # there / on DEAD — only traced creations ever enter)
        self._register_wall: Dict[ActorID, float] = {}
        # (namespace, name) -> actor_id
        self._named: Dict[Tuple[str, str], ActorID] = {}
        # node_id -> set of actor ids placed there
        self._by_node: Dict[NodeID, set] = {}
        self._lock = asyncio.Lock()
        self._load_persisted()

    # ---- persistence (reference: gcs_table_storage.cc actor table over the
    # Redis store client; here the append-log store) ------------------------

    def _persist(self, actor_id: ActorID) -> None:
        if self._store is None:
            return
        import pickle

        info = self._actors.get(actor_id)
        if info is None:
            return
        spec = self._creation_specs.get(actor_id)
        self._store.put("actors", actor_id.binary(),
                        pickle.dumps((info, spec), protocol=5))

    def _load_persisted(self) -> None:
        if self._store is None:
            return
        import pickle

        for key in self._store.keys("actors"):
            try:
                info, spec = pickle.loads(self._store.get("actors", key))
            except Exception:  # noqa: BLE001 — skip torn records
                logger.warning("actor recovery: skipping torn record %r", key)
                continue
            self._actors[info.actor_id] = info
            if spec is not None:
                self._creation_specs[info.actor_id] = spec
            if info.state == ActorState.PENDING_CREATION:
                self._pending_creation.add(info.actor_id)
            if info.name and info.state != ActorState.DEAD:
                self._named[(info.namespace or "", info.name)] = info.actor_id
            if info.address is not None and info.state == ActorState.ALIVE:
                self._by_node.setdefault(
                    info.address.node_id, set()).add(info.actor_id)

    def recover(self) -> None:
        """Called once after a GCS restart: actors persisted mid-creation
        (or mid-restart) resume scheduling; ALIVE actors keep serving at
        their recorded addresses untouched."""
        for actor_id, info in list(self._actors.items()):
            if info.state in (ActorState.PENDING_CREATION,
                              ActorState.RESTARTING):
                asyncio.ensure_future(self._schedule_actor(actor_id))

    # ---- RPC handlers -------------------------------------------------------

    async def handle_register_actor(self, payload):
        spec: TaskSpec = payload["spec"]
        get_if_exists: bool = payload.get("get_if_exists", False)
        creation = spec.actor_creation
        name = creation.name
        namespace = creation.namespace or ""
        async with self._lock:
            # Idempotent: actor ids are client-generated, so a retried
            # registration (lost reply / timeout on a pipelined register)
            # must NOT re-schedule — rerunning __init__ in a second
            # worker would double side effects and leak a lease.
            existing = self._actors.get(creation.actor_id)
            if existing is not None:
                return {"status": "registered", "info": existing}
            # Bounded creation queue (gcs_actor_creation_queue_max): a
            # registration burst beyond the bound gets typed retry_later
            # pushback (the owner re-registers with paced backoff) instead
            # of an unbounded PENDING_CREATION backlog each running its
            # own scheduling loop against the same full raylets.
            bound = CONFIG.gcs_actor_creation_queue_max
            pending = len(self._pending_creation)
            if bound > 0 and pending >= bound:
                trace_id = _tracing.trace_id_of(spec)
                _elog.emit("task.shed", actor_id=creation.actor_id.hex(),
                           trace_id=trace_id,
                           layer="gcs_actor_creation",
                           reason="creation queue full",
                           class_name=spec.function_name)
                _backoff.count_shed("gcs_actor_creation")
                _tracing.force_trace(trace_id, "task.shed:gcs_actor_creation")
                return {
                    "status": "retry_later",
                    # creations are heavier than leases: 2ms/item, 10s cap
                    "retry_after_s": _backoff.retry_after_hint(
                        pending, per_item_s=0.002, cap_s=10.0),
                }
            if name:
                existing_id = self._named.get((namespace, name))
                if existing_id is not None:
                    existing = self._actors.get(existing_id)
                    if existing is not None and existing.state != ActorState.DEAD:
                        if get_if_exists:
                            return {"status": "exists", "info": existing}
                        return {
                            "status": "error",
                            "message": f"Actor name '{name}' already taken in "
                                       f"namespace '{namespace}'",
                        }
                self._named[(namespace, name)] = creation.actor_id
            info = ActorInfo(
                actor_id=creation.actor_id,
                state=ActorState.PENDING_CREATION,
                name=name,
                namespace=namespace,
                is_detached=creation.is_detached,
                max_restarts=creation.max_restarts,
                class_name=spec.function_name,
                job_id=spec.job_id,
            )
            self._actors[creation.actor_id] = info
            self._creation_specs[creation.actor_id] = spec
            self._pending_creation.add(creation.actor_id)
            self._persist(creation.actor_id)
        if getattr(spec, "trace_ctx", None) is not None:
            # admission-span anchor: report_actor_alive closes it
            self._register_wall[creation.actor_id] = time.time()
        _elog.emit("actor.pending", actor_id=creation.actor_id.hex(),
                   class_name=spec.function_name, name=name)
        asyncio.ensure_future(self._schedule_actor(creation.actor_id))
        return {"status": "registered", "info": info}

    async def handle_get_actor_info(self, payload):
        return self._actors.get(payload["actor_id"])

    async def handle_list_actors(self, payload):
        return list(self._actors.values())

    async def handle_get_named_actor(self, payload):
        key = (payload.get("namespace") or "", payload["name"])
        actor_id = self._named.get(key)
        if actor_id is None:
            return None
        return self._actors.get(actor_id)

    async def handle_list_named_actors(self, payload):
        all_namespaces = payload.get("all_namespaces", False)
        namespace = payload.get("namespace") or ""
        out = []
        for (ns, name), actor_id in self._named.items():
            info = self._actors.get(actor_id)
            if info is None or info.state == ActorState.DEAD:
                continue
            if all_namespaces or ns == namespace:
                out.append({"namespace": ns, "name": name})
        return out

    async def handle_kill_actor(self, payload):
        actor_id: ActorID = payload["actor_id"]
        no_restart: bool = payload.get("no_restart", True)
        info = self._actors.get(actor_id)
        if info is None:
            return False
        if info.state == ActorState.ALIVE and info.address is not None:
            client = self._pool.get(info.address.rpc_address)
            try:
                await client.send_async(
                    "kill_actor", {"actor_id": actor_id, "no_restart": no_restart}
                )
            except (ConnectionLost, OSError):
                pass
        if no_restart:
            await self._mark_dead(actor_id, "ray_tpu.kill() was called")
        return True

    async def handle_report_actor_alive(self, payload):
        """Called by the worker once the creation task (__init__) succeeds."""
        actor_id: ActorID = payload["actor_id"]
        address: Address = payload["address"]
        info = self._actors.get(actor_id)
        if info is None:
            return False
        info.state = ActorState.ALIVE
        info.address = address
        info.pid = payload.get("pid", 0)
        self._pending_creation.discard(actor_id)
        self._by_node.setdefault(address.node_id, set()).add(actor_id)
        self._persist(actor_id)
        self._pub.publish(ps.ACTOR_CHANNEL, actor_id, info)
        registered_at = self._register_wall.pop(actor_id, None)
        if registered_at is not None:
            spec = self._creation_specs.get(actor_id)
            ctx = getattr(spec, "trace_ctx", None) if spec is not None \
                else None
            if ctx is not None:
                # GCS-side admission span of a traced actor creation:
                # register -> ALIVE (scheduling + lease + __init__)
                _tracing.record_span(
                    "gcs.actor_admission", ctx, registered_at, time.time(),
                    proc="gcs",
                    attrs={"actor_id": actor_id.hex(),
                           "restarts": info.num_restarts})
        _elog.emit("actor.alive", actor_id=actor_id.hex(),
                   node_id=(address.node_id.hex()
                            if address.node_id else None),
                   address=address.rpc_address, restarts=info.num_restarts)
        return True

    async def handle_report_actor_death(self, payload):
        """Called by a raylet when an actor's worker process exits."""
        actor_id: ActorID = payload["actor_id"]
        reason: str = payload.get("reason", "worker process died")
        intended: bool = payload.get("intended", False)
        await self._on_actor_failure(actor_id, reason, intended)
        return True

    # ---- internals ----------------------------------------------------------

    async def on_node_death(self, node_id: NodeID):
        for actor_id in list(self._by_node.get(node_id, ())):
            await self._on_actor_failure(
                actor_id, f"node {node_id.hex()[:8]} died", intended=False
            )

    async def on_job_finished(self, job_id):
        """Non-detached actors die with their job (owner lifetime)."""
        for actor_id, info in list(self._actors.items()):
            if info.job_id == job_id and not info.is_detached and (
                info.state != ActorState.DEAD
            ):
                await self.handle_kill_actor(
                    {"actor_id": actor_id, "no_restart": True}
                )

    async def _on_actor_failure(self, actor_id: ActorID, reason: str, intended: bool):
        info = self._actors.get(actor_id)
        if info is None or info.state == ActorState.DEAD:
            return
        if info.address is not None:
            self._by_node.get(info.address.node_id, set()).discard(actor_id)
        restarts_left = (
            info.max_restarts == -1 or info.num_restarts < info.max_restarts
        )
        if not intended and restarts_left:
            info.state = ActorState.RESTARTING
            info.num_restarts += 1
            info.address = None
            self._persist(actor_id)
            self._pub.publish(ps.ACTOR_CHANNEL, actor_id, info)
            # THE restart decision: failure observed, budget allows another
            # incarnation — the record chaos post-mortems pivot on
            _elog.emit("actor.restarting", actor_id=actor_id.hex(),
                       reason=reason, restarts=info.num_restarts)
            await asyncio.sleep(CONFIG.actor_restart_delay_ms / 1000.0)
            asyncio.ensure_future(self._schedule_actor(actor_id))
        else:
            await self._mark_dead(actor_id, reason)

    async def _mark_dead(self, actor_id: ActorID, reason: str):
        info = self._actors.get(actor_id)
        if info is None:
            return
        info.state = ActorState.DEAD
        info.death_cause = reason
        self._pending_creation.discard(actor_id)
        self._register_wall.pop(actor_id, None)
        if info.address is not None:
            self._by_node.get(info.address.node_id, set()).discard(actor_id)
            info.address = None
        if info.name:
            self._named.pop((info.namespace, info.name), None)
        self._creation_specs.pop(actor_id, None)
        self._persist(actor_id)
        self._pub.publish(ps.ACTOR_CHANNEL, actor_id, info)
        _elog.emit("actor.dead", actor_id=actor_id.hex(), reason=reason)

    async def _schedule_actor(self, actor_id: ActorID):
        """Lease a worker somewhere and push the creation task to it."""
        spec = self._creation_specs.get(actor_id)
        info = self._actors.get(actor_id)
        if spec is None or info is None or info.state == ActorState.DEAD:
            return
        attempt = 0
        refunds = 0
        failures = 0  # consecutive lease failures, drives the backoff
        policy = _backoff.BackoffPolicy(base_s=0.2, multiplier=1.5,
                                        max_s=2.0, jitter=0.2)
        pacer = _backoff.AIMDPacer(base_s=0.2, max_s=5.0)
        target_node: Optional[NodeID] = None
        while attempt < 60:
            info = self._actors.get(actor_id)
            if info is None or info.state == ActorState.DEAD:
                return
            candidates = self._nodes.pick_nodes_for(spec)
            if target_node is not None:
                candidates = [target_node] + [c for c in candidates if c != target_node]
                target_node = None
            if not candidates:
                # No feasible node RIGHT NOW (cluster scaling, PG bundles
                # re-placing after a drain, ...): stay PENDING without
                # burning the attempt budget — the reference keeps
                # pending actors queued until resources appear. The
                # budget guards against failing LEASES, not missing
                # capacity.
                await asyncio.sleep(0.25)
                continue
            attempt += 1
            node_id = candidates[0]
            raylet_addr = self._nodes.raylet_address(node_id)
            if raylet_addr is None:
                await asyncio.sleep(0.1)
                continue
            client = self._pool.get(raylet_addr)
            try:
                reply = await client.call_async(
                    "request_worker_lease",
                    {"spec": spec, "grant_or_reject": False},
                    timeout=CONFIG.worker_register_timeout_s,
                )
            except ConnectionLost as e:
                if not e.maybe_delivered and refunds < 120:
                    # The lease request provably never reached the raylet
                    # (connect refused): nothing leased, nothing executed —
                    # refund the attempt instead of burning the budget on
                    # a raylet that is restarting (the health checker
                    # removes a truly dead node from `candidates` long
                    # before the bounded refund pool drains).
                    attempt -= 1
                    refunds += 1
                failures += 1
                await asyncio.sleep(policy.delay(failures))
                continue
            except (OSError, asyncio.TimeoutError):
                failures += 1
                await asyncio.sleep(policy.delay(failures))
                continue
            if reply.get("retry_later"):
                # typed pushback from a full raylet lease queue: pace the
                # re-ask (AIMD) and refund the attempt — shed work is not
                # a failed lease, and burning the budget on it would turn
                # an overloaded-but-healthy cluster into dead actors
                if refunds < 120:
                    attempt -= 1
                    refunds += 1
                await asyncio.sleep(
                    pacer.on_pushback(reply.get("retry_after_s")))
                continue
            if reply.get("rejected"):
                if reply.get("runtime_env_error"):
                    # permanent env misconfiguration — the actor can never
                    # be placed on this (or likely any) node
                    await self._mark_dead(actor_id,
                                          reply["runtime_env_error"])
                    return
                failures += 1
                await asyncio.sleep(policy.delay(failures))
                continue
            if reply.get("retry_at"):
                target_node = reply["retry_at_node_id"]
                continue
            failures = 0
            pacer.on_success()
            worker_addr: Address = reply["worker_address"]
            ok = await self._push_creation_task(actor_id, spec, worker_addr, raylet_addr)
            if ok:
                return
            await asyncio.sleep(0.2)
        await self._mark_dead(actor_id, "actor creation could not be scheduled")

    async def _push_creation_task(
        self, actor_id: ActorID, spec: TaskSpec, worker_addr: Address, raylet_addr: str
    ) -> bool:
        client = self._pool.get(worker_addr.rpc_address)
        try:
            reply = await client.call_async(
                "push_task", {"spec": spec}, timeout=CONFIG.rpc_call_timeout_s * 10
            )
        except ConnectionLost as e:
            if not e.maybe_delivered:
                return False  # provably never started: re-lease freely
            # The connection died with the push possibly delivered: the
            # worker MAY be running __init__ right now and will report
            # itself ALIVE when it finishes (handle_report_actor_alive
            # comes over the worker's own GCS connection, not this one).
            # Re-leasing immediately would run __init__ a second time in
            # another worker — double side effects for a creation that
            # actually succeeded (flushed out by chaos `disconnect` on
            # push_task). Wait for the actor to RESOLVE before declaring
            # the push failed. "Resolved" must be judged against the
            # state at push time: a restart-path push starts from
            # RESTARTING (not PENDING_CREATION), so the test is
            # ALIVE/DEAD/another-restart-cycle — NOT merely "state
            # changed from PENDING_CREATION", which is instantly true
            # mid-restart and would abandon the actor forever.
            info = self._actors.get(actor_id)
            restarts_at_push = info.num_restarts if info is not None else -1
            deadline = (asyncio.get_event_loop().time()
                        + CONFIG.worker_register_timeout_s)
            while asyncio.get_event_loop().time() < deadline:
                info = self._actors.get(actor_id)
                if info is None or info.state in (ActorState.ALIVE,
                                                  ActorState.DEAD):
                    return True  # __init__ reported in, or a death path
                    # terminally resolved it — nothing left to push
                if info.num_restarts != restarts_at_push:
                    # the worker died and _on_actor_failure already
                    # spawned the next restart cycle's _schedule_actor:
                    # that task owns scheduling now; bowing out prevents
                    # two schedulers racing __init__ pushes
                    return True
                await asyncio.sleep(0.25)
            return False
        except (OSError, asyncio.TimeoutError):
            return False
        if reply.get("status") == "ok":
            # Worker reports itself alive (handle_report_actor_alive) with its
            # serving address; nothing more to do here.
            return True
        # __init__ raised: the actor is dead on arrival; propagate the error.
        await self._mark_dead(
            actor_id,
            reply.get("error_str", "actor constructor failed"),
        )
        info = self._actors.get(actor_id)
        if info is not None:
            info.death_cause = reply.get("error_str", "actor constructor failed")
            self._pub.publish(ps.ACTOR_CHANNEL, actor_id, info)
        # Return the leased worker to the pool.
        try:
            await self._pool.get(raylet_addr).send_async(
                "return_worker",
                {"worker_address": worker_addr, "disconnect": True},
            )
        except (ConnectionLost, OSError):
            pass
        return True
