"""GCS placement group manager: gang resource reservation with 2PC.

Role of the reference's GcsPlacementGroupManager + two-phase scheduler
(ray: src/ray/gcs/gcs_server/gcs_placement_group_manager.h:230,
gcs_placement_group_scheduler.h:274): choose nodes for every bundle per the
strategy (PACK / SPREAD / STRICT_PACK / STRICT_SPREAD), PREPARE resources on
each raylet, then COMMIT all-or-nothing; failed prepares roll back and the
group re-queues. TPU twist (SURVEY §7): a bundle asking for `TPU` resources
on nodes labeled with a slice topology is placed on a single slice so the
gang maps onto one ICI domain.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Dict, List, Optional

from ray_tpu._private.ids import NodeID, PlacementGroupID
from ray_tpu._private.rpc import ClientPool, ConnectionLost
from ray_tpu._private.specs import (
    PlacementGroupInfo,
    PlacementGroupSpec,
    PlacementGroupState,
    Resources,
    resources_fit,
    subtract_resources,
)
from ray_tpu.gcs import pubsub as ps
from ray_tpu._private import event_log

logger = logging.getLogger(__name__)
_elog = event_log.logger_for("gcs")


class GcsPlacementGroupManager:
    def __init__(self, node_view, publisher: ps.Publisher,
                 client_pool: ClientPool, store=None):
        self._nodes = node_view
        self._pub = publisher
        self._pool = client_pool
        self._store = store
        self._groups: Dict[PlacementGroupID, PlacementGroupInfo] = {}
        self._ready_events: Dict[PlacementGroupID, asyncio.Event] = {}
        self._named: Dict[str, PlacementGroupID] = {}
        self._load_persisted()

    # ---- persistence (append-log store; reference: PG table in
    # gcs_table_storage.cc) --------------------------------------------------

    def _persist(self, pg_id) -> None:
        if self._store is None:
            return
        import pickle

        info = self._groups.get(pg_id)
        if info is None:
            self._store.delete("pgs", pg_id.binary())
        else:
            self._store.put("pgs", pg_id.binary(),
                            pickle.dumps(info, protocol=5))

    def _load_persisted(self) -> None:
        if self._store is None:
            return
        import pickle

        for key in self._store.keys("pgs"):
            try:
                info = pickle.loads(self._store.get("pgs", key))
            except Exception:  # noqa: BLE001 — skip torn records
                logger.warning("pg recovery: skipping torn record %r", key)
                continue
            pg_id = info.spec.placement_group_id
            self._groups[pg_id] = info
            ev = asyncio.Event()
            if info.state == PlacementGroupState.CREATED:
                ev.set()
            self._ready_events[pg_id] = ev
            if info.spec.name and info.state != PlacementGroupState.REMOVED:
                self._named[info.spec.name] = pg_id

    def recover(self) -> None:
        """After a GCS restart: placed groups keep their reservations
        (the raylets still hold the bundles); groups caught mid-placement
        resume scheduling."""
        for pg_id, info in list(self._groups.items()):
            if info.state in (PlacementGroupState.PENDING,
                              PlacementGroupState.RESCHEDULING):
                asyncio.ensure_future(self._schedule(pg_id))

    def pending_bundle_shapes(self):
        """Bundle resource shapes of PGs not yet fully placed — gang demand
        for the autoscaler (reference: pending PGs in the autoscaler state
        from gcs_autoscaler_state_manager.cc)."""
        out = []
        for info in self._groups.values():
            if info.state in (PlacementGroupState.PENDING,
                              PlacementGroupState.RESCHEDULING):
                placed = set(info.bundle_locations)
                for i, b in enumerate(info.spec.bundles):
                    if i not in placed:
                        out.append(dict(b))
        return out

    # ---- RPC handlers -------------------------------------------------------

    async def handle_create_placement_group(self, payload):
        spec: PlacementGroupSpec = payload["spec"]
        if spec.name and spec.name in self._named:
            return {"status": "error",
                    "message": f"placement group name '{spec.name}' already taken"}
        info = PlacementGroupInfo(spec=spec, state=PlacementGroupState.PENDING)
        self._groups[spec.placement_group_id] = info
        self._ready_events[spec.placement_group_id] = asyncio.Event()
        if spec.name:
            self._named[spec.name] = spec.placement_group_id
        self._persist(spec.placement_group_id)
        asyncio.ensure_future(self._schedule(spec.placement_group_id))
        return {"status": "ok"}

    async def handle_remove_placement_group(self, payload):
        pg_id: PlacementGroupID = payload["placement_group_id"]
        info = self._groups.get(pg_id)
        if info is None:
            return False
        info.state = PlacementGroupState.REMOVED
        _elog.emit("pg.state", state="REMOVED", pg=pg_id.hex())
        if info.spec.name:
            self._named.pop(info.spec.name, None)
        self._persist(pg_id)
        # Release bundle reservations on every involved raylet.
        for node_id in set(info.bundle_locations.values()):
            addr = self._nodes.raylet_address(node_id)
            if addr is None:
                continue
            try:
                await self._pool.get(addr).send_async(
                    "cancel_bundles", {"placement_group_id": pg_id}
                )
            except (ConnectionLost, OSError):
                pass
        self._pub.publish(ps.PG_CHANNEL, pg_id, info)
        return True

    async def handle_wait_placement_group_ready(self, payload):
        pg_id: PlacementGroupID = payload["placement_group_id"]
        timeout = payload.get("timeout", -1)
        ev = self._ready_events.get(pg_id)
        info = self._groups.get(pg_id)
        if info is None:
            return {"status": "error", "message": "no such placement group"}
        if info.state == PlacementGroupState.CREATED:
            return {"status": "ready", "info": info}
        if ev is None:
            return {"status": "error", "message": "placement group removed"}
        try:
            if timeout is None or timeout < 0:
                await ev.wait()
            else:
                await asyncio.wait_for(ev.wait(), timeout)
        except asyncio.TimeoutError:
            return {"status": "timeout"}
        info = self._groups.get(pg_id)
        if info is None or info.state != PlacementGroupState.CREATED:
            return {"status": "error", "message": "placement group removed"}
        return {"status": "ready", "info": info}

    async def handle_get_placement_group(self, payload):
        pg_id = payload.get("placement_group_id")
        if pg_id is None:
            name = payload.get("name")
            pg_id = self._named.get(name)
            if pg_id is None:
                return None
        return self._groups.get(pg_id)

    async def handle_list_placement_groups(self, payload):
        return list(self._groups.values())

    # ---- internals ----------------------------------------------------------

    async def on_node_death(self, node_id: NodeID):
        """Reschedule bundles that lived on a dead node."""
        for pg_id, info in list(self._groups.items()):
            if info.state != PlacementGroupState.CREATED:
                continue
            lost = [i for i, n in info.bundle_locations.items() if n == node_id]
            if not lost:
                continue
            info.state = PlacementGroupState.RESCHEDULING
            _elog.emit("pg.state", state="RESCHEDULING",
                       node_id=node_id.hex(), pg=pg_id.hex())
            self._ready_events[pg_id] = asyncio.Event()
            for i in lost:
                info.bundle_locations.pop(i, None)
            if (info.spec.strategy == "STRICT_PACK"
                    and any(b.get("TPU", 0) > 0 for b in info.spec.bundles)):
                # TPU gang: rescheduling ONLY the lost bundle could land it
                # on a different slice (the surviving slice hosts are full),
                # silently straddling ICI domains. A gang is all-or-nothing
                # (SURVEY §7: a failed host restarts the whole gang): release
                # every surviving bundle and re-place the gang as a unit.
                for surv_node in set(info.bundle_locations.values()):
                    addr = self._nodes.raylet_address(surv_node)
                    if addr is None:
                        continue
                    try:
                        await self._pool.get(addr).send_async(
                            "cancel_bundles", {"placement_group_id": pg_id})
                    except (ConnectionLost, OSError):
                        pass
                info.bundle_locations.clear()
            self._pub.publish(ps.PG_CHANNEL, pg_id, info)
            asyncio.ensure_future(self._schedule(pg_id, partial=True))

    def _place_bundles(
        self, bundles: Dict[int, Resources], strategy: str
    ) -> Optional[Dict[int, NodeID]]:
        """Pick a node per bundle. Pure function over the GCS resource view."""
        view = self._nodes.resource_view()  # node_id -> available Resources (copy)
        if not view:
            return None
        placement: Dict[int, NodeID] = {}

        def nodes_sorted(prefer_packed: bool):
            # Most-available-first for spread; least-available-first for pack.
            items = sorted(
                view.items(),
                key=lambda kv: sum(kv[1].values()),
                reverse=not prefer_packed,
            )
            return [k for k, _ in items]

        if strategy == "STRICT_PACK":
            # TPU gang: STRICT_PACK of TPU bundles means ONE SLICE (one ICI
            # domain), not one host — a multi-host slice is the TPU analogue
            # of a single NVLink box. Delegated to the slice-aware path.
            if any(b.get("TPU", 0) > 0 for b in bundles.values()):
                return self._place_on_single_slice(bundles, view)
            total: Resources = {}
            for b in bundles.values():
                for k, v in b.items():
                    total[k] = total.get(k, 0.0) + v
            for node_id, avail in view.items():
                if resources_fit(avail, total):
                    return {i: node_id for i in bundles}
            return None

        used_nodes: Dict[NodeID, int] = {}
        for index, demand in sorted(bundles.items()):
            chosen = None
            if strategy == "STRICT_SPREAD":
                for node_id in nodes_sorted(prefer_packed=False):
                    if node_id in used_nodes:
                        continue
                    if resources_fit(view[node_id], demand):
                        chosen = node_id
                        break
            elif strategy == "SPREAD":
                fresh = [n for n in nodes_sorted(False) if n not in used_nodes]
                reused = [n for n in nodes_sorted(False) if n in used_nodes]
                for node_id in fresh + reused:
                    if resources_fit(view[node_id], demand):
                        chosen = node_id
                        break
            else:  # PACK (default)
                packed = [n for n in nodes_sorted(True) if n in used_nodes]
                fresh = [n for n in nodes_sorted(True) if n not in used_nodes]
                for node_id in packed + fresh:
                    if resources_fit(view[node_id], demand):
                        chosen = node_id
                        break
            if chosen is None:
                return None
            placement[index] = chosen
            used_nodes[chosen] = used_nodes.get(chosen, 0) + 1
            subtract_resources(view[chosen], demand)
        return placement

    def _place_on_single_slice(
        self, bundles: Dict[int, Resources], view: Dict[NodeID, Resources]
    ) -> Optional[Dict[int, NodeID]]:
        """Place a TPU gang so every bundle lands on hosts of ONE slice.

        Nodes carrying the ray.io/tpu-slice-name label group by slice;
        unlabeled TPU nodes each form their own singleton group (a dev box
        with chips is its own ICI domain). Groups are tried smallest-first
        (leave big slices for big gangs); within a group bundles pack
        per-host. A gang that fits no single group fails placement — it
        NEVER straddles slices, because cross-slice traffic would ride DCN,
        not ICI. Reference analogue: the TPU-<topo>-head pod resource +
        slice bookkeeping in ray tpu.py:75-210; here placement is
        topology-aware directly (SURVEY §7).
        """
        from ray_tpu._private.accelerators.tpu import SLICE_NAME_LABEL

        labels = self._nodes.label_view()
        groups: Dict[str, List[NodeID]] = {}
        for node_id, avail in view.items():
            if avail.get("TPU", 0) <= 0:
                continue  # CPU-only bundles of the gang also pack onto slice hosts
            slice_name = labels.get(node_id, {}).get(SLICE_NAME_LABEL)
            key = slice_name or f"__node__{node_id.hex()}"
            groups.setdefault(key, []).append(node_id)

        def group_tpu(nodes: List[NodeID]) -> float:
            return sum(view[n].get("TPU", 0) for n in nodes)

        for _, nodes in sorted(groups.items(),
                               key=lambda kv: group_tpu(kv[1])):
            scratch = {n: dict(view[n]) for n in nodes}
            placement: Dict[int, NodeID] = {}
            ok = True
            for index, demand in sorted(bundles.items()):
                chosen = None
                # pack: fewest free CHIPS first so partial hosts fill up —
                # ranking by sum of all resources would be dominated by the
                # ~1e9-scale memory term and can strand a feasible gang
                for node_id in sorted(
                        scratch, key=lambda n: scratch[n].get("TPU", 0.0)):
                    if resources_fit(scratch[node_id], demand):
                        chosen = node_id
                        break
                if chosen is None:
                    ok = False
                    break
                placement[index] = chosen
                subtract_resources(scratch[chosen], demand)
            if ok:
                return placement
        return None

    async def _schedule(self, pg_id: PlacementGroupID, partial: bool = False):
        info = self._groups.get(pg_id)
        if info is None:
            return
        attempt = 0
        while attempt < 240:
            attempt += 1
            info = self._groups.get(pg_id)
            if info is None or info.state == PlacementGroupState.REMOVED:
                return
            bundles = {
                i: b
                for i, b in enumerate(info.spec.bundles)
                if i not in info.bundle_locations
            }
            if not bundles:
                break
            placement = self._place_bundles(bundles, info.spec.strategy)
            if placement is None:
                await asyncio.sleep(0.25)
                continue
            ok = await self._prepare_and_commit(pg_id, placement, bundles)
            if ok:
                info.bundle_locations.update(placement)
                break
            await asyncio.sleep(0.25)
        info = self._groups.get(pg_id)
        if info is None:
            return
        if len(info.bundle_locations) == len(info.spec.bundles):
            info.state = PlacementGroupState.CREATED
            _elog.emit("pg.state", state="CREATED", pg=pg_id.hex())
            self._persist(pg_id)
            ev = self._ready_events.get(pg_id)
            if ev is not None:
                ev.set()
            self._pub.publish(ps.PG_CHANNEL, pg_id, info)
        else:
            logger.warning("placement group %s could not be scheduled", pg_id)

    async def _prepare_and_commit(
        self,
        pg_id: PlacementGroupID,
        placement: Dict[int, NodeID],
        bundles: Dict[int, Resources],
    ) -> bool:
        # Group bundle indices per node.
        per_node: Dict[NodeID, Dict[int, Resources]] = {}
        for index, node_id in placement.items():
            per_node.setdefault(node_id, {})[index] = bundles[index]

        # Phase 1: PREPARE on each raylet.
        prepared: List[NodeID] = []
        for node_id, node_bundles in per_node.items():
            addr = self._nodes.raylet_address(node_id)
            if addr is None:
                break
            try:
                ok = await self._pool.get(addr).call_async(
                    "prepare_bundles",
                    {"placement_group_id": pg_id, "bundles": node_bundles},
                )
            except (ConnectionLost, OSError):
                ok = False
            if not ok:
                break
            prepared.append(node_id)
        if len(prepared) != len(per_node):
            for node_id in prepared:
                addr = self._nodes.raylet_address(node_id)
                if addr is None:
                    continue
                try:
                    await self._pool.get(addr).send_async(
                        "cancel_bundles", {"placement_group_id": pg_id}
                    )
                except (ConnectionLost, OSError):
                    pass
            return False

        # Phase 2: COMMIT everywhere.
        for node_id, node_bundles in per_node.items():
            addr = self._nodes.raylet_address(node_id)
            if addr is None:
                continue
            try:
                await self._pool.get(addr).call_async(
                    "commit_bundles",
                    {"placement_group_id": pg_id, "indices": list(node_bundles)},
                )
            except (ConnectionLost, OSError):
                pass  # node died post-prepare; node-death path reschedules
        return True
