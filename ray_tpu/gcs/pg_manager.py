"""GCS placement group manager: gang resource reservation with 2PC.

Role of the reference's GcsPlacementGroupManager + two-phase scheduler
(ray: src/ray/gcs/gcs_server/gcs_placement_group_manager.h:230,
gcs_placement_group_scheduler.h:274): choose nodes for every bundle per the
strategy (PACK / SPREAD / STRICT_PACK / STRICT_SPREAD), PREPARE resources on
each raylet, then COMMIT all-or-nothing; failed prepares roll back and the
group re-queues. TPU twist (SURVEY §7): a bundle asking for `TPU` resources
on nodes labeled with a slice topology is placed on a single slice so the
gang maps onto one ICI domain.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Dict, List, Optional

from ray_tpu._private.ids import NodeID, PlacementGroupID
from ray_tpu._private.rpc import ClientPool, ConnectionLost
from ray_tpu._private.specs import (
    PlacementGroupInfo,
    PlacementGroupSpec,
    PlacementGroupState,
    Resources,
    resources_fit,
    subtract_resources,
)
from ray_tpu.gcs import pubsub as ps

logger = logging.getLogger(__name__)


class GcsPlacementGroupManager:
    def __init__(self, node_view, publisher: ps.Publisher, client_pool: ClientPool):
        self._nodes = node_view
        self._pub = publisher
        self._pool = client_pool
        self._groups: Dict[PlacementGroupID, PlacementGroupInfo] = {}
        self._ready_events: Dict[PlacementGroupID, asyncio.Event] = {}
        self._named: Dict[str, PlacementGroupID] = {}

    def pending_bundle_shapes(self):
        """Bundle resource shapes of PGs not yet fully placed — gang demand
        for the autoscaler (reference: pending PGs in the autoscaler state
        from gcs_autoscaler_state_manager.cc)."""
        out = []
        for info in self._groups.values():
            if info.state in (PlacementGroupState.PENDING,
                              PlacementGroupState.RESCHEDULING):
                placed = set(info.bundle_locations)
                for i, b in enumerate(info.spec.bundles):
                    if i not in placed:
                        out.append(dict(b))
        return out

    # ---- RPC handlers -------------------------------------------------------

    async def handle_create_placement_group(self, payload):
        spec: PlacementGroupSpec = payload["spec"]
        if spec.name and spec.name in self._named:
            return {"status": "error",
                    "message": f"placement group name '{spec.name}' already taken"}
        info = PlacementGroupInfo(spec=spec, state=PlacementGroupState.PENDING)
        self._groups[spec.placement_group_id] = info
        self._ready_events[spec.placement_group_id] = asyncio.Event()
        if spec.name:
            self._named[spec.name] = spec.placement_group_id
        asyncio.ensure_future(self._schedule(spec.placement_group_id))
        return {"status": "ok"}

    async def handle_remove_placement_group(self, payload):
        pg_id: PlacementGroupID = payload["placement_group_id"]
        info = self._groups.get(pg_id)
        if info is None:
            return False
        info.state = PlacementGroupState.REMOVED
        if info.spec.name:
            self._named.pop(info.spec.name, None)
        # Release bundle reservations on every involved raylet.
        for node_id in set(info.bundle_locations.values()):
            addr = self._nodes.raylet_address(node_id)
            if addr is None:
                continue
            try:
                await self._pool.get(addr).send_async(
                    "cancel_bundles", {"placement_group_id": pg_id}
                )
            except (ConnectionLost, OSError):
                pass
        self._pub.publish(ps.PG_CHANNEL, pg_id, info)
        return True

    async def handle_wait_placement_group_ready(self, payload):
        pg_id: PlacementGroupID = payload["placement_group_id"]
        timeout = payload.get("timeout", -1)
        ev = self._ready_events.get(pg_id)
        info = self._groups.get(pg_id)
        if info is None:
            return {"status": "error", "message": "no such placement group"}
        if info.state == PlacementGroupState.CREATED:
            return {"status": "ready", "info": info}
        if ev is None:
            return {"status": "error", "message": "placement group removed"}
        try:
            if timeout is None or timeout < 0:
                await ev.wait()
            else:
                await asyncio.wait_for(ev.wait(), timeout)
        except asyncio.TimeoutError:
            return {"status": "timeout"}
        info = self._groups.get(pg_id)
        if info is None or info.state != PlacementGroupState.CREATED:
            return {"status": "error", "message": "placement group removed"}
        return {"status": "ready", "info": info}

    async def handle_get_placement_group(self, payload):
        pg_id = payload.get("placement_group_id")
        if pg_id is None:
            name = payload.get("name")
            pg_id = self._named.get(name)
            if pg_id is None:
                return None
        return self._groups.get(pg_id)

    async def handle_list_placement_groups(self, payload):
        return list(self._groups.values())

    # ---- internals ----------------------------------------------------------

    async def on_node_death(self, node_id: NodeID):
        """Reschedule bundles that lived on a dead node."""
        for pg_id, info in list(self._groups.items()):
            if info.state != PlacementGroupState.CREATED:
                continue
            lost = [i for i, n in info.bundle_locations.items() if n == node_id]
            if not lost:
                continue
            info.state = PlacementGroupState.RESCHEDULING
            self._ready_events[pg_id] = asyncio.Event()
            for i in lost:
                info.bundle_locations.pop(i, None)
            self._pub.publish(ps.PG_CHANNEL, pg_id, info)
            asyncio.ensure_future(self._schedule(pg_id, partial=True))

    def _place_bundles(
        self, bundles: Dict[int, Resources], strategy: str
    ) -> Optional[Dict[int, NodeID]]:
        """Pick a node per bundle. Pure function over the GCS resource view."""
        view = self._nodes.resource_view()  # node_id -> available Resources (copy)
        if not view:
            return None
        placement: Dict[int, NodeID] = {}

        def nodes_sorted(prefer_packed: bool):
            # Most-available-first for spread; least-available-first for pack.
            items = sorted(
                view.items(),
                key=lambda kv: sum(kv[1].values()),
                reverse=not prefer_packed,
            )
            return [k for k, _ in items]

        if strategy == "STRICT_PACK":
            total: Resources = {}
            for b in bundles.values():
                for k, v in b.items():
                    total[k] = total.get(k, 0.0) + v
            for node_id, avail in view.items():
                if resources_fit(avail, total):
                    return {i: node_id for i in bundles}
            return None

        used_nodes: Dict[NodeID, int] = {}
        for index, demand in sorted(bundles.items()):
            chosen = None
            if strategy == "STRICT_SPREAD":
                for node_id in nodes_sorted(prefer_packed=False):
                    if node_id in used_nodes:
                        continue
                    if resources_fit(view[node_id], demand):
                        chosen = node_id
                        break
            elif strategy == "SPREAD":
                fresh = [n for n in nodes_sorted(False) if n not in used_nodes]
                reused = [n for n in nodes_sorted(False) if n in used_nodes]
                for node_id in fresh + reused:
                    if resources_fit(view[node_id], demand):
                        chosen = node_id
                        break
            else:  # PACK (default)
                packed = [n for n in nodes_sorted(True) if n in used_nodes]
                fresh = [n for n in nodes_sorted(True) if n not in used_nodes]
                for node_id in packed + fresh:
                    if resources_fit(view[node_id], demand):
                        chosen = node_id
                        break
            if chosen is None:
                return None
            placement[index] = chosen
            used_nodes[chosen] = used_nodes.get(chosen, 0) + 1
            subtract_resources(view[chosen], demand)
        return placement

    async def _schedule(self, pg_id: PlacementGroupID, partial: bool = False):
        info = self._groups.get(pg_id)
        if info is None:
            return
        attempt = 0
        while attempt < 240:
            attempt += 1
            info = self._groups.get(pg_id)
            if info is None or info.state == PlacementGroupState.REMOVED:
                return
            bundles = {
                i: b
                for i, b in enumerate(info.spec.bundles)
                if i not in info.bundle_locations
            }
            if not bundles:
                break
            placement = self._place_bundles(bundles, info.spec.strategy)
            if placement is None:
                await asyncio.sleep(0.25)
                continue
            ok = await self._prepare_and_commit(pg_id, placement, bundles)
            if ok:
                info.bundle_locations.update(placement)
                break
            await asyncio.sleep(0.25)
        info = self._groups.get(pg_id)
        if info is None:
            return
        if len(info.bundle_locations) == len(info.spec.bundles):
            info.state = PlacementGroupState.CREATED
            ev = self._ready_events.get(pg_id)
            if ev is not None:
                ev.set()
            self._pub.publish(ps.PG_CHANNEL, pg_id, info)
        else:
            logger.warning("placement group %s could not be scheduled", pg_id)

    async def _prepare_and_commit(
        self,
        pg_id: PlacementGroupID,
        placement: Dict[int, NodeID],
        bundles: Dict[int, Resources],
    ) -> bool:
        # Group bundle indices per node.
        per_node: Dict[NodeID, Dict[int, Resources]] = {}
        for index, node_id in placement.items():
            per_node.setdefault(node_id, {})[index] = bundles[index]

        # Phase 1: PREPARE on each raylet.
        prepared: List[NodeID] = []
        for node_id, node_bundles in per_node.items():
            addr = self._nodes.raylet_address(node_id)
            if addr is None:
                break
            try:
                ok = await self._pool.get(addr).call_async(
                    "prepare_bundles",
                    {"placement_group_id": pg_id, "bundles": node_bundles},
                )
            except (ConnectionLost, OSError):
                ok = False
            if not ok:
                break
            prepared.append(node_id)
        if len(prepared) != len(per_node):
            for node_id in prepared:
                addr = self._nodes.raylet_address(node_id)
                if addr is None:
                    continue
                try:
                    await self._pool.get(addr).send_async(
                        "cancel_bundles", {"placement_group_id": pg_id}
                    )
                except (ConnectionLost, OSError):
                    pass
            return False

        # Phase 2: COMMIT everywhere.
        for node_id, node_bundles in per_node.items():
            addr = self._nodes.raylet_address(node_id)
            if addr is None:
                continue
            try:
                await self._pool.get(addr).call_async(
                    "commit_bundles",
                    {"placement_group_id": pg_id, "indices": list(node_bundles)},
                )
            except (ConnectionLost, OSError):
                pass  # node died post-prepare; node-death path reschedules
        return True
