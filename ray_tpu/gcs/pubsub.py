"""GCS-hosted pubsub.

Role of the reference's publisher/subscriber channels
(ray: src/ray/pubsub/publisher.h:296, subscriber.h:70; GCS wrapper
gcs/gcs_server/pubsub_handler.cc). Channels carry actor-state, node-state,
job, error and log messages. Instead of long-polling, the publisher pushes
one-way RPC frames to each subscriber's own RpcServer ("pubsub_message"
handler); dead subscribers are dropped on first send failure.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Set

from ray_tpu._private.rpc import ClientPool, ConnectionLost, EventLoopThread

logger = logging.getLogger(__name__)

# Channel names
ACTOR_CHANNEL = "ACTOR"
NODE_CHANNEL = "NODE"
JOB_CHANNEL = "JOB"
ERROR_CHANNEL = "ERROR"
LOG_CHANNEL = "LOG"
PG_CHANNEL = "PLACEMENT_GROUP"
WORKER_CHANNEL = "WORKER"


class Publisher:
    """Pushes (channel, key, message) to every subscriber of the channel."""

    def __init__(self, loop_thread: EventLoopThread):
        self._lt = loop_thread
        self._pool = ClientPool(loop_thread)
        # channel -> set of subscriber rpc addresses
        self._subs: Dict[str, Set[str]] = {}
        # invoked as on_drop(channel, addr) when a dead subscriber is
        # discarded (lets the GCS prune its persisted subscription table)
        self.on_drop = None

    def subscribe(self, channel: str, subscriber_address: str) -> None:
        self._subs.setdefault(channel, set()).add(subscriber_address)

    def unsubscribe(self, channel: str, subscriber_address: str) -> None:
        self._subs.get(channel, set()).discard(subscriber_address)

    def unsubscribe_all(self, subscriber_address: str) -> None:
        for subs in self._subs.values():
            subs.discard(subscriber_address)

    def publish(self, channel: str, key: Any, message: Any) -> None:
        for addr in list(self._subs.get(channel, ())):
            self._lt.submit(self._push(channel, addr, key, message))

    async def _push(self, channel: str, addr: str, key: Any, message: Any):
        client = self._pool.get(addr)
        try:
            await client.send_async("pubsub_message", (channel, key, message))
        except (ConnectionLost, OSError):
            self._subs.get(channel, set()).discard(addr)
            self._pool.invalidate(addr)
            if self.on_drop is not None:
                try:
                    self.on_drop(channel, addr)
                except Exception:  # noqa: BLE001
                    logger.exception("pubsub on_drop failed")

    def close(self):
        self._pool.close_all()
