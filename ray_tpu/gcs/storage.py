"""GCS table storage: pluggable persistence.

Reference: ray src/ray/gcs/store_client/{in_memory,redis}_store_client.cc
and the table layer gcs_table_storage.cc. In-memory is the default; the
file-backed store gives restart-survivable state the way the reference
uses Redis.

Persistence is an APPEND-ONLY LOG with periodic compaction (VERDICT r3
#3): each mutation appends one pickled (op, table, key, value) record —
O(record), not O(cluster state) like the old snapshot-per-mutation —
and once the log accumulates enough records the whole table set is
rewritten as a snapshot and the log truncated. Recovery loads the
snapshot, then replays the log.
"""

from __future__ import annotations

import logging
import os
import pickle
import struct
import threading
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)

_OP_PUT = 0
_OP_DEL = 1


class InMemoryStore:
    """table -> key(bytes) -> value(bytes). Thread-safe."""

    def __init__(self):
        self._tables: Dict[str, Dict[bytes, bytes]] = {}
        self._lock = threading.RLock()

    def put(self, table: str, key: bytes, value: bytes) -> None:
        with self._lock:
            self._tables.setdefault(table, {})[key] = value
            self._append(_OP_PUT, table, key, value)

    def get(self, table: str, key: bytes) -> Optional[bytes]:
        with self._lock:
            return self._tables.get(table, {}).get(key)

    def delete(self, table: str, key: bytes) -> bool:
        with self._lock:
            existed = self._tables.get(table, {}).pop(key, None) is not None
            if existed:
                self._append(_OP_DEL, table, key, b"")
        return existed

    def keys(self, table: str, prefix: bytes = b"") -> List[bytes]:
        with self._lock:
            return [k for k in self._tables.get(table, {}) if k.startswith(prefix)]

    def get_all(self, table: str) -> Dict[bytes, bytes]:
        with self._lock:
            return dict(self._tables.get(table, {}))

    def _append(self, op: int, table: str, key: bytes, value: bytes):
        pass


class FileBackedStore(InMemoryStore):
    """Append-log persistence with compaction (see module docstring)."""

    COMPACT_EVERY = 2000  # log records between snapshot rewrites

    def __init__(self, path: str):
        super().__init__()
        self._path = path
        self._log_path = path + ".log"
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._replayed = 0
        self._load()
        self._log = open(self._log_path, "ab")
        # records already sitting in the log count toward the threshold:
        # a store restarted more often than COMPACT_EVERY mutations would
        # otherwise never compact and replay would grow without bound
        self._log_records = self._replayed
        if self._log_records >= self.COMPACT_EVERY:
            self._compact()

    # -- recovery ------------------------------------------------------------

    def _load(self) -> None:
        if os.path.exists(self._path):
            with open(self._path, "rb") as f:
                try:
                    self._tables = pickle.load(f)
                except Exception:  # noqa: BLE001 — torn snapshot: start empty
                    self._tables = {}
        if os.path.exists(self._log_path):
            try:
                with open(self._log_path, "rb") as f:
                    while True:
                        header = f.read(4)
                        if len(header) < 4:
                            break
                        (length,) = struct.unpack("<I", header)
                        blob = f.read(length)
                        if len(blob) < length:
                            break  # torn tail record (crash mid-append)
                        op, table, key, value = pickle.loads(blob)
                        self._replayed += 1
                        if op == _OP_PUT:
                            self._tables.setdefault(table, {})[key] = value
                        else:
                            self._tables.get(table, {}).pop(key, None)
            except Exception:  # noqa: BLE001 — replay what we could
                logger.warning(
                    "store recovery: log replay stopped after %d records "
                    "(torn tail is expected after a crash)",
                    self._replayed, exc_info=True)

    # -- logging -------------------------------------------------------------

    def _append(self, op: int, table: str, key: bytes, value: bytes) -> None:
        blob = pickle.dumps((op, table, key, value), protocol=5)
        self._log.write(struct.pack("<I", len(blob)) + blob)
        self._log.flush()
        self._log_records += 1
        if self._log_records >= self.COMPACT_EVERY:
            self._compact()

    def _compact(self) -> None:
        tmp = self._path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(self._tables, f, protocol=5)
        os.replace(tmp, self._path)
        self._log.close()
        self._log = open(self._log_path, "wb")  # truncate
        self._log_records = 0

    def close(self) -> None:
        try:
            self._log.close()
        except Exception:  # noqa: BLE001 — already closed / fs gone
            logger.debug("store log close failed", exc_info=True)


def make_store(path: str = "", external_address: str = "",
               on_down=None) -> InMemoryStore:
    """external_address ("host:port" of an ExternalStoreServer) wins over
    a local file path: with an external store the authoritative copy lives
    off-host and the head keeps nothing durable locally (reference: Redis
    replaces the local store entirely, redis_store_client.cc)."""
    if external_address:
        from ray_tpu.gcs.external_store import ExternalStore

        return ExternalStore(external_address, on_down=on_down)
    return FileBackedStore(path) if path else InMemoryStore()
