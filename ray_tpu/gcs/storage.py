"""GCS table storage: pluggable persistence.

Reference: ray src/ray/gcs/store_client/{in_memory,redis}_store_client.cc and
the table layer gcs_table_storage.cc. In-memory is the default; a file-backed
store (append-less JSON-pickle snapshot on mutation batches) provides
restart-survivable state the way the reference uses Redis.
"""

from __future__ import annotations

import os
import pickle
import threading
from typing import Dict, List, Optional


class InMemoryStore:
    """table -> key(bytes) -> value(bytes). Thread-safe."""

    def __init__(self):
        self._tables: Dict[str, Dict[bytes, bytes]] = {}
        self._lock = threading.RLock()

    def put(self, table: str, key: bytes, value: bytes) -> None:
        with self._lock:
            self._tables.setdefault(table, {})[key] = value
        self._persist()

    def get(self, table: str, key: bytes) -> Optional[bytes]:
        with self._lock:
            return self._tables.get(table, {}).get(key)

    def delete(self, table: str, key: bytes) -> bool:
        with self._lock:
            existed = self._tables.get(table, {}).pop(key, None) is not None
        self._persist()
        return existed

    def keys(self, table: str, prefix: bytes = b"") -> List[bytes]:
        with self._lock:
            return [k for k in self._tables.get(table, {}) if k.startswith(prefix)]

    def get_all(self, table: str) -> Dict[bytes, bytes]:
        with self._lock:
            return dict(self._tables.get(table, {}))

    def _persist(self):
        pass


class FileBackedStore(InMemoryStore):
    """Snapshot-on-write persistence for GCS fault tolerance."""

    def __init__(self, path: str):
        super().__init__()
        self._path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        if os.path.exists(path):
            with open(path, "rb") as f:
                try:
                    self._tables = pickle.load(f)
                except Exception:
                    self._tables = {}

    def _persist(self):
        tmp = self._path + ".tmp"
        with self._lock:
            data = pickle.dumps(self._tables)
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, self._path)


def make_store(path: str = "") -> InMemoryStore:
    return FileBackedStore(path) if path else InMemoryStore()
