"""GCS server: headnode control plane.

Role of the reference's GcsServer (ray: src/ray/gcs/gcs_server/gcs_server.h,
gcs_server_main.cc), hosting:
  - node membership + health checks (gcs_node_manager.cc,
    gcs_health_check_manager.h:39 — here: heartbeat staleness detection),
  - resource view sync (the ray_syncer equivalent: heartbeat replies carry the
    full cluster resource view back to each raylet),
  - actor manager (actor_manager.py), placement groups (pg_manager.py),
  - jobs (gcs_job_manager.cc), internal KV (gcs_kv_manager.cc) which also
    stores exported functions (gcs_function_manager.h),
  - task events for observability (gcs_task_manager.cc),
  - pubsub (pubsub_handler.cc).

Runs embedded in the head node process on its own EventLoopThread, or
standalone via `python -m ray_tpu.gcs.server`.
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time
from collections import OrderedDict, deque
from fnmatch import fnmatchcase
from typing import Dict, List, Optional

from ray_tpu._private import event_log
from ray_tpu._private import tracing as _tracing
from ray_tpu._private.config import CONFIG
from ray_tpu._private.ids import JobID, NodeID
from ray_tpu._private.rpc import (ClientPool, ConnectionLost,
                                  EventLoopThread, RpcServer)
from ray_tpu._private.specs import (
    JobInfo,
    NodeInfo,
    Resources,
    TaskSpec,
    resources_fit,
)
from ray_tpu.gcs import pubsub as ps
from ray_tpu.gcs.actor_manager import GcsActorManager
from ray_tpu.gcs.metrics_manager import GcsMetricsManager
from ray_tpu.gcs.pg_manager import GcsPlacementGroupManager
from ray_tpu.gcs.storage import make_store

logger = logging.getLogger(__name__)
_elog = event_log.logger_for("gcs")


class GcsNodeManager:
    """Node registry + cluster resource view + failure detection."""

    def __init__(self, publisher: ps.Publisher, store=None):
        self._pub = publisher
        self._store = store
        self._nodes: Dict[NodeID, NodeInfo] = {}
        self._last_heartbeat: Dict[NodeID, float] = {}
        self._pending_demands: Dict[NodeID, list] = {}
        # explicit autoscaler.sdk.request_resources() demand: shapes the
        # cluster must be able to fit even with no tasks queued
        self._requested_resources: list = []
        self._death_listeners = []
        self.pg_locator = None  # wired to GcsPlacementGroupManager by GcsServer
        # Versioned view for delta heartbeats (reference:
        # ray_syncer.h:78-88 — per-node snapshots with version numbers,
        # only newer snapshots relayed). A node's version bumps only when
        # its entry CHANGES, so idle-cluster heartbeat replies are empty
        # deltas instead of the O(N) full view (O(N^2)/period cluster-wide).
        self._view_version = 0
        self._node_versions: Dict[NodeID, int] = {}
        self._removed_log: deque = deque(maxlen=10_000)  # (version, nid)
        self._removed_pruned_below = 0
        self._load_persisted()

    def _persist_node(self, node_id: NodeID) -> None:
        if self._store is None:
            return
        import pickle

        info = self._nodes.get(node_id)
        if info is not None:
            self._store.put("nodes", node_id.binary(),
                            pickle.dumps(info, protocol=5))

    def _load_persisted(self) -> None:
        """Reload the node registry after a GCS restart: live raylets keep
        heartbeating the same address, so their entries pick right back
        up (a fresh heartbeat grace period applies); truly dead nodes age
        out through the normal health check."""
        if self._store is None:
            return
        import pickle

        for key in self._store.keys("nodes"):
            try:
                info = pickle.loads(self._store.get("nodes", key))
            except Exception:  # noqa: BLE001 — skip torn records
                logger.warning("node recovery: skipping torn record %r", key)
                continue
            if info.alive:
                self._nodes[info.node_id] = info
                self._last_heartbeat[info.node_id] = time.monotonic()
                self._bump_node(info.node_id)

    def _bump_node(self, node_id: NodeID) -> None:
        self._view_version += 1
        self._node_versions[node_id] = self._view_version

    def add_death_listener(self, cb):
        self._death_listeners.append(cb)

    # -- RPC --
    async def handle_register_node(self, payload):
        info: NodeInfo = payload["info"]
        self._nodes[info.node_id] = info
        self._last_heartbeat[info.node_id] = time.monotonic()
        self._bump_node(info.node_id)
        self._persist_node(info.node_id)
        self._pub.publish(ps.NODE_CHANNEL, info.node_id, info)
        _elog.emit("node.alive", node_id=info.node_id.hex(),
                   address=info.raylet_address)
        logger.info("node %s registered (%s)", info.node_id.hex()[:8], info.raylet_address)
        return True

    async def handle_unregister_node(self, payload):
        await self._mark_dead(payload["node_id"], expected=True)
        return True

    async def handle_report_resources(self, payload):
        """Raylet heartbeat; the reply syncs the cluster view (syncer
        role). With known_version the reply is a DELTA — only nodes whose
        entries changed since the caller's version, plus removals; a full
        view goes out only on version-gap (or to legacy callers)."""
        node_id: NodeID = payload["node_id"]
        info = self._nodes.get(node_id)
        if info is None or not info.alive:
            return {"status": "unknown_node"}
        if (info.resources_available != payload["available"]
                or info.resources_total != payload.get(
                    "total", info.resources_total)):
            self._bump_node(node_id)
        info.resources_available = payload["available"]
        info.resources_total = payload.get("total", info.resources_total)
        if payload.get("draining") and not getattr(info, "draining", False):
            info.draining = True
            self._bump_node(node_id)
        self._last_heartbeat[node_id] = time.monotonic()
        self._pending_demands[node_id] = payload.get("pending_demands", [])
        known = payload.get("known_version")
        if known is None:
            return {
                "status": "ok",
                "cluster_view": {
                    nid: (n.raylet_address, n.resources_total,
                          n.resources_available, n.labels)
                    for nid, n in self._nodes.items()
                    if n.alive
                },
            }
        if (known and known >= self._removed_pruned_below
                and known <= self._view_version):
            # (known > _view_version means WE restarted and lost version
            # state — fall through to the full view, else the caller would
            # keep a stale view forever)
            delta = {
                nid: (n.raylet_address, n.resources_total,
                      n.resources_available, n.labels)
                for nid, n in self._nodes.items()
                if n.alive and self._node_versions.get(nid, 0) > known
            }
            removed = [nid for v, nid in self._removed_log if v > known]
            return {"status": "ok", "view_version": self._view_version,
                    "cluster_delta": delta, "removed": removed}
        # version gap (fresh raylet, or removals pruned past `known`):
        # resend everything, flagged full so the caller REPLACES its view
        return {
            "status": "ok", "view_version": self._view_version,
            "full": True,
            "cluster_delta": {
                nid: (n.raylet_address, n.resources_total,
                      n.resources_available, n.labels)
                for nid, n in self._nodes.items()
                if n.alive
            },
            "removed": [],
        }

    async def handle_get_all_node_info(self, payload):
        return list(self._nodes.values())

    async def handle_request_resources(self, payload):
        """Programmatic scale-up hint (reference:
        ray.autoscaler.sdk.request_resources — python/ray/autoscaler/
        sdk/sdk.py): the given bundle shapes become standing demand the
        autoscaler must satisfy, REPLACING any previous request (so
        request_resources() with no shapes cancels). Not persisted: like
        the reference, the hint is advisory runtime state."""
        shapes = payload.get("shapes") or []
        self._requested_resources = [
            (dict(s), 1, None) for s in shapes if s]
        return len(self._requested_resources)

    async def handle_get_cluster_load(self, payload):
        """Autoscaler snapshot: per-node usage + aggregated unfulfilled
        demand shapes (reference: GCS load feeding load_metrics.py and the
        autoscaler state API gcs_autoscaler_state_manager.cc)."""
        demands: Dict[tuple, int] = {}
        for nid, shapes in self._pending_demands.items():
            info = self._nodes.get(nid)
            if info is None or not info.alive:
                continue
            for shape, count, labels in shapes:
                from ray_tpu._private.specs import _freeze

                key = (tuple(sorted(shape.items())), _freeze(labels) or ())
                demands[key] = demands.get(key, 0) + count
        pending_pgs = []
        if self.pg_locator is not None:
            pending_pgs = self.pg_locator.pending_bundle_shapes()
        return {
            "nodes": {
                nid.hex(): {
                    "total": dict(n.resources_total),
                    "available": dict(n.resources_available),
                    "alive": n.alive,
                    "is_head": n.is_head,
                    "draining": getattr(n, "draining", False),
                    "labels": dict(n.labels),
                }
                for nid, n in self._nodes.items()
            },
            "demands": [(dict(res), v, dict(labels) or None)
                        for (res, labels), v in demands.items()]
                       + [(dict(s), c, lbl)
                          for s, c, lbl in self._requested_resources],
            "pending_pg_bundles": pending_pgs,
        }

    async def handle_check_alive(self, payload):
        node_ids = payload.get("node_ids") or list(self._nodes)
        return {nid: (nid in self._nodes and self._nodes[nid].alive) for nid in node_ids}

    # -- used by actor/pg schedulers --
    def resource_view(self) -> Dict[NodeID, Resources]:
        return {
            nid: dict(n.resources_available)
            for nid, n in self._nodes.items()
            if n.alive and not getattr(n, "draining", False)
        }

    def label_view(self) -> Dict[NodeID, Dict[str, str]]:
        return {
            nid: dict(n.labels)
            for nid, n in self._nodes.items()
            if n.alive
        }

    def raylet_address(self, node_id: NodeID) -> Optional[str]:
        info = self._nodes.get(node_id)
        return info.raylet_address if info is not None and info.alive else None

    def pick_nodes_for(self, spec: TaskSpec) -> List[NodeID]:
        """Feasible nodes for a task spec, best-first (GCS-side scheduling)."""
        strat = spec.scheduling_strategy
        alive = [n for n in self._nodes.values()
                 if n.alive and not getattr(n, "draining", False)]
        if strat.kind == "PLACEMENT_GROUP" and self.pg_locator is not None:
            info = self.pg_locator._groups.get(strat.placement_group_id)
            if info is None:
                return []
            if strat.bundle_index >= 0:
                node = info.bundle_locations.get(strat.bundle_index)
                return [node] if node is not None else []
            return list(dict.fromkeys(info.bundle_locations.values()))
        if strat.kind == "NODE_AFFINITY":
            out = [n.node_id for n in alive if n.node_id == strat.node_id]
            if out or not strat.soft:
                return out
        soft_pref: set = set()
        if strat.kind == "NODE_LABEL":
            from ray_tpu.raylet.scheduling_policy import _labels_match

            alive = [n for n in alive
                     if _labels_match(n.labels, strat.hard_labels or {})]
            # soft constraints PREFER (sort first below) but never exclude:
            # a preferred node that can't fit must fall back to the other
            # hard-eligible nodes, matching the raylet's tiered policy
            soft_pref = {
                n.node_id for n in alive
                if _labels_match(n.labels, strat.soft_labels or {})}
        candidates = [
            n.node_id
            for n in alive
            if resources_fit(n.resources_available, spec.resources)
            or resources_fit(n.resources_total, spec.resources)
        ]
        # Soft-label-preferred first, then most-available (actors spread by
        # default here; per-task fine-grained policy lives in the raylet's
        # cluster task manager).
        candidates.sort(
            key=lambda nid: (
                nid not in soft_pref,
                -sum(self._nodes[nid].resources_available.values()),
            ),
        )
        return candidates

    # -- health loop --
    async def health_check_loop(self):
        period = CONFIG.health_check_period_ms / 1000.0
        threshold = CONFIG.health_check_failure_threshold
        while True:
            await asyncio.sleep(period)
            now = time.monotonic()
            for node_id, info in list(self._nodes.items()):
                if not info.alive:
                    continue
                last = self._last_heartbeat.get(node_id, now)
                if now - last > period * threshold + CONFIG.heartbeat_period_ms / 1000.0 * threshold:
                    logger.warning("node %s missed heartbeats; marking dead",
                                   node_id.hex()[:8])
                    await self._mark_dead(node_id, expected=False)

    async def _mark_dead(self, node_id: NodeID, expected: bool):
        info = self._nodes.get(node_id)
        if info is None or not info.alive:
            return
        info.alive = False
        info.resources_available = {}
        self._view_version += 1
        self._node_versions.pop(node_id, None)
        self._removed_log.append((self._view_version, node_id))
        if len(self._removed_log) == self._removed_log.maxlen:
            # oldest retained removal sets the floor below which delta
            # requests must fall back to a full view
            self._removed_pruned_below = self._removed_log[0][0] + 1
        self._pending_demands.pop(node_id, None)
        self._last_heartbeat.pop(node_id, None)
        self._persist_node(node_id)
        self._pub.publish(ps.NODE_CHANNEL, node_id, info)
        _elog.emit("node.dead", node_id=node_id.hex(), expected=expected)
        for cb in self._death_listeners:
            try:
                await cb(node_id)
            except Exception:
                logger.exception("node-death listener failed")


class GcsKvManager:
    """Namespaced binary KV (internal KV + function/code storage)."""

    def __init__(self, store):
        self._store = store

    @staticmethod
    def _table(ns: Optional[str]) -> str:
        return "kv:" + (ns or "")

    @staticmethod
    def _key(k) -> bytes:
        """Canonical bytes keys: clients pass str or bytes freely, but a
        table mixing both would break prefix scans (str.startswith(bytes)
        raises) and make the same key a silent miss."""
        return k.encode() if isinstance(k, str) else k

    async def handle_kv_put(self, payload):
        overwrite = payload.get("overwrite", True)
        table = self._table(payload.get("namespace"))
        key = self._key(payload["key"])
        if not overwrite and self._store.get(table, key) is not None:
            return False
        self._store.put(table, key, payload["value"])
        return True

    async def handle_kv_get(self, payload):
        return self._store.get(self._table(payload.get("namespace")),
                               self._key(payload["key"]))

    async def handle_kv_multi_get(self, payload):
        table = self._table(payload.get("namespace"))
        return {k: self._store.get(table, self._key(k))
                for k in payload["keys"]}

    async def handle_kv_multi_put(self, payload):
        """Batch put (one round trip per spill batch, not per object)."""
        table = self._table(payload.get("namespace"))
        for k, v in payload["entries"].items():
            self._store.put(table, self._key(k), v)
        return True

    async def handle_kv_del(self, payload):
        table = self._table(payload.get("namespace"))
        key = self._key(payload["key"])
        if payload.get("del_by_prefix"):
            n = 0
            for k in self._store.keys(table, key):
                n += int(self._store.delete(table, k))
            return n
        return int(self._store.delete(table, key))

    async def handle_kv_keys(self, payload):
        return self._store.keys(
            self._table(payload.get("namespace")),
            self._key(payload.get("prefix", b"")))

    async def handle_kv_exists(self, payload):
        return (
            self._store.get(self._table(payload.get("namespace")),
                            self._key(payload["key"]))
            is not None
        )


class GcsJobManager:
    def __init__(self, publisher: ps.Publisher, store=None):
        self._pub = publisher
        self._store = store
        self._jobs: Dict[JobID, JobInfo] = {}
        self._counter = 0
        self._finish_listeners = []
        if store is not None:
            import pickle

            raw = store.get("meta", b"next_job_id")
            if raw is not None:
                # never reuse job ids across GCS incarnations: task/actor
                # ids embed the job id, so a reset counter would collide
                self._counter = int.from_bytes(raw, "little")
            for key in store.keys("jobs"):
                try:
                    info = pickle.loads(store.get("jobs", key))
                    self._jobs[info.job_id] = info
                except Exception:  # noqa: BLE001 — skip torn records
                    logger.warning(
                        "job recovery: skipping torn record %r", key)

    def add_finish_listener(self, cb):
        self._finish_listeners.append(cb)

    def _persist_job(self, job_id) -> None:
        if self._store is None:
            return
        import pickle

        info = self._jobs.get(job_id)
        if info is not None:
            self._store.put("jobs", job_id.binary(),
                            pickle.dumps(info, protocol=5))

    async def handle_get_next_job_id(self, payload):
        self._counter += 1
        if self._store is not None:
            self._store.put("meta", b"next_job_id",
                            self._counter.to_bytes(8, "little"))
        return JobID.from_int(self._counter)

    async def handle_add_job(self, payload):
        info: JobInfo = payload["info"]
        self._jobs[info.job_id] = info
        self._persist_job(info.job_id)
        self._pub.publish(ps.JOB_CHANNEL, info.job_id, info)
        return True

    async def handle_mark_job_finished(self, payload):
        job_id: JobID = payload["job_id"]
        info = self._jobs.get(job_id)
        if info is not None:
            info.is_dead = True
            info.end_time = time.time()
            self._persist_job(job_id)
            self._pub.publish(ps.JOB_CHANNEL, job_id, info)
        for cb in self._finish_listeners:
            try:
                await cb(job_id)
            except Exception:
                logger.exception("job-finish listener failed")
        return True

    async def handle_get_all_job_info(self, payload):
        return list(self._jobs.values())


class GcsTaskEventManager:
    """Bounded task-event buffer for the state API / timeline.

    Reference: src/ray/gcs/gcs_server/gcs_task_manager.cc fed by per-worker
    TaskEventBuffers.
    """

    def __init__(self, max_events: int = 100_000):
        self._events = deque(maxlen=max_events)

    async def handle_add_task_events(self, payload):
        self._events.extend(payload["events"])
        return True

    async def handle_get_task_events(self, payload):
        limit = payload.get("limit", 10_000)
        job_id = payload.get("job_id")
        # server-side task filter: per-task timelines must not ship the
        # whole 100k-event deque over the wire to keep a handful of rows
        task_id = payload.get("task_id")
        out = []
        for ev in reversed(self._events):
            if job_id is not None and ev.get("job_id") != job_id:
                continue
            if task_id is not None and ev.get("task_id") != task_id:
                continue
            out.append(ev)
            if len(out) >= limit:
                break
        return out


class GcsEventManager:
    """Cluster-wide structured lifecycle event store (the generalized
    sibling of GcsTaskEventManager; reference lineage: gcs_task_manager.cc
    fed by per-worker buffers — here fed by every process's
    _private/event_log flusher).

    Thread-safe: the embedded deployment's direct sink appends from the
    event-log flusher THREAD while handlers read on the gcs-io loop.
    """

    def __init__(self, max_events: int = 200_000):
        self._events = deque(maxlen=max_events)
        self._lock = threading.Lock()
        # "<source>#<pid>" -> last flush stats (depth / dropped / emitted)
        self._sources: Dict[str, dict] = {}
        self._type_counts: Dict[str, int] = {}

    def add_local(self, events: List[dict], stats: Optional[dict]) -> None:
        """Direct sink for an in-process event_log (embedded head node):
        same path the RPC handler takes, minus the wire."""
        with self._lock:
            for ev in events:
                self._events.append(ev)
                t = ev.get("type", "?")
                self._type_counts[t] = self._type_counts.get(t, 0) + 1
            if stats:
                # keyed by pid: a process whose label refines during
                # bring-up ("proc:N" -> "driver:N") stays one row
                now = time.time()
                self._sources[stats.get("pid")] = dict(
                    stats, received=now)
                if len(self._sources) > 512:
                    # worker churn: age out sources silent past the
                    # staleness window (stats reporting marks them stale
                    # first), evicting oldest-first past the cap so dead
                    # pids can't grow this forever (and a recycled pid
                    # can't inherit a dead process's counters for long)
                    for pid, _ in sorted(
                            self._sources.items(),
                            key=lambda kv: kv[1].get("received", 0.0)
                    )[:len(self._sources) - 512]:
                        self._sources.pop(pid, None)

    async def handle_add_cluster_events(self, payload):
        self.add_local(payload.get("events") or [],
                       payload.get("stats"))
        return True

    async def handle_get_cluster_events(self, payload):
        """Filtered query, newest-first (callers re-sort for timelines).
        Filters: type (glob), task_id/actor_id/node_id/object_id (exact),
        since (wall time), limit."""
        limit = payload.get("limit", 10_000)
        type_glob = payload.get("type")
        since = payload.get("since")
        id_filters = [(k, payload[k]) for k in
                      ("task_id", "actor_id", "node_id", "object_id",
                       "trace_id")
                      if payload.get(k)]
        out = []
        stale_run = 0
        with self._lock:
            events = list(self._events)
        for ev in reversed(events):
            if since is not None and ev.get("time", 0) < since:
                # Arrival order only approximates event time, so one
                # stale event must not stop the scan — but a long
                # CONSECUTIVE run of them means we are past any
                # realistic flush-lag inversion and the rest of the
                # deque is history. Without this, every 1s preempt
                # watcher poll scans the full 100k ring even when the
                # cluster is idle.
                stale_run += 1
                if stale_run >= 2048:
                    break
                continue
            stale_run = 0
            if type_glob and not fnmatchcase(ev.get("type", ""), type_glob):
                continue
            if any(ev.get(k) != v for k, v in id_filters):
                continue
            out.append(ev)
            if len(out) >= limit:
                break
        return out

    async def handle_get_event_log_stats(self, payload):
        """Pipeline visibility: per-source buffer depth / flush lag /
        cumulative drops (so silent drops are visible in `ray-tpu
        status`), plus per-type totals."""
        now = time.time()
        with self._lock:
            # prune sources silent for >10min: exited workers must not
            # read as ever-worsening flush lag forever (a WEDGED live
            # process still shows up — its own gauges keep exporting
            # locally, and it stays listed as stale for the full window)
            for pid in [p for p, st in self._sources.items()
                        if now - st.get("received", now) > 600.0]:
                self._sources.pop(pid, None)
            return {
                "total_events": len(self._events),
                "by_type": dict(self._type_counts),
                "sources": {
                    f"{st.get('source')}#{pid}": {
                        "depth": st.get("depth", 0),
                        "dropped": st.get("dropped", 0),
                        "emitted": st.get("emitted", 0),
                        "flush_lag_s": max(0.0, now - st.get(
                            "received", now)),
                        "stale": now - st.get("received", now) > 30.0,
                    }
                    for pid, st in self._sources.items()
                },
            }


class GcsSpanManager:
    """Cluster-wide span store for distributed request tracing (ISSUE 11)
    — the tracing sibling of GcsEventManager, fed by every process's
    _private/tracing span flusher.

    Two tiers implement tail-based sampling at the collector:

    * durable store — spans of head-SAMPLED traces, and of traces that
      were FORCE-kept (error / deadline expired / shed / latency p99
      breach anywhere in the cluster);
    * provisional ring — spans of unsampled traces, held in arrival
      order until a force marker promotes their trace or they age out of
      the bounded ring. `ray-tpu trace <id>` reads both, so a just-served
      request is inspectable even at sample rate 0 while storage stays
      bounded.

    Profile spans (util.tracing trace_span — no trace id) land in their
    own ring feeding the cluster-wide `ray-tpu timeline`.

    Thread-safe: the embedded head's direct sink appends from the span-
    flusher thread while handlers read on the gcs-io loop.
    """

    def __init__(self, max_spans: Optional[int] = None,
                 provisional_max: Optional[int] = None,
                 profile_max: Optional[int] = None):
        # Both tiers are trace-id-INDEXED (OrderedDict of trace_id ->
        # span list, oldest trace first), bounded by TOTAL span count
        # with whole-trace eviction. The index keeps every store
        # operation O(one trace): promotion is a dict pop, get_trace a
        # dict read, eviction pops oldest traces — a flat deque made all
        # three O(store-size) Python scans on the gcs-io loop / under
        # the ingestion lock, which stalled every GCS RPC and every span
        # flusher once the store neared its 250k-span capacity.
        self._max_spans = max_spans or CONFIG.trace_store_max_spans
        self._provisional_max = (provisional_max
                                 or CONFIG.trace_provisional_max_spans)
        self._spans: "OrderedDict[str, List[dict]]" = OrderedDict()
        self._span_count = 0
        self._provisional: "OrderedDict[str, List[dict]]" = OrderedDict()
        self._provisional_count = 0
        self._profile = deque(maxlen=profile_max
                              or CONFIG.trace_profile_max_spans)
        self._forced: "OrderedDict[str, str]" = OrderedDict()
        self._lock = threading.Lock()
        self._sources: Dict[int, dict] = {}
        self._received = 0

    def add_local(self, spans: List[dict], forced: Optional[list],
                  stats: Optional[dict]) -> None:
        """Direct sink for an in-process tracing buffer (embedded head):
        same path the RPC handler takes, minus the wire."""
        with self._lock:
            for trace_id, reason in forced or ():
                if trace_id not in self._forced:
                    self._forced[trace_id] = reason
                    while len(self._forced) > 4096:
                        self._forced.popitem(last=False)
                    self._promote_locked(trace_id)
            for span in spans or ():
                self._received += 1
                trace_id = span.get("trace_id")
                if trace_id is None:
                    self._profile.append(span)
                elif span.get("sampled") or trace_id in self._forced:
                    self._spans.setdefault(trace_id, []).append(span)
                    self._span_count += 1
                else:
                    self._provisional.setdefault(trace_id,
                                                 []).append(span)
                    self._provisional_count += 1
            # whole-trace eviction, oldest (first-span arrival) first
            while (self._span_count > self._max_spans
                   and len(self._spans) > 1):
                _, evicted = self._spans.popitem(last=False)
                self._span_count -= len(evicted)
            while (self._provisional_count > self._provisional_max
                   and len(self._provisional) > 1):
                _, evicted = self._provisional.popitem(last=False)
                self._provisional_count -= len(evicted)
            if stats:
                self._sources[stats.get("pid")] = dict(stats,
                                                       received=time.time())
                if len(self._sources) > 512:
                    for pid, _ in sorted(
                            self._sources.items(),
                            key=lambda kv: kv[1].get("received", 0.0)
                    )[:len(self._sources) - 512]:
                        self._sources.pop(pid, None)

    def _promote_locked(self, trace_id: str) -> None:
        # O(one trace): failure bursts fire one promotion per refused
        # request, so this must never scan the whole provisional tier
        keep = self._provisional.pop(trace_id, None)
        if keep:
            self._provisional_count -= len(keep)
            self._spans.setdefault(trace_id, []).extend(keep)
            self._span_count += len(keep)

    async def handle_add_spans(self, payload):
        self.add_local(payload.get("spans") or [],
                       payload.get("forced") or [],
                       payload.get("stats"))
        return True

    async def handle_get_trace(self, payload):
        """Every stored span of one trace (durable + provisional),
        ordered by start time, plus the force verdict."""
        trace_id = payload.get("trace_id")
        with self._lock:
            spans = list(self._spans.get(trace_id) or ())
            spans += self._provisional.get(trace_id) or ()
            forced_reason = self._forced.get(trace_id)
        # a span can reach both tiers across a promotion/flush race
        seen = set()
        out = []
        for s in sorted(spans, key=lambda s: (s.get("start", 0.0),
                                              s.get("span_id") or "")):
            key = s.get("span_id")
            if key in seen:
                continue
            seen.add(key)
            out.append(s)
        return {"trace_id": trace_id, "spans": out,
                "forced": forced_reason is not None,
                "forced_reason": forced_reason}

    async def handle_list_traces(self, payload):
        """Newest-first trace summaries from the durable store (sampled +
        force-kept traces — the ones worth listing). Only the newest
        `limit` traces are summarized — the store can hold thousands."""
        limit = payload.get("limit", 100)
        with self._lock:
            newest = list(self._spans.keys())[-limit:]
            groups = [(tid, list(self._spans[tid])) for tid in newest]
            forced = dict(self._forced)
        rows = []
        for trace_id, spans in groups:
            span_ids = {s.get("span_id") for s in spans}
            # root = the earliest span whose parent never arrived; a
            # client-originated trace has NO parentless span here (the
            # proxy's span is a child of the client's), so "parent not
            # stored" is the right rule, same as build_span_tree
            roots = [s for s in spans
                     if s.get("parent_id") not in span_ids]
            roots.sort(key=lambda s: s.get("start", 0.0))
            rows.append({
                "trace_id": trace_id,
                "root": roots[0].get("name") if roots else None,
                "spans": len(spans),
                "procs": sorted({s.get("proc", "?") for s in spans}),
                "start": min(s.get("start", 0.0) for s in spans),
                "duration_s": max(0.0, max(s.get("end", 0.0)
                                           for s in spans)
                                  - min(s.get("start", 0.0)
                                        for s in spans)),
                "forced_reason": forced.get(trace_id),
            })
        rows.sort(key=lambda t: -t["start"])
        return rows

    async def handle_get_profile_spans(self, payload):
        """Cluster-wide profile spans (util.tracing) for the timeline —
        the spans the old process-local-only path silently dropped for
        every non-driver process."""
        limit = payload.get("limit", 10_000)
        with self._lock:
            out = list(self._profile)
        return out[-limit:]

    async def handle_get_span_stats(self, payload):
        now = time.time()
        with self._lock:
            return {
                "spans": self._span_count,
                "provisional": self._provisional_count,
                "traces": len(self._spans),
                "profile": len(self._profile),
                "forced_traces": len(self._forced),
                "received": self._received,
                "sources": {
                    f"{st.get('source')}#{pid}": {
                        "depth": st.get("depth", 0),
                        "dropped": st.get("dropped", 0),
                        "recorded": st.get("recorded", 0),
                        "flush_lag_s": max(0.0, now - st.get(
                            "received", now)),
                    }
                    for pid, st in self._sources.items()
                },
            }


class GcsServer:
    """Assembles all managers onto one RpcServer + loop."""

    def __init__(self, host: str = "127.0.0.1", storage_path: str = "",
                 external_store: str = ""):
        self._lt = EventLoopThread("gcs-io")
        self._server = RpcServer(self._lt, host, label="gcs")
        self._pool = ClientPool(self._lt, peer_meta={"label": "gcs"},
                                label="gcs")
        self.publisher = ps.Publisher(self._lt)
        # Set when the external-store failure detector fires; a supervisor
        # (or the standalone main) watches this to take the GCS down so it
        # can be restarted against a healthy store (reference:
        # gcs_redis_failure_detector.h:34 FATALs the GCS).
        self.store_down = False
        store = make_store(storage_path or CONFIG.gcs_storage_path,
                           external_address=(external_store
                                             or CONFIG.gcs_external_store),
                           on_down=self._on_store_down)
        self._store = store
        self.node_manager = GcsNodeManager(self.publisher, store=store)
        self.kv_manager = GcsKvManager(store)
        self.job_manager = GcsJobManager(self.publisher, store=store)
        self.actor_manager = GcsActorManager(
            self.node_manager, self.publisher, self._pool, store=store)
        self.pg_manager = GcsPlacementGroupManager(
            self.node_manager, self.publisher, self._pool, store=store)
        # pubsub subscriptions persist so a restarted GCS resumes pushing
        # actor/node/log events without clients re-subscribing; dead
        # subscribers prune back OUT of the table when a push fails, so
        # worker churn can't grow it without bound
        self.publisher.on_drop = lambda channel, addr: store.delete(
            "pubsub", f"{channel}|{addr}".encode())
        for key in store.keys("pubsub"):
            try:
                channel, addr = key.decode().split("|", 1)
                self.publisher.subscribe(channel, addr)
            except Exception:  # noqa: BLE001 — skip torn records
                logger.warning(
                    "pubsub recovery: skipping torn subscription %r", key)
        self.task_event_manager = GcsTaskEventManager()
        self.event_manager = GcsEventManager()
        self.span_manager = GcsSpanManager()
        self.metrics_manager = GcsMetricsManager(self.node_manager,
                                                 self.event_manager)
        # The head process's lifecycle events skip the wire entirely; the
        # token scopes teardown so a later sink owner isn't clobbered.
        self._event_sink_token = event_log.set_sink(
            self.event_manager.add_local)
        self._span_sink_token = _tracing.set_span_sink(
            self.span_manager.add_local)
        self.node_manager.pg_locator = self.pg_manager
        self.node_manager.add_death_listener(self.actor_manager.on_node_death)
        self.node_manager.add_death_listener(self.pg_manager.on_node_death)
        self.job_manager.add_finish_listener(self.actor_manager.on_job_finished)
        self.address: Optional[str] = None
        self._health_task = None
        self._slo_eval_task = None

    def start(self, port: int = 0) -> str:
        for mgr in (
            self.node_manager,
            self.kv_manager,
            self.job_manager,
            self.actor_manager,
            self.pg_manager,
            self.task_event_manager,
            self.event_manager,
            self.span_manager,
            self.metrics_manager,
        ):
            self._server.register_all(mgr)
        self._server.register("drain_node", self._handle_drain_node)
        self._server.register("preempt_node", self._handle_preempt_node)
        self._server.register("subscribe", self._handle_subscribe)
        self._server.register("unsubscribe", self._handle_unsubscribe)
        self._server.register("gcs_ping", self._handle_ping)
        self._server.register("publish_logs", self._handle_publish_logs)
        self._server.register("report_error", self._handle_report_error)
        self._server.register("get_cluster_memory",
                              self._handle_get_cluster_memory)
        self._server.register("chaos_start", self._handle_chaos_start)
        self._server.register("chaos_stop", self._handle_chaos_stop)
        self._server.register("chaos_status", self._handle_chaos_status)
        self.address = self._server.start(port)
        self._pool.set_local_id(self.address)
        self._health_task = self._lt.submit(self.node_manager.health_check_loop())
        self._slo_eval_task = self._lt.submit(self.metrics_manager.eval_loop())
        # resume actors/PGs that were mid-schedule when a previous GCS
        # incarnation stopped (no-ops on a fresh start)
        self._lt.loop.call_soon_threadsafe(self.actor_manager.recover)
        self._lt.loop.call_soon_threadsafe(self.pg_manager.recover)
        return self.address

    async def _handle_drain_node(self, payload):
        """Graceful drain entry point (reference: `ray drain-node` →
        GcsNodeManager DrainNode). Marks the node draining (excluded from
        GCS-side scheduling immediately) and forwards the drain to its
        raylet, which stops leasing and unregisters once idle."""
        nid: NodeID = payload["node_id"]
        info = self.node_manager._nodes.get(nid)
        if info is None or not info.alive:
            return {"status": "not_found"}
        if info.draining:
            # a drain/preempt is already in flight. Proceeding would be
            # actively destructive during a PREEMPT notice window: the
            # bundle teardown below would kill a training gang
            # mid-checkpoint-drain, and the rollback branch could clear
            # the preempt's scheduling exclusion.
            return {"status": "already_draining"}
        info.draining = True
        self.node_manager._bump_node(nid)
        try:
            reply = await self._pool.get(info.raylet_address).call_async(
                "drain_node",
                {"reason": payload.get("reason", ""),
                 "deadline_s": payload.get("deadline_s", 300.0)},
                timeout=10.0)
        except Exception as e:  # noqa: BLE001 — report, don't crash the GCS
            # the raylet never received the drain: undo the mark, or the
            # node would be excluded from scheduling forever while still
            # accepting direct leases (half-drained wedge)
            info.draining = False
            self.node_manager._bump_node(nid)
            return {"status": "unreachable", "error": str(e)}
        # Re-place any placement-group bundles living on the draining node
        # (reference: drain reschedules bundles like node removal). Leases
        # targeted at those bundles would otherwise spin on 'draining'
        # rejections behind unrelated work until the deadline. This kills
        # the bundles' leased workers on the drained node (cancel_bundles);
        # gang actors restart with their group elsewhere.
        await self.pg_manager.on_node_death(nid)
        return {"status": "ok", "raylet": reply}

    async def _handle_preempt_node(self, payload):
        """Preemptible-TPU advance notice (the announced-node-loss sibling
        of drain_node): the node is excluded from scheduling immediately
        and its raylet stops leasing, but — unlike drain — its placement-
        group bundles are NOT torn down up front. The notice window
        belongs to the workloads: training gangs checkpoint-and-drain
        (train/_internal/backend_executor watches for the
        node.preempt_notice event), serve replicas deregister-then-drain
        (serve controller), and only at the deadline does the raylet kill
        stragglers and unregister. Bundles re-place through the normal
        node-death listener when the node leaves."""
        nid: NodeID = payload["node_id"]
        deadline_s = float(payload.get("deadline_s", 30.0))
        reason = payload.get("reason", "preemption")
        info = self.node_manager._nodes.get(nid)
        if info is None or not info.alive:
            return {"status": "not_found"}
        if info.draining:
            # a drain_node/preempt_node is already in flight — do NOT
            # re-notify, and (crucially) never let this call's rollback
            # clear the exclusion the earlier operation installed
            return {"status": "already_draining"}
        info.draining = True
        self.node_manager._bump_node(nid)
        try:
            reply = await self._pool.get(info.raylet_address).call_async(
                "preempt_notice",
                {"deadline_s": deadline_s, "reason": reason},
                timeout=10.0)
        except Exception as e:  # noqa: BLE001 — report, don't crash the GCS
            if isinstance(e, ConnectionLost) and not e.maybe_delivered:
                # the raylet provably never got the notice: undo the
                # scheduling exclusion (same half-drained-wedge hazard
                # as drain_node)
                info.draining = False
                self.node_manager._bump_node(nid)
                return {"status": "unreachable", "error": str(e)}
            # Timeout / mid-call reset: the raylet MAY already be draining
            # (it rejects its lease queue and arms the deadline on
            # receipt). Keep the exclusion — leasing onto a node that
            # rejects everything and kills itself at the deadline is
            # worse than an idle one.
            return {"status": "unknown", "error": str(e)}
        # The raylet is the single emitter of node.preempt_notice (on
        # receipt, before it touches its queue): one event per notice,
        # and none at all when the notice provably never took effect.
        return {"status": "ok", "deadline_s": deadline_s, "raylet": reply}

    async def _handle_get_cluster_memory(self, payload):
        """Cluster-wide memory aggregation (ISSUE 16): every alive
        raylet's node_memory_report (arena + spill + per-worker reference
        tables), fanned out CONCURRENTLY — per-node failures land in-band
        so one partitioned node degrades the report instead of timing the
        whole call out. Callers (`ray-tpu memory`, the state API, the
        leak sweep) merge their own driver-side report on top: drivers
        register with the GCS, not a raylet worker pool."""
        payload = payload or {}
        node_timeout = float(payload.get("node_timeout_s", 30.0))
        sub = {"refs": bool(payload.get("refs", True)),
               "worker_timeout_s": float(payload.get("worker_timeout_s",
                                                     10.0))}
        nodes = self._alive_raylets()

        async def _one(addr):
            try:
                return await self._pool.get(addr).call_async(
                    "node_memory_report", dict(sub), timeout=node_timeout)
            except Exception as e:  # noqa: BLE001 — node mid-death
                return {"error": str(e)}

        replies = await asyncio.gather(*(_one(addr) for _, addr in nodes))
        return {"nodes": {nid.hex(): reply
                          for (nid, _), reply in zip(nodes, replies)}}

    # -- chaos control plane (`ray-tpu chaos`, ray_tpu.chaos) -----------------

    def _alive_raylets(self):
        return [(nid, info.raylet_address)
                for nid, info in self.node_manager._nodes.items()
                if info.alive]

    async def _chaos_fanout(self, method: str, payload: dict) -> dict:
        """Relay a chaos op to every alive raylet CONCURRENTLY; per-node
        outcome map. Unreachable/partitioned nodes report as errors and
        cost one shared 5s timeout, not 5s each — `chaos stop` on a
        half-partitioned cluster must not leave faults firing for
        N_dead*5s while it crawls the node list."""
        nodes = self._alive_raylets()

        async def _one(addr):
            try:
                return await self._pool.get(addr).call_async(
                    method, dict(payload, scope="local"), timeout=5.0)
            except Exception as e:  # noqa: BLE001 — chaos bites its own tail
                return {"status": "unreachable", "error": str(e)}

        replies = await asyncio.gather(*(_one(addr) for _, addr in nodes))
        return {nid.hex()[:12]: reply
                for (nid, _), reply in zip(nodes, replies)}

    async def _handle_chaos_start(self, payload):
        from ray_tpu._private import fault_injection as fi

        plan_json = payload["plan"]
        plan = fi.ChaosPlan.from_json(plan_json)  # validate before fan-out
        nodes = {}
        if payload.get("scope", "cluster") == "cluster":
            nodes = await self._chaos_fanout("chaos_start",
                                             {"plan": plan_json})
        fi.install(plan)  # install on the GCS LAST so the fan-out itself
        # is never subject to the plan it is installing
        return {"status": "installed", "seed": plan.seed,
                "rules": len(plan.rules), "nodes": nodes}

    async def _handle_chaos_stop(self, payload):
        from ray_tpu._private import fault_injection as fi

        plan = fi.uninstall()  # uninstall FIRST so the fan-out runs clean
        nodes = {}
        if payload.get("scope", "cluster") == "cluster":
            nodes = await self._chaos_fanout("chaos_stop", {})
        return {"status": "uninstalled",
                "stats": plan.stats() if plan else None, "nodes": nodes}

    async def _handle_chaos_status(self, payload):
        from ray_tpu._private import fault_injection as fi

        plan = fi.active_plan()
        nodes = {}
        if payload.get("scope", "cluster") == "cluster":
            nodes = await self._chaos_fanout("chaos_status", {})
        return {"installed": plan is not None,
                "stats": plan.stats() if plan else None, "nodes": nodes}

    async def _handle_subscribe(self, payload):
        channel = payload["channel"]
        addr = payload["subscriber_address"]
        self.publisher.subscribe(channel, addr)
        self._store.put("pubsub", f"{channel}|{addr}".encode(), b"1")
        return True

    async def _handle_unsubscribe(self, payload):
        addr = payload["subscriber_address"]
        if payload.get("all"):
            self.publisher.unsubscribe_all(addr)
            for key in self._store.keys("pubsub"):
                if key.decode().split("|", 1)[1] == addr:
                    self._store.delete("pubsub", key)
        else:
            self.publisher.unsubscribe(payload["channel"], addr)
            self._store.delete(
                "pubsub", f"{payload['channel']}|{addr}".encode())
        return True

    async def _handle_ping(self, payload):
        # store_down surfaces the external-store failure detector to
        # embedded deployments and `ray-tpu healthcheck`: a supervisor that
        # cannot watch the attribute can still poll the ping
        return {"status": "degraded" if self.store_down else "ok",
                "time": time.time(), "store_down": self.store_down}

    def _on_store_down(self) -> None:
        self.store_down = True
        logger.critical(
            "external GCS store unreachable past the failure-detector "
            "window; GCS state writes are stalled — restart the GCS "
            "against a healthy store")

    async def _handle_publish_logs(self, payload):
        """Raylet log monitors push worker-log batches here; fan out to
        every subscribed driver (reference: the LOG pubsub channel that
        worker.py:2003 print_worker_logs consumes)."""
        self.publisher.publish(ps.LOG_CHANNEL, payload.get("node"), payload)
        return True

    async def _handle_report_error(self, payload):
        """Task/actor errors pushed by workers; fan out to drivers
        (reference: ERROR channel, worker.py:2115 listen_error_messages)."""
        self.publisher.publish(
            ps.ERROR_CHANNEL, payload.get("job_id"), payload)
        return True

    def stop(self):
        event_log.flush(timeout=0.5)  # pull in the head's own tail events
        event_log.clear_sink(self._event_sink_token)
        _tracing.flush_spans(timeout=0.5)
        _tracing.clear_span_sink(self._span_sink_token)
        if self._health_task is not None:
            self._health_task.cancel()
        if self._slo_eval_task is not None:
            self._slo_eval_task.cancel()
        self.metrics_manager.stop()
        self.publisher.close()
        self._pool.close_all()
        self._server.stop()
        self._lt.stop()
        close = getattr(self._store, "close", None)
        if close is not None:
            close()


def main():
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--port", type=int, default=6380)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--storage-path", default="")
    parser.add_argument("--external-store", default="",
                        help="host:port of an ExternalStoreServer "
                             "(gcs/external_store.py); overrides "
                             "--storage-path")
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)
    event_log.set_default_proc_label("gcs")
    event_log.install_flight_recorder(on_exit=True)
    server = GcsServer(host=args.host, storage_path=args.storage_path,
                       external_store=args.external_store)
    addr = server.start(args.port)
    logger.info("GCS serving at %s", addr)
    try:
        while not server.store_down:
            time.sleep(1.0)
        # reference behavior: the redis failure detector FATALs the GCS so
        # a supervisor restarts it against a healthy store
        logger.critical("exiting: external store failure detector fired")
        server.stop()
        raise SystemExit(1)
    except KeyboardInterrupt:
        server.stop()


if __name__ == "__main__":
    main()
