"""External GCS persistence: a standalone KV store process + store client.

Reference: ray parks GCS state in external Redis
(src/ray/gcs/store_client/redis_store_client.cc) so a replacement head can
recover the whole cluster after losing its own disk, and watches the store
with a failure detector (src/ray/gcs/gcs_server/gcs_redis_failure_detector.h:34)
that takes the GCS down when the store is unreachable so a supervisor can
restart it somewhere healthy.

This module is the single-language equivalent: `ExternalStoreServer` is a
small authoritative KV process (same asyncio RPC stack as the rest of the
control plane; it may itself persist to an append-log on ITS disk, which can
live on a different host than the GCS head). `ExternalStore` is the GCS-side
client: reads come from a full in-memory mirror (same read performance as
the in-memory store), mutations are shipped in order to the external server
by a write-behind batcher — matching the reference's async Redis writes —
and a ping-based failure detector fires `on_down` after a configurable
window of unreachability.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from ray_tpu._private.config import CONFIG
from ray_tpu._private.rpc import EventLoopThread, RpcClient, RpcServer
from ray_tpu.gcs.storage import _OP_PUT, InMemoryStore, make_store

logger = logging.getLogger(__name__)


class ExternalStoreServer:
    """Authoritative KV server holding the cluster's GCS state.

    Run it on a host other than the GCS head (or at minimum as a separate
    process) and point the GCS at it via RT_GCS_EXTERNAL_STORE; then head
    disk loss no longer loses the cluster. With `storage_path` set, the
    server additionally journals to its own append-log so IT can restart
    in place too.
    """

    def __init__(self, host: str = "127.0.0.1", storage_path: str = ""):
        self._lt = EventLoopThread("xstore-io")
        self._server = RpcServer(self._lt, host)
        self._store = make_store(storage_path)
        self.address: Optional[str] = None

    def start(self, port: int = 0) -> str:
        self._server.register("xs_apply", self._handle_apply)
        self._server.register("xs_dump", self._handle_dump)
        self._server.register("xs_ping", self._handle_ping)
        self.address = self._server.start(port)
        return self.address

    async def _handle_apply(self, payload):
        records: List[Tuple[int, str, bytes, bytes]] = payload["records"]
        for op, table, key, value in records:
            if op == _OP_PUT:
                self._store.put(table, key, value)
            else:
                self._store.delete(table, key)
        return len(records)

    async def _handle_dump(self, payload):
        return {t: self._store.get_all(t) for t in list(self._store._tables)}

    async def _handle_ping(self, payload):
        return {"status": "ok", "time": time.time()}

    def stop(self):
        self._server.stop()
        self._lt.stop()
        close = getattr(self._store, "close", None)
        if close is not None:
            close()


class ExternalStore(InMemoryStore):
    """GCS store client backed by an ExternalStoreServer.

    Reads hit the local mirror. Mutations are WRITE-THROUGH by default:
    while the store is reachable, `put`/`delete` return only after the
    external server acks, so state a client observed as committed survives
    a head crash (the reference replies from the Redis write callback for
    the same reason). The inline write runs on the caller's thread — for
    the GCS that is the gcs-io loop, which therefore pays one store RTT
    per mutation (same shape as FileBackedStore's fsync-per-append) and at
    most `gcs_external_store_inline_timeout_s` ONCE when the store first
    dies. While the store is unreachable, mutations divert to an ordered,
    bounded retry queue drained by the shipper thread on recovery — during
    that window acks are NOT durable (loss window = outage duration,
    bounded by the failure detector firing `on_down`).
    `gcs_external_store_write_through=False` selects write-behind batching
    (faster, crash loses the unshipped tail). Recovery = full `xs_dump` at
    construction, so a brand-new GCS on a brand-new host reconstructs the
    whole cluster state from the external server alone.
    """

    BATCH = 512

    def __init__(self, address: str,
                 on_down: Optional[Callable[[], None]] = None):
        super().__init__()
        self._address = address
        self._on_down = on_down
        self._lt = EventLoopThread("xstore-client")
        self._client = RpcClient(address, self._lt)
        # Seed the mirror from the authoritative copy (recovery path).
        dump: Dict[str, Dict[bytes, bytes]] = self._client.call(
            "xs_dump", {}, timeout=CONFIG.gcs_external_store_op_timeout_s)
        with self._lock:
            self._tables = {t: dict(kv) for t, kv in dump.items()}
        # bounded by gcs_external_store_max_queue at enqueue time (the
        # shipper drops-oldest past it while the store is down)
        self._queue: deque = deque()  # raylint: disable=unbounded-queue
        self._cv = threading.Condition()
        self._inflight = 0
        self._closed = False
        self._down_since: Optional[float] = None
        self._down_fired = False
        self._shipper = threading.Thread(
            target=self._ship_loop, name="xstore-shipper", daemon=True)
        self._shipper.start()

    # -- mutation shipping ---------------------------------------------------

    def put(self, table: str, key: bytes, value: bytes) -> None:
        self._check_capacity()
        super().put(table, key, value)

    def delete(self, table: str, key: bytes) -> bool:
        self._check_capacity()
        return super().delete(table, key)

    def _check_capacity(self) -> None:
        # refuse BEFORE mutating the local mirror: raising after the
        # mirror write would leave live state permanently ahead of the
        # authoritative copy. Refusal is the reference's behavior too —
        # a dead Redis stalls GCS writes until the failure detector kills
        # the server.
        with self._cv:
            if len(self._queue) >= CONFIG.gcs_external_store_max_queue:
                raise RuntimeError(
                    "external GCS store unreachable and retry queue full")

    def _append(self, op: int, table: str, key: bytes, value: bytes) -> None:
        # called under InMemoryStore._lock, which serializes all mutations
        rec = (op, table, key, value)
        if not CONFIG.gcs_external_store_write_through:
            with self._cv:
                self._queue.append(rec)
                self._cv.notify()
            return
        with self._cv:
            if self._queue or self._inflight or self._down_since is not None:
                # a backlog exists (store down or recovering): never ship
                # inline ahead of queued records — order must hold
                self._queue.append(rec)
                self._cv.notify()
                return
        try:
            self._client.call(
                "xs_apply", {"records": [rec]},
                timeout=CONFIG.gcs_external_store_inline_timeout_s)
        except Exception as e:  # noqa: BLE001 — divert to the retry queue
            with self._cv:
                if self._down_since is None:
                    self._down_since = time.monotonic()
                    logger.warning(
                        "external GCS store write failed (queued for "
                        "retry): %s", e)
                self._queue.append(rec)
                self._cv.notify()

    def _ship_loop(self) -> None:
        ping_interval = CONFIG.gcs_external_store_ping_interval_s
        while True:
            with self._cv:
                while not self._queue and not self._closed:
                    if not self._cv.wait(timeout=ping_interval):
                        break  # idle: fall through to a health ping
                if self._closed and not self._queue:
                    return
                batch = []
                while self._queue and len(batch) < self.BATCH:
                    batch.append(self._queue.popleft())
                self._inflight = len(batch)
            try:
                if batch:
                    self._client.call(
                        "xs_apply", {"records": batch},
                        timeout=CONFIG.gcs_external_store_op_timeout_s)
                else:
                    self._client.call(
                        "xs_ping", {},
                        timeout=CONFIG.gcs_external_store_op_timeout_s)
                with self._cv:
                    # reset under the cv: _append's divert path does a
                    # check-then-set on _down_since from writer threads,
                    # and a reset torn across its check would restart the
                    # down clock mid-outage (detector never fires)
                    self._down_since = None
                    self._down_fired = False
                    self._inflight = 0
                    self._cv.notify_all()
            except Exception as e:  # noqa: BLE001 — store unreachable
                if self._closed:
                    return
                now = time.monotonic()
                fire = False
                with self._cv:
                    # requeue IN ORDER ahead of anything newer
                    self._queue.extendleft(reversed(batch))
                    self._inflight = 0
                    # check-then-set under the cv, same as _append's
                    # divert path: torn against it, a concurrent writer
                    # could re-arm _down_since mid-outage or the detector
                    # could fire twice for one outage
                    if self._down_since is None:
                        self._down_since = now
                        logger.warning(
                            "external GCS store unreachable: %s", e)
                    down_for = now - self._down_since
                    if (not self._down_fired and down_for
                            >= CONFIG.gcs_external_store_down_after_s):
                        self._down_fired = True
                        fire = True
                if fire:
                    # callback OUTSIDE the cv: it is user code and may
                    # block or call back into the store
                    logger.critical(
                        "external GCS store down for %.0fs — failure "
                        "detector fired (reference: "
                        "gcs_redis_failure_detector.h:34)", down_for)
                    if self._on_down is not None:
                        try:
                            self._on_down()
                        except Exception:  # noqa: BLE001
                            logger.exception("on_down callback failed")
                time.sleep(min(1.0, ping_interval))

    # -- utilities -----------------------------------------------------------

    def flush(self, timeout: float = 30.0) -> bool:
        """Block until every queued mutation has been acked (tests, stop)."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._queue or self._inflight:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cv.notify()
                self._cv.wait(timeout=min(0.1, remaining))
        return True

    def ping(self) -> bool:
        try:
            self._client.call("xs_ping", {}, timeout=2.0)
            return True
        except Exception:  # noqa: BLE001
            return False

    def close(self) -> None:
        self.flush(timeout=5.0)
        self._closed = True
        with self._cv:
            self._cv.notify_all()
        self._shipper.join(timeout=5.0)
        try:
            self._client.close()
        except Exception:  # noqa: BLE001 — peer may already be gone
            logger.debug("external-store client close failed", exc_info=True)
        self._lt.stop()


def main():
    import argparse

    parser = argparse.ArgumentParser(
        description="Standalone external GCS KV store (Redis-equivalent)")
    parser.add_argument("--port", type=int, default=6381)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--storage-path", default="",
                        help="append-log path for the server's own restarts")
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)
    server = ExternalStoreServer(host=args.host,
                                 storage_path=args.storage_path)
    addr = server.start(args.port)
    logger.info("external GCS store serving at %s", addr)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.stop()


if __name__ == "__main__":
    main()
