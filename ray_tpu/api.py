"""Public API: init / remote / get / put / wait / kill / cancel / get_actor.

Reference: ray python/ray/_private/worker.py — init (:1216), get (:2550),
put (:2662), wait (:2727), get_actor (:2873), kill (:2908), cancel (:2939),
remote decorator (:3119+); process bring-up mirrors _private/node.py:37
(head = GCS + raylet + driver connect, see SURVEY §3.1) except that the head
node's GCS and raylet run as in-process services on their own event loops
rather than separate OS processes (workers are real subprocesses).
"""

from __future__ import annotations

import atexit
import logging
import os
import threading
from typing import Any, List, Optional, Sequence, Union

from ray_tpu import exceptions as exc
from ray_tpu._private.config import CONFIG
from ray_tpu._private.ids import ActorID
from ray_tpu._raylet import ObjectRef, ObjectRefGenerator, get_core_worker, global_state
from ray_tpu.actor import ActorClass, ActorHandle
from ray_tpu.remote_function import RemoteFunction

logger = logging.getLogger(__name__)

_init_lock = threading.RLock()
_global_node = None  # _HeadNode | None


class _HeadNode:
    """In-process head: GCS + head raylet (SURVEY §3.1 process layout)."""

    def __init__(self, num_cpus=None, resources=None, _system_config=None,
                 object_store_memory=None, include_dashboard=False):
        from ray_tpu.gcs.server import GcsServer
        from ray_tpu.raylet.raylet import Raylet

        if _system_config:
            CONFIG.apply_system_config(_system_config)
        self.gcs = GcsServer()
        self.gcs_address = self.gcs.start(0)
        node_resources = dict(resources or {})
        if num_cpus is not None:
            node_resources["CPU"] = float(num_cpus)
        self.raylet = Raylet(
            gcs_address=self.gcs_address,
            resources=node_resources or None,
            is_head=True,
        )
        self.raylet_address = self.raylet.start(0)
        self.dashboard = None
        self.dashboard_agent = None
        if include_dashboard:
            from ray_tpu.dashboard import DashboardHead
            from ray_tpu.dashboard.agent import DashboardAgent

            self.dashboard = DashboardHead(self.gcs_address, port=0)
            self.dashboard_agent = DashboardAgent(
                self.gcs_address, self.raylet.node_id.hex(),
                self.raylet_address)

    def stop(self):
        if self.dashboard_agent is not None:
            self.dashboard_agent.stop()
            self.dashboard_agent = None
        if self.dashboard is not None:
            self.dashboard.stop()
            self.dashboard = None
        self.raylet.stop(unregister=False)
        self.gcs.stop()


class RayContext:
    def __init__(self, gcs_address: str, node_id, namespace: str,
                 dashboard_url=None):
        self.address_info = {"gcs_address": gcs_address, "address": gcs_address}
        self.dashboard_url = dashboard_url
        self.node_id = node_id
        self.namespace = namespace

    def __enter__(self):
        return self

    def __exit__(self, *a):
        shutdown()

    def __getitem__(self, key):
        return self.address_info[key]


def init(
    address: Optional[str] = None,
    *,
    num_cpus: Optional[int] = None,
    resources: Optional[dict] = None,
    namespace: Optional[str] = None,
    object_store_memory: Optional[int] = None,
    ignore_reinit_error: bool = False,
    include_dashboard: bool = False,
    log_to_driver: bool = True,
    runtime_env: Optional[dict] = None,
    _system_config: Optional[dict] = None,
    **_kwargs,
) -> RayContext:
    global _global_node
    with _init_lock:
        if global_state.core_worker is not None:
            if ignore_reinit_error:
                cw = global_state.core_worker
                return RayContext(cw.gcs_address, cw.node_id, cw.namespace)
            raise RuntimeError(
                "ray_tpu.init() has already been called; pass "
                "ignore_reinit_error=True to ignore."
            )
        if address is None:
            address = os.environ.get("RT_ADDRESS")
        if address and address.startswith("client://"):
            # proxied remote driver (reference: ray.init("ray://host:port")
            # through util/client) — token-authenticated; the proxy hosts
            # this session's actual driver
            from ray_tpu.util.client import connect

            cw = connect(
                address[len("client://"):],
                token=_kwargs.get("token")
                or os.environ.get("RT_CLIENT_TOKEN"),
                namespace=namespace or "",
                runtime_env=runtime_env)
            atexit.register(shutdown)
            return RayContext(cw.gcs_address, cw.node_id, cw.namespace)
        gcs_address = None
        raylet_address = None
        if address is None:
            _global_node = _HeadNode(
                num_cpus=num_cpus, resources=resources,
                _system_config=_system_config,
                object_store_memory=object_store_memory,
                include_dashboard=include_dashboard,
            )
            gcs_address = _global_node.gcs_address
            raylet_address = _global_node.raylet_address
        else:
            gcs_address = address
            # Connect as a driver to an existing cluster: use the head raylet.
            from ray_tpu._private.rpc import EventLoopThread, RpcClient

            lt = EventLoopThread("bootstrap")
            client = RpcClient(gcs_address, lt)
            try:
                nodes = client.call("get_all_node_info", {})
            finally:
                client.close()
                lt.stop()
            head = next((n for n in nodes if n.alive and n.is_head), None)
            if head is None:
                head = next((n for n in nodes if n.alive), None)
            if head is None:
                raise ConnectionError(f"no alive nodes in cluster at {gcs_address}")
            raylet_address = head.raylet_address

        from ray_tpu.worker.core_worker import CoreWorker
        from ray_tpu._private.specs import JobInfo

        cw = CoreWorker(
            mode="driver",
            gcs_address=gcs_address,
            raylet_address=raylet_address,
            namespace=namespace or "",
        )
        if runtime_env:
            from ray_tpu import runtime_env as re_mod

            cw.job_runtime_env = re_mod.validate(runtime_env)
            # env_vars of the job-level env apply to the driver itself too
            # (reference: job runtime env is the driver's env).
            for k, v in (cw.job_runtime_env or {}).get(
                    "env_vars", {}).items():
                os.environ[k] = v
        cw._gcs.call(
            "add_job",
            {"info": JobInfo(job_id=cw.job_id, driver_address=cw.address_str,
                             namespace=namespace or "")},
        )
        atexit.register(shutdown)
        dash = (_global_node.dashboard.url
                if _global_node is not None and _global_node.dashboard
                else None)
        return RayContext(gcs_address, cw.node_id, namespace or "", dash)


def shutdown():
    global _global_node
    with _init_lock:
        cw = global_state.core_worker
        if cw is not None:
            cw.shutdown()
        if _global_node is not None:
            _global_node.stop()
            _global_node = None


def is_initialized() -> bool:
    return global_state.core_worker is not None


def remote(*args, **kwargs):
    """@remote decorator for tasks and actors (worker.py:3119)."""

    def make(target, options):
        if isinstance(target, type):
            return ActorClass(target, options)
        if callable(target):
            return RemoteFunction(target, options)
        raise TypeError(f"@remote target must be a function or class, got {target!r}")

    if len(args) == 1 and not kwargs and (callable(args[0]) or isinstance(args[0], type)):
        return make(args[0], {})
    if args:
        raise TypeError("@remote takes keyword options only, e.g. @remote(num_cpus=2)")

    def decorator(target):
        return make(target, dict(kwargs))

    return decorator


def get(
    refs: Union[ObjectRef, Sequence[ObjectRef]],
    *,
    timeout: Optional[float] = None,
) -> Any:
    # Compiled-DAG channel results resolve through their shm channel, not
    # the object store (dag/compiled_channels.py CompiledDAGRef).
    if hasattr(refs, "_rt_dag_get"):
        return refs._rt_dag_get(timeout)
    cw = get_core_worker()
    if isinstance(refs, ObjectRef):
        return cw.get([refs], timeout=timeout)[0]
    if isinstance(refs, ObjectRefGenerator):
        raise TypeError("pass generator items, not the generator, to get()")
    if not isinstance(refs, (list, tuple)):
        raise TypeError(f"get() expects an ObjectRef or list of them, got {type(refs)}")
    if refs and all(hasattr(r, "_rt_dag_get") for r in refs):
        return [r._rt_dag_get(timeout) for r in refs]
    for r in refs:
        if not isinstance(r, ObjectRef):
            raise TypeError(f"get() list items must be ObjectRefs, got {type(r)}")
    return cw.get(list(refs), timeout=timeout)


def put(value: Any) -> ObjectRef:
    if isinstance(value, ObjectRef):
        raise TypeError("calling put() on an ObjectRef is not allowed")
    return get_core_worker().put(value)


def wait(
    refs: List[ObjectRef],
    *,
    num_returns: int = 1,
    timeout: Optional[float] = None,
    fetch_local: bool = True,
):
    if isinstance(refs, ObjectRef):
        raise TypeError("wait() expects a list of ObjectRefs")
    if num_returns <= 0:
        raise ValueError("num_returns must be > 0")
    if num_returns > len(refs):
        raise ValueError("num_returns cannot exceed the number of refs")
    return get_core_worker().wait(
        list(refs), num_returns=num_returns, timeout=timeout, fetch_local=fetch_local
    )


def kill(actor: ActorHandle, *, no_restart: bool = True):
    if not isinstance(actor, ActorHandle):
        raise TypeError("kill() expects an ActorHandle")
    get_core_worker().kill_actor(actor._actor_id, no_restart=no_restart)


def cancel(ref: ObjectRef, *, force: bool = False, recursive: bool = True):
    get_core_worker().cancel_task(ref, force=force)


def get_actor(name: str, namespace: Optional[str] = None) -> ActorHandle:
    from ray_tpu._private.specs import ActorState

    info = get_core_worker().get_named_actor(name, namespace)
    if info is None or info.state == ActorState.DEAD:
        raise ValueError(f"Failed to look up actor with name '{name}'")
    return ActorHandle(info.actor_id)


def available_resources() -> dict:
    cw = get_core_worker()
    nodes = cw._gcs.call("get_all_node_info", {})
    out: dict = {}
    for n in nodes:
        if not n.alive:
            continue
        for k, v in n.resources_available.items():
            out[k] = out.get(k, 0.0) + v
    return out


def cluster_resources() -> dict:
    cw = get_core_worker()
    nodes = cw._gcs.call("get_all_node_info", {})
    out: dict = {}
    for n in nodes:
        if not n.alive:
            continue
        for k, v in n.resources_total.items():
            out[k] = out.get(k, 0.0) + v
    return out


def timeline(filename: Optional[str] = None) -> list:
    """Chrome-trace events of task execution so far (reference: ray.timeline,
    worker.py — same data as the `ray-tpu timeline` CLI). Writes JSON when
    `filename` is given; always returns the event list."""
    from ray_tpu.util.state.api import task_timeline_events

    trace = task_timeline_events()
    if filename:
        import json as _json

        with open(filename, "w") as f:
            _json.dump(trace, f)
    return trace


def nodes() -> List[dict]:
    cw = get_core_worker()
    infos = cw._gcs.call("get_all_node_info", {})
    return [
        {
            "NodeID": n.node_id.hex(),
            "Alive": n.alive,
            "RayletAddress": n.raylet_address,
            "Resources": dict(n.resources_total),
            "Labels": dict(n.labels),
            "IsHead": n.is_head,
        }
        for n in infos
    ]
