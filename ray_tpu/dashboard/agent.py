"""Per-node dashboard agent.

Reference: ray dashboard/agent.py (DashboardAgent) + the reporter module
(dashboard/modules/reporter/reporter_agent.py — /proc stats; and
profile_manager.py — py-spy/memray endpoints). One agent runs next to each
raylet and owns the NODE-LOCAL views the head process can't see: per-worker
process stats from /proc, log file tails, and live profiling of local
workers. The head dashboard discovers agents through a GCS KV registration
(`dashboard_agent:<node_id>` -> http address) and transparently proxies
`/api/nodes/<node_id>/...` to them.

Design notes (TPU-first, single-language): the reference runs the agent as
a raylet-supervised child process with its own gRPC + HTTP servers; here
the agent is an HTTP thread inside the node process (raylet and agent
share a pid — one fewer process per host on small nodes), talking to its
raylet over the same asyncio RPC every other component uses. Profiling
needs no ptrace helper (py-spy) because workers self-sample
(util/profiling.py) behind the raylet's profile_worker RPC.
"""

from __future__ import annotations

import glob
import json
import logging
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional
from urllib.parse import parse_qs, urlparse

logger = logging.getLogger(__name__)

AGENT_KV_PREFIX = "dashboard_agent:"

_WORKER_CMDLINE_MARKS = (
    b"ray_tpu._private.workers.default_worker",
    b"ray_tpu._private.workers.zygote",
)


def _read_proc_stat(pid: int) -> Optional[Dict[str, Any]]:
    """One process's rss/cpu ticks from /proc/<pid>/stat (no psutil in
    the image; the fields are stable kernel ABI)."""
    try:
        with open(f"/proc/{pid}/stat", "rb") as fh:
            raw = fh.read().decode("ascii", "replace")
        # comm may contain spaces/parens: split after the LAST ')'
        rest = raw[raw.rindex(")") + 2:].split()
        utime, stime = int(rest[11]), int(rest[12])
        rss_pages = int(rest[21])
        return {
            "pid": pid,
            "cpu_ticks": utime + stime,
            "rss_bytes": rss_pages * os.sysconf("SC_PAGE_SIZE"),
        }
    except (OSError, ValueError, IndexError):
        return None


def _node_cpu_ticks() -> Optional[tuple]:
    try:
        with open("/proc/stat", "rb") as fh:
            first = fh.readline().split()
        vals = [int(v) for v in first[1:]]
        idle = vals[3] + (vals[4] if len(vals) > 4 else 0)
        return sum(vals), idle
    except (OSError, ValueError, IndexError):
        return None


def _meminfo() -> Dict[str, int]:
    out = {}
    try:
        with open("/proc/meminfo", "rb") as fh:
            for line in fh:
                k, _, v = line.decode().partition(":")
                if k in ("MemTotal", "MemAvailable"):
                    out[k] = int(v.split()[0]) * 1024
    except (OSError, ValueError):
        pass
    return {"total_bytes": out.get("MemTotal", 0),
            "available_bytes": out.get("MemAvailable", 0)}


def _worker_pids() -> List[int]:
    pids = []
    for p in glob.glob("/proc/[0-9]*/cmdline"):
        try:
            with open(p, "rb") as fh:
                cmdline = fh.read()
        except OSError:
            continue
        if any(m in cmdline for m in _WORKER_CMDLINE_MARKS):
            pids.append(int(p.split("/")[2]))
    return pids


class DashboardAgent:
    """Node-local stats/logs/profiling over HTTP; self-registers in GCS KV
    so the head can proxy to it."""

    def __init__(self, gcs_address: str, node_id: str,
                 raylet_address: str, host: str = "127.0.0.1",
                 port: int = 0):
        from ray_tpu._private.config import CONFIG
        from ray_tpu._private.rpc import EventLoopThread, RpcClient

        self.node_id = node_id
        self.log_dir = CONFIG.log_dir
        self._lt = EventLoopThread(f"dash-agent-{node_id[:8]}")
        self._gcs = RpcClient(gcs_address, self._lt)
        self._raylet = RpcClient(raylet_address, self._lt)
        # previous cpu sample, for utilization deltas between requests;
        # ThreadingHTTPServer handles requests concurrently, so the
        # read-modify-write of the baseline needs the lock
        self._stats_lock = threading.Lock()
        self._last_node = _node_cpu_ticks()
        self._last_proc: Dict[int, int] = {}
        self._last_t = time.monotonic()
        agent = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # noqa: N802 — quiet
                pass

            def do_GET(self):  # noqa: N802 — http.server API
                try:
                    agent._route(self)
                except BrokenPipeError:
                    pass
                except Exception as e:  # noqa: BLE001
                    logger.exception("agent request failed")
                    try:
                        self.send_error(500, str(e))
                    except Exception:  # noqa: BLE001
                        pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.url = f"http://{host}:{self._httpd.server_address[1]}"
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name=f"dash-agent-{node_id[:8]}")
        self._thread.start()
        self._register()

    def _register(self) -> None:
        try:
            self._gcs.call("kv_put", {
                "key": f"{AGENT_KV_PREFIX}{self.node_id}",
                "value": self.url.encode(), "overwrite": True}, timeout=10)
        except Exception:  # noqa: BLE001 — head just won't proxy to us
            logger.warning("agent KV registration failed", exc_info=True)

    # -- routing -------------------------------------------------------------

    def _route(self, req: BaseHTTPRequestHandler) -> None:
        parsed = urlparse(req.path)
        q = {k: v[0] for k, v in parse_qs(parsed.query).items()}
        if parsed.path == "/api/local/stats":
            self._json(req, self.stats())
        elif parsed.path == "/api/local/logs":
            self._json(req, self.log_tail(q.get("name", ""),
                                          int(q.get("lines", 200))))
        elif parsed.path == "/api/local/profile":
            self._json(req, self.profile(
                int(q.get("pid", 0)),
                kind=q.get("kind", "cpu"),
                duration_s=float(q.get("duration", 5.0))))
        else:
            req.send_error(404, "unknown agent path")

    def _json(self, req, obj: Any) -> None:
        body = json.dumps(obj).encode()
        req.send_response(200)
        req.send_header("Content-Type", "application/json")
        req.send_header("Content-Length", str(len(body)))
        req.end_headers()
        req.wfile.write(body)

    # -- endpoints -----------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        """Node + per-worker-process utilization since the last call."""
        with self._stats_lock:
            return self._stats_locked()

    def _stats_locked(self) -> Dict[str, Any]:
        now = time.monotonic()
        dt = max(1e-3, now - self._last_t)
        node_now = _node_cpu_ticks()
        node_cpu_pct = None
        if node_now and self._last_node:
            total = node_now[0] - self._last_node[0]
            idle = node_now[1] - self._last_node[1]
            if total > 0:
                node_cpu_pct = round(100.0 * (total - idle) / total, 1)
        self._last_node = node_now

        tick_hz = os.sysconf("SC_CLK_TCK")
        try:
            registered = set(self._raylet.call("list_worker_pids", {},
                                               timeout=10))
        except Exception:  # noqa: BLE001 — tag everything unregistered
            registered = set()
        workers = []
        seen = {}
        for pid in _worker_pids():
            st = _read_proc_stat(pid)
            if st is None:
                continue
            seen[pid] = st["cpu_ticks"]
            prev = self._last_proc.get(pid)
            cpu_pct = (round(100.0 * (st["cpu_ticks"] - prev)
                             / tick_hz / dt, 1)
                       if prev is not None else None)
            workers.append({"pid": pid, "rss_bytes": st["rss_bytes"],
                            "cpu_percent": cpu_pct,
                            # registered workers are profile-able; the rest
                            # are fork-servers sharing the worker cmdline
                            "registered": pid in registered})
        self._last_proc = seen
        self._last_t = now
        try:
            load1, load5, load15 = os.getloadavg()
        except OSError:
            load1 = load5 = load15 = None
        return {
            "node_id": self.node_id,
            "now": time.time(),
            "cpu_percent": node_cpu_pct,
            "load_avg": [load1, load5, load15],
            "mem": _meminfo(),
            "workers": sorted(workers, key=lambda w: -(w["rss_bytes"])),
        }

    def log_tail(self, name: str, lines: int = 200) -> Dict[str, Any]:
        """Tail one node-local log file by basename (no path traversal:
        the name is resolved under log_dir and must stay there)."""
        roots = [self.log_dir, os.path.join(self.log_dir, "workers"),
                 os.path.join(self.log_dir, "jobs")]
        if not name:
            files = []
            for root in roots:
                for f in sorted(glob.glob(os.path.join(root, "*.log"))):
                    files.append(os.path.relpath(f, self.log_dir))
            return {"files": files}
        for root in roots:
            path = os.path.realpath(os.path.join(root, os.path.basename(name)))
            if not path.startswith(os.path.realpath(self.log_dir) + os.sep):
                continue
            if os.path.isfile(path):
                with open(path, "r", errors="replace") as fh:
                    tail = fh.readlines()[-lines:]
                return {"name": name, "lines": tail}
        return {"error": f"no such log: {name}"}

    def profile(self, pid: int, kind: str = "cpu",
                duration_s: float = 5.0) -> Dict[str, Any]:
        """Live-profile a local worker through the raylet (the worker
        self-samples; no ptrace)."""
        try:
            return self._raylet.call(
                "profile_worker",
                {"pid": pid, "kind": kind, "duration_s": duration_s,
                 "top": 0, "stop": False},
                timeout=duration_s + 30)
        except Exception as e:  # noqa: BLE001 — surface to the caller
            return {"error": str(e)}

    def stop(self) -> None:
        try:
            self._gcs.call("kv_del", {
                "key": f"{AGENT_KV_PREFIX}{self.node_id}"}, timeout=5)
        except Exception:  # noqa: BLE001
            pass
        self._httpd.shutdown()
        self._httpd.server_close()
        self._lt.stop()


def main() -> int:
    """Standalone agent (`python -m ray_tpu.dashboard.agent`) for setups
    that want it out-of-process like the reference's."""
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--gcs-address", required=True)
    parser.add_argument("--node-id", required=True)
    parser.add_argument("--raylet-address", required=True)
    parser.add_argument("--port", type=int, default=0)
    args = parser.parse_args()
    agent = DashboardAgent(args.gcs_address, args.node_id,
                           args.raylet_address, port=args.port)
    print(f"agent listening on {agent.url}")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        agent.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
