"""Dashboard head: HTTP state API + Prometheus metrics.

Reference: ray dashboard/head.py (aiohttp app with pluggable modules —
job/state/reporter/metrics) + the per-node metrics agent's Prometheus
exposition (_private/metrics_agent.py). This implementation is a stdlib
threaded HTTP server talking straight to the GCS, so it runs standalone on
the head node with zero extra dependencies.

Endpoints:
  GET /                     tiny HTML overview
  GET /api/cluster_status   nodes + resource totals/available + demands
  GET /api/nodes|actors|jobs|placement_groups|tasks|workers
  GET /api/version
  GET /api/metrics_timeseries  ring-buffered time series for the SPA's
                               live metrics page (task throughput, stage
                               latency percentiles, store bytes, node CPU)
  GET /metrics              Prometheus exposition (user metrics + core gauges)
"""

from __future__ import annotations

import json
import logging
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

from ray_tpu._private.rpc import ClientPool, EventLoopThread, RpcClient

logger = logging.getLogger(__name__)

# time-series ring buffers: one hour at the 5s background cadence
TS_MAXLEN = 720
TS_SAMPLE_PERIOD_S = 5.0
TS_MIN_SAMPLE_GAP_S = 1.0  # on-demand endpoint sampling floor


class DashboardHead:
    def __init__(self, gcs_address: str, host: str = "127.0.0.1",
                 port: int = 8265):
        self.gcs_address = gcs_address
        self._lt = EventLoopThread("dashboard")
        self._gcs = RpcClient(gcs_address, self._lt)
        self._raylets = ClientPool(self._lt)  # reused across /api/logs calls
        self._jobs_lock = threading.Lock()
        self._jobs_sdk = None
        dash = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # noqa: N802 — quiet
                pass

            def do_GET(self):  # noqa: N802 — http.server API
                try:
                    dash._route(self)
                except BrokenPipeError:
                    pass
                except Exception as e:  # noqa: BLE001
                    logger.exception("dashboard request failed")
                    try:
                        self.send_error(500, str(e))
                    except Exception:  # noqa: BLE001
                        pass

            def do_POST(self):  # noqa: N802 — http.server API
                try:
                    dash._route_post(self)
                except BrokenPipeError:
                    pass
                except Exception as e:  # noqa: BLE001
                    logger.exception("dashboard POST failed")
                    try:
                        self.send_error(500, str(e))
                    except Exception:  # noqa: BLE001
                        pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.url = f"http://{host}:{self._httpd.server_address[1]}"
        # Live metrics time series: a background sampler fills ring
        # buffers; the endpoint also samples on demand so a freshly-polled
        # page never sees an empty window. State must exist BEFORE the
        # HTTP thread starts serving, or a scrape racing startup 500s.
        self._ts_lock = threading.Lock()       # ring-buffer reads/writes
        self._ts_sampling = threading.Lock()   # one sampler at a time
        self._ts: Dict[str, deque] = {}
        self._ts_last_sample = 0.0
        # sampler health (ISSUE 20 satellite): a failed sample used to be
        # a debug log + a last point persisting indefinitely — now every
        # failure is counted, surfaced in /api/metrics_timeseries, and
        # logged at warning (rate-limited) so "flat" and "dead" are
        # distinguishable
        self._ts_last_success = 0.0
        self._ts_fail_count = 0
        self._ts_last_warn = 0.0
        self._ts_tp_prev_t: Optional[float] = None
        self._ts_finished_cum = 0
        self._ts_event_watermarks: Dict[str, float] = {}
        self._ts_stop = threading.Event()
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="dashboard-http",
            daemon=True)
        self._thread.start()
        self._ts_thread = threading.Thread(
            target=self._ts_loop, name="dashboard-ts", daemon=True)
        self._ts_thread.start()

    # -- routing -------------------------------------------------------------

    # -- job submission REST API (reference: dashboard/modules/job/
    # job_head.py — POST/GET /api/jobs/) ------------------------------------

    def _jobs_client(self):
        """Lazy driver connection for the submission API: actor calls need
        a core worker, which `start --head` processes don't have until the
        first job request arrives. Locked: ThreadingHTTPServer handlers run
        concurrently and double-init raises."""
        with self._jobs_lock:
            if self._jobs_sdk is None:
                import ray_tpu
                from ray_tpu.job_submission import JobSubmissionClient

                if not ray_tpu.is_initialized():
                    ray_tpu.init(address=self.gcs_address)
                self._jobs_sdk = JobSubmissionClient()
            return self._jobs_sdk

    @staticmethod
    def _job_json(details) -> Dict[str, Any]:
        return {
            "submission_id": details.submission_id,
            "entrypoint": details.entrypoint,
            "status": details.status.value,
            "message": details.message,
            "metadata": details.metadata,
            "runtime_env": details.runtime_env,
            "start_time": details.start_time,
            "end_time": details.end_time,
            "driver_exit_code": details.driver_exit_code,
        }

    def _route_jobs_get(self, req, parts) -> None:
        client = self._jobs_client()
        if not parts:  # GET /api/jobs/  — list submissions
            self._json(req, [self._job_json(d)
                             for d in client.list_jobs()])
        elif len(parts) == 1:  # GET /api/jobs/<sid>
            try:
                details = client.get_job_info(parts[0])
            except RuntimeError:
                req.send_error(404, f"job {parts[0]!r} not found")
                return
            self._json(req, self._job_json(details))
        elif len(parts) == 2 and parts[1] == "logs":
            # ?offset=N: the manager seeks past N bytes — neither the actor
            # RPC nor the HTTP response carries the already-seen prefix
            from urllib.parse import parse_qs, urlparse

            q = parse_qs(urlparse(req.path).query)
            offset = int(q.get("offset", ["0"])[0])
            text, end = client.get_job_logs_from(parts[0], offset)
            self._json(req, {"logs": text, "total_len": end})
        else:
            req.send_error(404)

    def _route_post(self, req: BaseHTTPRequestHandler) -> None:
        path = req.path.split("?")[0].rstrip("/")
        length = int(req.headers.get("Content-Length") or 0)
        body = json.loads(req.rfile.read(length) or b"{}") if length else {}
        if path == "/api/jobs":
            if not body.get("entrypoint"):
                req.send_error(400, "missing required field 'entrypoint'")
                return
            client = self._jobs_client()
            sid = client.submit_job(
                entrypoint=body["entrypoint"],
                submission_id=body.get("submission_id"),
                runtime_env=body.get("runtime_env"),
                metadata=body.get("metadata"))
            self._json(req, {"submission_id": sid})
        elif path.startswith("/api/jobs/") and path.endswith("/stop"):
            sid = path[len("/api/jobs/"):-len("/stop")]
            self._json(req, {"stopped": self._jobs_client().stop_job(sid)})
        else:
            req.send_error(404)

    def _route(self, req: BaseHTTPRequestHandler) -> None:
        path = req.path.split("?")[0].rstrip("/") or "/"
        # submission API: /api/jobs/<...> (GET /api/jobs without a subpath
        # keeps serving cluster job info from the GCS, like /api/nodes)
        if path.startswith("/api/jobs/") or (
                req.path.split("?")[0] == "/api/jobs/"):
            self._route_jobs_get(
                req, [p for p in path[len("/api/jobs/"):].split("/") if p])
            return
        if path == "/":
            html = self._client_file("index.html")
            if html is not None:
                self._respond(req, html, "text/html")
            else:  # packaged frontend missing: keep the minimal overview
                self._respond(req, self._index_html(), "text/html")
        elif path.startswith("/static/"):
            name = path[len("/static/"):]
            body = self._client_file(name)
            if body is None:
                req.send_error(404)
            else:
                ctype = ("text/css" if name.endswith(".css")
                         else "application/javascript"
                         if name.endswith(".js") else "text/plain")
                self._respond(req, body, ctype)
        elif path == "/api/serve":
            self._json(req, self._serve_status(req))
        elif path == "/api/logs":
            # worker log tails, fanned out over each raylet's
            # tail_worker_logs RPC (reference: dashboard log routes)
            from urllib.parse import parse_qs, urlparse

            q = parse_qs(urlparse(req.path).query)
            self._json(req, self._worker_logs(
                lines=int(q.get("lines", ["100"])[0]),
                node_id=(q.get("node_id", [None])[0])))
        elif path == "/api/metrics_timeseries":
            self._json(req, self._timeseries())
        elif path == "/api/health":
            # cluster health plane (ISSUE 20): scorecard + firing alerts
            # + demand signals, straight from the GCS metrics manager
            self._json(req, self._gcs.call("get_health", {}, timeout=10))
        elif path == "/api/alerts":
            self._json(req, self._gcs.call("get_alerts", {}, timeout=10))
        elif path == "/metrics":
            self._respond(req, self._metrics_text(),
                          "text/plain; version=0.0.4")
        elif path == "/api/version":
            self._json(req, {"ray_version": "ray_tpu-0.1",
                             "gcs_address": self.gcs_address})
        elif path == "/api/cluster_status":
            self._json(req, self._cluster_status())
        elif path == "/api/timeline":
            # chrome-trace task timeline (load in Perfetto / chrome://tracing,
            # or the SPA's Timeline page)
            from ray_tpu.util.state.api import build_chrome_trace

            events = self._gcs.call(
                "get_task_events", {"job_id": None, "limit": 100_000},
                timeout=30)
            self._json(req, build_chrome_trace(events))
        elif path == "/api/trace":
            # distributed-request trace lookup (ISSUE 11): ?trace_id=<id>
            # returns the cross-process span set + a rendered tree +
            # the lifecycle events stamped with the id; without trace_id,
            # recent sampled/force-kept trace summaries (the SPA's Trace
            # page and curl both consume this)
            from urllib.parse import parse_qs, urlparse

            from ray_tpu._private.tracing import format_trace, trace_chrome

            q = parse_qs(urlparse(req.path).query)
            trace_id = q.get("trace_id", [None])[0]
            if not trace_id:
                self._json(req, {
                    "traces": self._gcs.call(
                        "list_traces",
                        {"limit": int(q.get("limit", ["50"])[0])},
                        timeout=30)})
            else:
                reply = self._gcs.call(
                    "get_trace", {"trace_id": trace_id}, timeout=30)
                spans = reply.get("spans") or []
                reply["tree"] = format_trace(spans) if spans else ""
                if q.get("chrome", [None])[0]:
                    reply["chrome"] = trace_chrome(spans)
                reply["events"] = self._gcs.call(
                    "get_cluster_events",
                    {"limit": 1000, "trace_id": trace_id}, timeout=30)
                self._json(req, reply)
        elif path == "/api/events":
            # cluster-wide lifecycle event feed (same filters as the
            # `ray-tpu events` CLI: type glob + id exact-matches)
            from urllib.parse import parse_qs, urlparse

            q = parse_qs(urlparse(req.path).query)

            def _one(key):
                return q.get(key, [None])[0]

            self._json(req, {
                "events": self._gcs.call("get_cluster_events", {
                    "limit": int(_one("limit") or 1000),
                    "type": _one("type"), "task_id": _one("task_id"),
                    "actor_id": _one("actor_id"),
                    "node_id": _one("node_id")}, timeout=30),
                "stats": self._gcs.call("get_event_log_stats", {},
                                        timeout=30),
            })
        elif path == "/api/agents":
            self._json(req, self._agents())
        elif path.startswith("/api/nodes/") and path.count("/") >= 4:
            # per-node agent proxy: /api/nodes/<node_id>/<stats|logs|profile>
            _, _, _, node_id, sub = path.split("/", 4)
            self._proxy_agent(req, node_id, sub)
        elif path.startswith("/api/nodes/"):
            req.send_error(
                404, "expected /api/nodes/<node_id>/<stats|logs|profile>")
        elif path.startswith("/api/"):
            kind = path[len("/api/"):]
            data = self._list(kind)
            if data is None:
                req.send_error(404, f"unknown resource {kind!r}")
            else:
                self._json(req, data)
        else:
            req.send_error(404)

    def _agents(self) -> Dict[str, str]:
        """node_id -> agent http url, from the agents' KV registrations."""
        from ray_tpu.dashboard.agent import AGENT_KV_PREFIX

        out: Dict[str, str] = {}
        try:
            keys = self._gcs.call(
                "kv_keys", {"prefix": AGENT_KV_PREFIX}, timeout=10)
            vals = self._gcs.call(
                "kv_multi_get", {"keys": list(keys)}, timeout=10)
        except Exception:  # noqa: BLE001 — no agents registered
            return out
        for key, val in (vals or {}).items():
            if val is None:
                continue
            k = key.decode() if isinstance(key, bytes) else key
            v = val.decode() if isinstance(val, bytes) else val
            out[k[len(AGENT_KV_PREFIX):]] = v
        return out

    def _proxy_agent(self, req, node_id: str, sub: str) -> None:
        """Forward /api/nodes/<id>/<sub>?... to that node's agent
        (reference: the head's DataOrganizer pulling per-node agent data)."""
        import urllib.request
        from urllib.parse import urlparse

        url = self._agents().get(node_id)
        if url is None:
            req.send_error(404, f"no agent registered for node {node_id}")
            return
        query = urlparse(req.path).query
        target = f"{url}/api/local/{sub}" + (f"?{query}" if query else "")
        try:
            with urllib.request.urlopen(target, timeout=60) as resp:
                self._respond(req, resp.read().decode(), "application/json")
        except Exception as e:  # noqa: BLE001 — agent down
            req.send_error(502, f"agent unreachable: {e}")

    def _respond(self, req, body: str, ctype: str) -> None:
        data = body.encode()
        req.send_response(200)
        req.send_header("Content-Type", ctype)
        req.send_header("Content-Length", str(len(data)))
        req.end_headers()
        req.wfile.write(data)

    def _json(self, req, obj: Any) -> None:
        self._respond(req, json.dumps(obj, default=str), "application/json")

    @staticmethod
    def _client_file(name: str) -> Optional[str]:
        """Read a packaged frontend file (dashboard/client/) — no build
        step, no extra server: the same stdlib handler serves the SPA
        (reference capability: dashboard/client/src React app)."""
        import os

        base = os.path.join(os.path.dirname(__file__), "client")
        path = os.path.normpath(os.path.join(base, name))
        # trailing separator: plain startswith(base) would admit sibling
        # paths like .../client_extra
        if not path.startswith(base + os.sep):  # no traversal
            return None
        try:
            with open(path, encoding="utf-8") as f:
                return f.read()
        except OSError:
            return None

    def _serve_status(self, req) -> Dict[str, Any]:
        """Serve application/deployment states for the Serve page, plus
        the control plane's FT posture (ISSUE 12): controller
        incarnation, checkpoint freshness, and the last recovery's
        adopted-vs-restarted replica split."""
        self._jobs_client()  # ensures a connected driver
        from ray_tpu.serve import api as serve_api

        try:
            out: Dict[str, Any] = {"applications": serve_api.status()}
        except Exception:  # noqa: BLE001 — serve not running
            return {"applications": {}}
        try:
            import ray_tpu
            from ray_tpu.serve.context import CONTROLLER_NAME

            controller = ray_tpu.get_actor(CONTROLLER_NAME)
            out["controller"] = ray_tpu.get(
                controller.get_recovery_info.remote(), timeout=5)
        except Exception:  # noqa: BLE001 — controller down mid-recovery
            pass
        return out

    # -- data ----------------------------------------------------------------

    def _worker_logs(self, lines: int = 100,
                     node_id: Optional[str] = None) -> Dict[str, Any]:
        from ray_tpu.util.state.api import collect_worker_logs

        # short per-node timeout: one wedged raylet must not stall the
        # whole fan-out (calls are sequential on this thread)
        return collect_worker_logs(
            self._gcs.call("get_all_node_info", {}, timeout=10),
            lambda addr, payload: self._raylets.get(addr).call(
                "tail_worker_logs", payload, timeout=5),
            node_id=node_id, lines=lines)

    def _cluster_status(self) -> Dict[str, Any]:
        load = self._gcs.call("get_cluster_load", {}, timeout=10)
        total: Dict[str, float] = {}
        avail: Dict[str, float] = {}
        for n in load["nodes"].values():
            if not n["alive"]:
                continue
            for k, v in n["total"].items():
                total[k] = total.get(k, 0.0) + v
            for k, v in n["available"].items():
                avail[k] = avail.get(k, 0.0) + v
        return {"nodes": load["nodes"], "resources_total": total,
                "resources_available": avail,
                "pending_demands": load.get("demands", []),
                "pending_pg_bundles": load.get("pending_pg_bundles", [])}

    def _list(self, kind: str) -> Optional[list]:
        if kind == "nodes":
            infos = self._gcs.call("get_all_node_info", {}, timeout=10)
            return [{
                "node_id": n.node_id.hex(),
                "state": "ALIVE" if n.alive else "DEAD",
                "raylet_address": n.raylet_address,
                "resources_total": dict(n.resources_total),
                "resources_available": dict(n.resources_available),
                "is_head_node": n.is_head,
            } for n in infos]
        if kind == "actors":
            actors = self._gcs.call("list_actors", {}, timeout=10)
            return [{
                "actor_id": a.actor_id.hex(),
                "state": getattr(a.state, "name", str(a.state)),
                "name": a.name or "",
                "class_name": a.class_name,
                "pid": a.pid,
                "restarts": a.num_restarts,
            } for a in actors]
        if kind == "jobs":
            jobs = self._gcs.call("get_all_job_info", {}, timeout=10)
            return [{
                "job_id": j.job_id.hex() if hasattr(j.job_id, "hex")
                else str(j.job_id),
                "is_dead": j.is_dead,
                "driver_address": j.driver_address,
            } for j in jobs]
        if kind == "placement_groups":
            pgs = self._gcs.call("list_placement_groups", {}, timeout=10)
            return pgs
        if kind == "tasks":
            events = self._gcs.call(
                "get_task_events", {"job_id": None, "limit": 10_000},
                timeout=10)
            from ray_tpu.util.state.api import latest_task_events

            return list(latest_task_events(events).values())
        if kind == "workers":
            from ray_tpu.util.state import list_workers

            try:
                return list_workers()
            except Exception:  # noqa: BLE001 — needs a connected worker
                return []
        return None

    # -- live metrics time series -------------------------------------------

    def _ts_loop(self) -> None:
        while not self._ts_stop.wait(TS_SAMPLE_PERIOD_S):
            try:
                self._ts_sample()
            except Exception:  # noqa: BLE001 — sampler must never die
                self._ts_fail_count += 1
                now = time.time()
                if now - self._ts_last_warn > 60.0:
                    self._ts_last_warn = now
                    logger.warning(
                        "timeseries sample failed (%d consecutive; series "
                        "are going stale, last success %.0fs ago)",
                        self._ts_fail_count,
                        now - self._ts_last_success
                        if self._ts_last_success else -1.0,
                        exc_info=True)

    def _ts_add(self, name: str, t: float, value: float) -> None:
        buf = self._ts.get(name)
        if buf is None:
            buf = self._ts[name] = deque(maxlen=TS_MAXLEN)
        buf.append((round(t, 3), value))

    def _ts_sample(self) -> None:
        """Collect one point of every series. Sources: the process-local
        metrics registry (stage-latency histograms — the head runs in the
        driver process for in-process clusters), GCS task events (task
        throughput), per-raylet node stats (store bytes, leases), and
        dashboard agents (per-node CPU). Every source is best-effort.

        The cluster fan-out can block for seconds (per-node RPCs with
        nodes mid-death), so it runs OUTSIDE _ts_lock — holding it here
        would hang every /api/metrics_timeseries request on the HTTP
        threads. _ts_sampling serializes samplers instead (an on-demand
        request racing the background loop simply skips; the buffers are
        at most one cycle stale)."""
        if not self._ts_sampling.acquire(blocking=False):
            return
        try:
            now = time.time()
            if now - self._ts_last_sample < TS_MIN_SAMPLE_GAP_S:
                return
            points: list = []
            self._ts_collect(now, points)
            with self._ts_lock:
                self._ts_last_sample = now
                self._ts_last_success = now
                self._ts_fail_count = 0
                for name, value in points:
                    self._ts_add(name, now, value)
            # Ship the collected points to the GCS health store (ISSUE
            # 20): the dashboard ring becomes a warm cache over the
            # cluster-wide store, so series survive dashboard restarts.
            # Tagged src=dash so _timeseries can query exactly its own
            # families back without pattern-matching names.
            if points:
                try:
                    import os as _os

                    self._gcs.send("push_metrics", {
                        "source": "dashboard", "pid": _os.getpid(),
                        "time": now,
                        "points": [[name, {"src": "dash"}, float(v)]
                                   for name, v in points]})
                except Exception:  # noqa: BLE001 — GCS mid-restart
                    logger.debug("metric push failed", exc_info=True)
        finally:
            self._ts_sampling.release()

    def _ts_collect(self, now: float, points: list) -> None:
        """Gather one (name, value) point per series into `points`.
        Runs unlocked — must not touch the ring buffers."""
        add = lambda name, value: points.append((name, value))  # noqa: E731
        # 1) stage-latency percentiles from the local metrics registry
        from ray_tpu.util.metrics import get_metric

        hist = get_metric("ray_tpu_task_stage_seconds")
        if hist is not None and hasattr(hist, "quantiles_by"):
            for stage, qs in hist.quantiles_by("stage").items():
                for q, label in ((0.5, "p50"), (0.9, "p90"), (0.99, "p99")):
                    add(f"stage_{stage}_{label}", qs.get(q, 0.0))
        total_hist = get_metric("ray_tpu_task_total_seconds")
        if total_hist is not None and hasattr(total_hist, "quantiles_by"):
            merged = total_hist.quantiles_by("type")
            for ttype, qs in merged.items():
                for q, label in ((0.5, "p50"), (0.9, "p90"), (0.99, "p99")):
                    add(f"task_total_{ttype}_{label}", qs.get(q, 0.0))
        # 1.5) LLM serving series (serve.llm): scrape replica metric
        # snapshots into the local registry (no-op unless serve is
        # running and reachable from this process), then sample the
        # merged TTFT/TPOT quantiles and queue/occupancy gauges.
        try:
            from ray_tpu.serve.llm import metrics as llm_m

            llm_m.maybe_collect_local(timeout_s=2.0)
            for metric, label in ((llm_m.TTFT_NAME, "llm_ttft"),
                                  (llm_m.TPOT_NAME, "llm_tpot")):
                hist = get_metric(metric)
                if hist is not None and hasattr(hist, "quantiles_by"):
                    for dep, qs in hist.quantiles_by("deployment").items():
                        for q, ql in ((0.5, "p50"), (0.99, "p99")):
                            add(f"{label}_{dep}_{ql}", qs.get(q, 0.0))
            for metric, label in (
                    (llm_m.QUEUE_DEPTH_NAME, "llm_queue_depth"),
                    (llm_m.OCCUPANCY_NAME, "llm_batch_occupancy")):
                g = get_metric(metric)
                if g is not None:
                    for _, tags, v in g._samples():
                        add(f"{label}_{tags.get('replica', '')[:24]}", v)
        except Exception:  # noqa: BLE001 — serving stack not up
            pass
        # 1.55) device-plane performance (ISSUE 15): step-phase p50/p99
        # per phase (input_wait/h2d/compile/device_execute/reply), live
        # MFU, and HBM occupancy — the series that say whether the chip
        # is input-starved, recompiling, or compute-bound.
        hist = get_metric("ray_tpu_step_phase_seconds")
        if hist is not None and hasattr(hist, "quantiles_by"):
            for phase, qs in hist.quantiles_by("phase").items():
                for q, label in ((0.5, "p50"), (0.99, "p99")):
                    add(f"device_phase_{phase}_{label}", qs.get(q, 0.0))
        for metric, label in (("ray_tpu_device_mfu", "device_mfu"),
                              ("ray_tpu_hbm_bytes_in_use", "hbm_in_use"),
                              ("ray_tpu_hbm_bytes_peak", "hbm_peak")):
            g = get_metric(metric)
            if g is not None:
                for _, tags, v in g._samples():
                    tag = (tags.get("profiler") or tags.get("device")
                           or "")[:24]
                    add(f"{label}_{tag}", v)
        # 1.6) overload protection (ISSUE 9): cluster-wide shed and
        # doomed-work totals from the GCS event manager's per-type
        # counts (covers every process, not just this one's registry),
        # plus this process's retry-budget fail-fast counter.
        try:
            stats = self._gcs.call("get_event_log_stats", {}, timeout=5)
            by_type = stats.get("by_type") or {}
            add("overload_shed_total", float(by_type.get("task.shed", 0)))
            add("overload_deadline_expired_total",
                float(by_type.get("task.deadline_expired", 0)))
        except Exception:  # noqa: BLE001 — GCS unreachable mid-sample
            pass
        budget_c = get_metric("ray_tpu_retry_budget_exhausted_total")
        if budget_c is not None:
            try:
                add("retry_budget_exhausted_total",
                    float(sum(v for _, v in budget_c._values.items())))
            except Exception:  # noqa: BLE001
                pass
        # 2) task throughput from GCS task events. Count FINISHED events
        # past a PER-JOB watermark over EVENT timestamps — a delta of the
        # windowed count would flatline to zero once the event store holds
        # more than the fetch window (exactly when the cluster is
        # busiest), a sample-wall-time cutoff would drop every event still
        # in an owner's ~1s flush buffer at fetch time, and one global
        # watermark would drop a lagging driver's events whenever another
        # driver's fresher flush landed first.
        try:
            events = self._gcs.call(
                "get_task_events", {"job_id": None, "limit": 10_000},
                timeout=5)
            wms = self._ts_event_watermarks
            fresh = 0
            batch_max: Dict[str, float] = {}
            for ev in events:
                if ev.get("state") != "FINISHED":
                    continue
                job, t = ev.get("job_id", ""), ev.get("time", 0)
                if t > wms.get(job, 0.0):
                    fresh += 1
                    if t > batch_max.get(job, 0.0):
                        batch_max[job] = t
            # marks advance only after the whole batch is counted — doing
            # it mid-loop would drop same-batch events older than a
            # fresher sibling
            wms.update(batch_max)
            self._ts_finished_cum += fresh
            add("tasks_finished_total", self._ts_finished_cum)
            # rate over the span since the last SUCCESSFUL fetch: using
            # the plain sample time would divide a whole GCS outage's
            # backlog by one 5s interval and render a phantom spike
            prev = self._ts_tp_prev_t
            if prev is not None and now > prev:
                add("task_throughput", fresh / (now - prev))
            self._ts_tp_prev_t = now
        except Exception:  # noqa: BLE001 — GCS restarting
            pass
        # 3) per-node raylet stats: store usage + lease queue depth
        try:
            nodes = self._gcs.call("get_all_node_info", {}, timeout=5)
        except Exception:  # noqa: BLE001
            nodes = []
        store_used = store_cap = 0
        active = queued = 0
        got_store = False
        for n in nodes:
            if not n.alive:
                continue
            try:
                st = self._raylets.get(n.raylet_address).call(
                    "get_node_stats", {}, timeout=3)
            except Exception:  # noqa: BLE001 — node mid-death
                continue
            active += st.get("active_leases", 0)
            queued += st.get("queued_leases", 0)
            store = st.get("store")
            if store:
                got_store = True
                store_used += store.get("used_bytes", 0)
                store_cap += store.get("capacity_bytes", 0)
        add("leases_active", active)
        add("leases_queued", queued)
        if got_store:
            add("store_used_bytes", store_used)
            add("store_capacity_bytes", store_cap)
        # 3b) memory plane: spill bytes + cluster ref/KV-block totals from
        # the cheap ({"refs": False}) get_cluster_memory fan-out. The same
        # report refreshes the ray_tpu_object_store_*/object_refs/
        # kv_blocks prometheus gauges served at /metrics.
        try:
            from ray_tpu._private import memory_obs

            mem = self._gcs.call(
                "get_cluster_memory",
                {"refs": False, "node_timeout_s": 4.0,
                 "worker_timeout_s": 2.0}, timeout=5)
            memory_obs.export_metrics(mem)
            spilled = 0
            refs = {"owned": 0, "borrowed": 0, "pinned": 0}
            kv = {"free": 0, "cached": 0, "active": 0}
            for node in (mem.get("nodes") or {}).values():
                if not isinstance(node, dict) or "error" in node:
                    continue
                spilled += (node.get("spill") or {}).get("bytes") or 0
            for _nid, _pid, rep in memory_obs.iter_worker_reports(mem):
                counts = rep.get("counts") or {}
                refs["owned"] += counts.get("num_owned", 0)
                refs["borrowed"] += counts.get("num_borrowed", 0)
                refs["pinned"] += counts.get("num_pinned", 0)
                for rpt in rep.get("kv") or ():
                    for state in kv:
                        kv[state] += int(rpt.get(f"{state}_blocks", 0))
            add("store_spilled_bytes", spilled)
            for kind, n in refs.items():
                add(f"object_refs_{kind}", n)
            if any(kv.values()):
                for state, n in kv.items():
                    add(f"kv_blocks_{state}", n)
        except Exception:  # noqa: BLE001 — GCS predating the RPC
            pass
        # 4) per-node CPU via the dashboard agents
        try:
            agents = self._agents()
        except Exception:  # noqa: BLE001
            agents = {}
        import urllib.request

        for node_id, url in agents.items():
            try:
                with urllib.request.urlopen(
                        f"{url}/api/local/stats", timeout=2) as resp:
                    st = json.loads(resp.read().decode())
                cpu = st.get("cpu_percent")
                if cpu is not None:
                    add(f"node_cpu_percent_{node_id[:8]}", cpu)
            except Exception:  # noqa: BLE001 — agent down
                continue

    def _timeseries(self) -> Dict[str, Any]:
        # Thin query over the GCS health store (ISSUE 20): the sampler
        # pushes every collected point there tagged src=dash, so the
        # series are cluster-wide state that survives dashboard restarts.
        # The local ring buffers stay as the fallback when the GCS (or a
        # GCS predating the RPC) can't answer. Sample on demand ONLY
        # while the rings are still empty — so the first page load has
        # data, without paying the multi-second cluster fan-out on an
        # HTTP request thread during an incident (nodes mid-death make
        # the fan-out slowest exactly when the user opens the dashboard
        # to look).
        with self._ts_lock:
            empty = not self._ts
        if empty:
            try:
                self._ts_sample()
            except Exception:  # noqa: BLE001
                logger.debug("on-demand sample failed", exc_info=True)
        now = time.time()
        series: Dict[str, list] = {}
        try:
            for row in self._gcs.call(
                    "query_metrics",
                    {"tags": {"src": "dash"}, "resolution": "raw",
                     "since": now - 3600.0, "limit_series": 500},
                    timeout=10):
                series[row["name"]] = [list(p) for p in row["points"]]
        except Exception:  # noqa: BLE001 — store-less GCS: local rings
            logger.debug("query_metrics failed; serving local rings",
                         exc_info=True)
            with self._ts_lock:
                series = {k: list(v) for k, v in self._ts.items()}
        # Per-series staleness from each point's collection stamp, plus
        # sampler health — so the SPA and the health scorecard can
        # distinguish a legitimately flat series from a dead sampler.
        stale_s = {
            name: round(now - pts[-1][0], 1)
            for name, pts in series.items() if pts}
        with self._ts_lock:
            last_success = self._ts_last_success
            failures = self._ts_fail_count
        return {
            "now": now,
            "sample_period_s": TS_SAMPLE_PERIOD_S,
            "series": series,
            "stale_s": stale_s,
            "stale_after_s": TS_SAMPLE_PERIOD_S * 3,
            "sampler": {
                "last_success": last_success,
                "age_s": (round(now - last_success, 1)
                          if last_success else None),
                "consecutive_failures": failures,
                "healthy": bool(
                    last_success
                    and now - last_success < TS_SAMPLE_PERIOD_S * 3),
            },
        }

    def _metrics_text(self) -> str:
        from ray_tpu.util.metrics import prometheus_text

        lines = [prometheus_text()]
        try:
            status = self._cluster_status()
            for k, v in status["resources_total"].items():
                name = k.replace(":", "_").replace(".", "_")
                lines.append(
                    f'ray_tpu_cluster_resource_total{{resource="{name}"}} {v}')
            for k, v in status["resources_available"].items():
                name = k.replace(":", "_").replace(".", "_")
                lines.append(
                    f'ray_tpu_cluster_resource_available{{resource="{name}"}}'
                    f' {v}')
            alive = sum(1 for n in status["nodes"].values() if n["alive"])
            from ray_tpu.util.metrics import get_metric

            if get_metric("ray_tpu_cluster_nodes_alive") is None:
                # embedded heads share a registry with the GCS, whose
                # metrics manager exports this as a real gauge — don't
                # emit the raw line twice
                lines.append(f"ray_tpu_cluster_nodes_alive {alive}")
        except Exception:  # noqa: BLE001 — GCS may be mid-restart
            pass
        return "\n".join(lines) + "\n"

    def _index_html(self) -> str:
        status = self._cluster_status()
        rows = "".join(
            f"<tr><td>{nid[:12]}</td>"
            f"<td>{'ALIVE' if n['alive'] else 'DEAD'}</td>"
            f"<td>{n['total']}</td></tr>"
            for nid, n in status["nodes"].items())
        return (
            "<html><head><title>ray_tpu dashboard</title></head><body>"
            "<h2>ray_tpu cluster</h2>"
            f"<p>GCS: {self.gcs_address}</p>"
            f"<p>Resources: {status['resources_available']} free of "
            f"{status['resources_total']}</p>"
            "<table border=1><tr><th>node</th><th>state</th>"
            f"<th>resources</th></tr>{rows}</table>"
            "<p>APIs: /api/cluster_status /api/nodes /api/actors /api/jobs "
            "/api/placement_groups /api/tasks /metrics</p>"
            "</body></html>")

    def stop(self) -> None:
        self._ts_stop.set()
        self._httpd.shutdown()
        self._httpd.server_close()
        self._raylets.close_all()
        self._gcs.close()
        self._lt.stop()
