from ray_tpu.dashboard.head import DashboardHead  # noqa: F401
