/* ray_tpu dashboard SPA: hash-routed pages over the JSON state API
   (/api/cluster_status, /api/nodes, /api/actors, /api/tasks, /api/jobs/,
   /api/placement_groups, /api/serve, /api/logs). Vanilla JS, no build. */
"use strict";

const $ = (sel) => document.querySelector(sel);
const esc = (s) => String(s ?? "").replace(/[&<>"]/g,
  (c) => ({"&": "&amp;", "<": "&lt;", ">": "&gt;", '"': "&quot;"}[c]));

async function getJSON(path) {
  const r = await fetch(path);
  if (!r.ok) throw new Error(`${path}: HTTP ${r.status}`);
  return r.json();
}

function table(headers, rows) {
  if (!rows.length) return '<p class="muted">none</p>';
  const head = headers.map((h) => `<th>${esc(h)}</th>`).join("");
  const body = rows.map((r) => `<tr>${r.join("")}</tr>`).join("");
  return `<table><tr>${head}</tr>${body}</table>`;
}

const td = (v, cls) => `<td${cls ? ` class="${cls}"` : ""}>${v}</td>`;

function statusCell(text) {
  const t = String(text).toUpperCase();
  const cls = t === "ALIVE" || t === "RUNNING" || t === "FINISHED" ||
      t === "SUCCEEDED" || t === "CREATED" || t === "OK" ? "ok"
    : t === "DEAD" || t === "FAILED" || t === "REMOVED" ? "dead"
    : "warn";
  return td(`<span class="status ${cls}">${esc(text)}</span>`);
}

function meter(name, used, total) {
  const pct = total > 0 ? Math.min(100, 100 * used / total) : 0;
  return `<div class="meter">
    <div class="label"><span>${esc(name)}</span>
      <span>${used.toFixed(1)} / ${total.toFixed(1)}</span></div>
    <div class="track"><div class="fill" style="width:${pct}%"></div></div>
  </div>`;
}

// ---- pages -----------------------------------------------------------------

async function pageOverview() {
  const s = await getJSON("/api/cluster_status");
  const nodes = Object.values(s.nodes || {});
  const alive = nodes.filter((n) => n.alive).length;
  let actors = [], version = {};
  try { actors = await getJSON("/api/actors"); } catch {}
  try { version = await getJSON("/api/version"); } catch {}
  const tiles = [
    ["nodes alive", `${alive} / ${nodes.length}`],
    ["actors", actors.filter((a) => a.state === "ALIVE").length],
    ["pending demands", (s.pending_demands || []).length],
    ["GCS", esc(version.gcs_address || "?")],
  ].map(([k, v]) =>
    `<div class="tile"><div class="v">${v}</div>
     <div class="k">${k}</div></div>`).join("");
  const meters = Object.keys(s.resources_total || {}).sort().map((k) => {
    const total = s.resources_total[k] || 0;
    const used = total - (s.resources_available[k] || 0);
    return meter(k, used, total);
  }).join("");
  return `<h2>Cluster</h2><div class="tiles">${tiles}</div>
    <h3>Resource utilization</h3>${meters || '<p class="muted">none</p>'}`;
}

async function pageNodes() {
  const nodes = await getJSON("/api/nodes");
  let agents = {};
  try { agents = await getJSON("/api/agents"); } catch {}
  return `<h2>Nodes</h2>` + table(
    ["node id", "state", "role", "address", "resources (avail / total)", ""],
    nodes.map((n) => [
      td(esc(n.node_id.slice(0, 12)), "mono"),
      statusCell(n.state),
      td(n.is_head_node ? "head" : "worker"),
      td(esc(n.raylet_address), "mono"),
      td(esc(fmtRes(n.resources_available)) + " / " +
         esc(fmtRes(n.resources_total)), "mono"),
      td(agents[n.node_id]
         ? `<a href="#node-${esc(n.node_id)}">detail</a>` : ""),
    ]));
}

async function pageNode(nodeId) {
  const short = nodeId.slice(0, 12);
  let s;
  try { s = await getJSON(`/api/nodes/${nodeId}/stats`); }
  catch (e) {
    return `<h2>Node ${esc(short)}</h2>
      <p class="error">agent unreachable: ${esc(e)}</p>`;
  }
  const mem = s.mem || {};
  const gib = (b) => (b / 2 ** 30).toFixed(2);
  const tiles = [
    ["node CPU %", s.cpu_percent ?? "…"],
    ["load (1m)", (s.load_avg || [])[0]?.toFixed?.(2) ?? "-"],
    ["mem avail", `${gib(mem.available_bytes || 0)} /
                   ${gib(mem.total_bytes || 0)} GiB`],
    ["workers", (s.workers || []).length],
  ].map(([k, v]) => `<div class="tile"><div class="v">${v}</div>
      <div class="k">${k}</div></div>`).join("");
  const workers = table(
    ["pid", "kind", "rss", "cpu %", "profile"],
    (s.workers || []).map((w) => [
      td(w.pid, "mono"),
      td(w.registered ? "worker" : "fork-server"),
      td(`${(w.rss_bytes / 2 ** 20).toFixed(1)} MiB`),
      td(w.cpu_percent ?? "…"),
      td(w.registered
         ? `<button class="secondary"
             onclick="profileWorker('${esc(nodeId)}', ${w.pid})">
             cpu 5s</button>` : ""),
    ]));
  return `<h2>Node ${esc(short)}</h2><div class="tiles">${tiles}</div>
    <h3>Worker processes</h3>${workers}
    <div id="profile-out"></div>`;
}

window.profileWorker = async (nodeId, pid) => {
  const out = $("#profile-out");
  window._busy = true;  // pause auto-rerender while sampling
  out.innerHTML = `<h3>profile pid ${pid}</h3>
    <pre class="logbox">sampling 5s…</pre>`;
  try {
    const r = await getJSON(
      `/api/nodes/${nodeId}/profile?pid=${pid}&duration=5`);
    const folded = Object.entries(r.folded || {})
      .sort((a, b) => b[1] - a[1])
      .map(([k, v]) => `${k} ${v}`).join("\n");
    out.querySelector("pre").textContent =
      r.error ? `error: ${r.error}`
      : folded || JSON.stringify(r, null, 2);
  } catch (e) { out.querySelector("pre").textContent = String(e); }
  window._busy = false;
};

const TIMELINE_MAX_SPANS = 2000;

async function pageTimeline() {
  const trace = await getJSON("/api/timeline");
  window._trace = trace;  // for the on-click chrome-trace download
  let spans = trace.filter((e) => e.ph === "X");
  const total = spans.length;
  if (!total) {
    return `<h2>Task timeline</h2>
      <p class="muted">no finished tasks recorded yet.</p>`;
  }
  // keep the DOM bounded on long histories: newest spans win
  spans.sort((a, b) => a.ts - b.ts);
  spans = spans.slice(-TIMELINE_MAX_SPANS);
  const t0 = Math.min(...spans.map((e) => e.ts));
  const t1 = Math.max(...spans.map((e) => e.ts + (e.dur || 0)));
  const range = Math.max(1, t1 - t0);
  // one swimlane per worker thread, grouped by node
  const lanes = new Map();
  for (const e of spans) {
    const key = `${e.pid} · ${e.tid}`;
    if (!lanes.has(key)) lanes.set(key, []);
    lanes.get(key).push(e);
  }
  const laneHtml = [...lanes.entries()].map(([key, evs]) => {
    const bars = evs.map((e) => {
      const left = (100 * (e.ts - t0) / range).toFixed(3);
      const width = Math.max(0.15, 100 * (e.dur || 0) / range).toFixed(3);
      const ms = ((e.dur || 0) / 1000).toFixed(1);
      const parent = e.args?.parent
        ? ` ← ${String(e.args.parent).slice(0, 8)}` : "";
      return `<div class="span" style="left:${left}%;width:${width}%"
        title="${esc(e.name)} (${ms} ms)${esc(parent)}
task ${esc(String(e.args?.task_id || "").slice(0, 12))}">
        ${esc(e.name)}</div>`;
    }).join("");
    return `<div class="lane"><div class="lane-label mono">
      ${esc(key)}</div><div class="lane-track">${bars}</div></div>`;
  }).join("");
  const shown = spans.length < total
    ? ` (showing newest ${spans.length} of ${total})` : "";
  return `<h2>Task timeline
    <span class="muted">(${total} spans${shown},
     ${((t1 - t0) / 1e6).toFixed(2)}s)</span></h2>
    <p><a href="#" onclick="return downloadTrace()">download chrome
      trace</a>
      <span class="muted"> — open in Perfetto / chrome://tracing for the
      full flow-arrow tree</span></p>
    <div class="timeline">${laneHtml}</div>`;
}

window.downloadTrace = () => {
  // built on demand: serializing the whole trace into an href on every
  // 5s auto-refresh would churn MBs of attribute data
  const blob = new Blob([JSON.stringify(window._trace || [])],
                        {type: "application/json"});
  const a = document.createElement("a");
  a.href = URL.createObjectURL(blob);
  a.download = "timeline.json";
  a.click();
  setTimeout(() => URL.revokeObjectURL(a.href), 5000);
  return false;
};

async function pageTraces() {
  // distributed-request trace lookup (/api/trace): recent sampled or
  // force-kept traces, plus lookup by the X-Trace-Id a response carried
  const hash = location.hash.slice(1);
  const traceId = hash.startsWith("traces-") ? hash.slice(7) : null;
  const lookup = `<form onsubmit="location.hash =
      'traces-' + this.tid.value.trim(); return false">
    <input name="tid" class="mono" size="36"
      placeholder="trace id (X-Trace-Id header)"
      value="${esc(traceId || "")}">
    <button>look up</button></form>`;
  if (traceId) {
    const t = await getJSON(
      `/api/trace?trace_id=${encodeURIComponent(traceId)}`);
    const spans = t.spans || [];
    if (!spans.length) {
      return `<h2>Trace</h2>${lookup}
        <p class="muted">no spans stored for
        <span class="mono">${esc(traceId)}</span> (unsampled traces age
        out unless force-kept).</p>`;
    }
    const forced = t.forced
      ? `<p>force-kept: <span class="status warn">
          ${esc(t.forced_reason)}</span></p>` : "";
    const events = (t.events || []).map((e) =>
      `<tr>${td(new Date(e.time * 1000).toLocaleTimeString())}
       ${td(esc(e.proc))}${td(esc(e.type), "mono")}</tr>`).join("");
    return `<h2>Trace <span class="mono">${esc(traceId)}</span></h2>
      ${lookup}${forced}
      <pre class="mono">${esc(t.tree)}</pre>
      ${events ? `<h3>lifecycle events</h3>
        <table><tr><th>time</th><th>proc</th><th>type</th></tr>
        ${events}</table>` : ""}`;
  }
  const data = await getJSON("/api/trace");
  const rows = (data.traces || []).map((t) => [
    td(`<a href="#traces-${esc(t.trace_id)}" class="mono">
        ${esc(t.trace_id)}</a>`),
    td(new Date(t.start * 1000).toLocaleTimeString()),
    td((t.duration_s * 1e3).toFixed(2) + " ms"),
    td(t.spans), td((t.procs || []).length),
    td(esc(t.root || "")),
    t.forced_reason ? statusCell(t.forced_reason) : td("-"),
  ]);
  return `<h2>Traces
      <span class="muted">(sampled or force-kept)</span></h2>
    ${lookup}
    ${table(["trace id", "start", "duration", "spans", "procs", "root",
             "force-kept"], rows)}`;
}

function fmtRes(r) {
  return Object.entries(r || {}).sort()
    .map(([k, v]) => `${k}:${(+v).toFixed(1)}`).join(" ") || "-";
}

async function pageActors() {
  const actors = await getJSON("/api/actors");
  return `<h2>Actors</h2>` + table(
    ["actor id", "class", "name", "state", "pid", "restarts"],
    actors.map((a) => [
      td(esc(a.actor_id.slice(0, 12)), "mono"),
      td(esc(a.class_name)),
      td(esc(a.name || "-")),
      statusCell(a.state),
      td(a.pid || "-"),
      td(a.restarts),
    ]));
}

async function pageTasks() {
  const tasks = await getJSON("/api/tasks");
  tasks.sort((a, b) => (b.ts || 0) - (a.ts || 0));
  return `<h2>Tasks <span class="muted">(latest state, newest first,
    up to 10k)</span></h2>` + table(
    ["task", "type", "state", "job"],
    tasks.slice(0, 500).map((t) => [
      td(esc(t.name || t.func || "?")),
      td(esc(t.type || "")),
      statusCell(t.state || "?"),
      td(esc(String(t.job_id || "").slice(0, 8)), "mono"),
    ]));
}

async function pageJobs() {
  let subs = [];
  try { subs = await getJSON("/api/jobs/"); } catch {}
  const drivers = await getJSON("/api/jobs");
  const form = `
    <form class="inline" onsubmit="return submitJob(event)">
      <input type="text" id="entrypoint"
             placeholder="entrypoint, e.g. python my_job.py">
      <button>Submit job</button>
    </form><div id="submit-out" class="muted"></div>`;
  const subTable = table(
    ["submission", "entrypoint", "status", "message", ""],
    subs.map((j) => [
      td(esc(j.submission_id), "mono"),
      td(esc(j.entrypoint)),
      statusCell(j.status),
      td(esc(j.message || "")),
      td(`<button class="secondary"
           onclick="jobLogs('${esc(j.submission_id)}')">logs</button>`),
    ]));
  const drvTable = table(
    ["job id", "driver", "state"],
    drivers.map((j) => [
      td(esc(j.job_id), "mono"),
      td(esc(j.driver_address), "mono"),
      statusCell(j.is_dead ? "DEAD" : "ALIVE"),
    ]));
  return `<h2>Jobs</h2>${form}
    <h3>Submissions</h3>${subTable}
    <div id="job-logs"></div>
    <h3>Drivers</h3>${drvTable}`;
}

window.submitJob = async (ev) => {
  ev.preventDefault();
  const entrypoint = $("#entrypoint").value.trim();
  if (!entrypoint) return false;
  $("#submit-out").textContent = "submitting…";
  try {
    const r = await fetch("/api/jobs", {
      method: "POST", headers: {"Content-Type": "application/json"},
      body: JSON.stringify({entrypoint}),
    });
    const body = await r.json();
    $("#submit-out").textContent =
      r.ok ? `submitted: ${body.submission_id}` : `error: ${body}`;
  } catch (e) { $("#submit-out").textContent = `error: ${e}`; }
  return false;
};

window.jobLogs = async (sid) => {
  const out = $("#job-logs");
  out.innerHTML = `<h3>logs: ${esc(sid)}</h3>
    <pre class="logbox">loading…</pre>`;
  try {
    const r = await getJSON(`/api/jobs/${sid}/logs`);
    out.querySelector("pre").textContent = r.logs || "(empty)";
  } catch (e) { out.querySelector("pre").textContent = String(e); }
};

async function pagePGs() {
  const pgs = await getJSON("/api/placement_groups");
  return `<h2>Placement groups</h2>` + table(
    ["pg id", "name", "strategy", "state", "bundles"],
    pgs.map((p) => [
      td(esc(String(p.placement_group_id || p.id || "")).slice(0, 12),
         "mono"),
      td(esc(p.name || "-")),
      td(esc(p.strategy || "")),
      statusCell(p.state || "?"),
      td(esc(JSON.stringify(p.bundles || [])), "mono"),
    ]));
}

async function pageServe() {
  let s;
  try { s = await getJSON("/api/serve"); }
  catch { return `<h2>Serve</h2><p class="muted">serve is not running
    (or the controller is unreachable).</p>`; }
  const apps = Object.entries(s.applications || {});
  // control-plane FT posture: incarnation, checkpoint freshness, and
  // the last recovery's adopted-vs-restarted replica split
  let ctl = "";
  if (s.controller) {
    const c = s.controller;
    const age = c.last_checkpoint_age_s;
    const bits = [`incarnation ${esc(String(c.incarnation))}`,
                  `${esc(String(c.checkpoints_written || 0))} checkpoint(s)` +
                  (age != null ? ` (last ${Number(age).toFixed(1)}s ago)`
                              : "")];
    if (c.recovered_at) {
      bits.push(`last recovery adopted ` +
        `${esc(String(c.adopted_replicas || 0))} replica(s) + ` +
        `${esc(String(c.adopted_proxies || 0))} proxy shard(s), ` +
        `${esc(String(c.restarted_replicas || 0))} restarted`);
    }
    ctl = `<p class="muted">controller: ${bits.join(" · ")}</p>`;
  }
  if (!apps.length) {
    return `<h2>Serve</h2>${ctl}
      <p class="muted">no applications deployed.</p>`;
  }
  const rows = [];
  for (const [app, info] of apps) {
    for (const [dep, d] of Object.entries(info.deployments || {})) {
      rows.push([
        td(esc(app)), td(esc(dep)), statusCell(d.status || "?"),
        td(d.replica_states ? esc(JSON.stringify(d.replica_states))
           : String(d.num_replicas ?? "-")),
        td(esc(d.message || "")),
      ]);
    }
  }
  return `<h2>Serve</h2>` + ctl + table(
    ["application", "deployment", "status", "replicas", "message"], rows);
}

// ---- live metrics ----------------------------------------------------------

const CHART_COLORS = ["#4f86f7", "#e0723c", "#3cb371", "#c95fcf",
                      "#d9b036", "#56b8c9", "#e05c6c", "#8a8f98"];

function svgChart(title, series, fmt, gapS) {
  // series: [{name, points: [[t, v], ...], stale}]; vanilla inline SVG,
  // no deps. Points carry their collection stamps, so a sampling gap
  // larger than `gapS` BREAKS the line instead of drawing a flat bridge
  // — a dead sampler looks dead, not flat.
  const W = 560, H = 150, PAD = 36;
  const all = series.flatMap((s) => s.points);
  if (!all.length) {
    return `<div class="chart"><h4>${esc(title)}</h4>
      <p class="muted">no samples yet</p></div>`;
  }
  const t0 = Math.min(...all.map((p) => p[0]));
  const t1 = Math.max(...all.map((p) => p[0]));
  const vmax = Math.max(...all.map((p) => p[1]), 1e-12);
  const sx = (t) => PAD + (W - PAD - 6) * (t1 > t0 ? (t - t0) / (t1 - t0) : 1);
  const sy = (v) => H - 18 - (H - 30) * (v / vmax);
  const lines = series.map((s, i) => {
    const color = CHART_COLORS[i % CHART_COLORS.length];
    // split into segments at sampling gaps
    const segs = [];
    let seg = [];
    for (const p of s.points) {
      if (seg.length && gapS && p[0] - seg[seg.length - 1][0] > gapS) {
        segs.push(seg); seg = [];
      }
      seg.push(p);
    }
    if (seg.length) segs.push(seg);
    return segs.map((pts) => {
      if (pts.length === 1) {
        const [t, v] = pts[0];
        return `<circle cx="${sx(t).toFixed(1)}" cy="${sy(v).toFixed(1)}"
          r="2.5" fill="${color}"/>`;
      }
      const pstr = pts.map(
        (p) => `${sx(p[0]).toFixed(1)},${sy(p[1]).toFixed(1)}`).join(" ");
      return `<polyline points="${pstr}" fill="none" stroke="${color}"
        stroke-width="1.5"/>`;
    }).join("");
  }).join("");
  const legend = series.map((s, i) => {
    const color = CHART_COLORS[i % CHART_COLORS.length];
    const last = s.points.length ? s.points[s.points.length - 1][1] : 0;
    const stale = s.stale
      ? ` <span class="status dead">stale ${s.stale}s</span>` : "";
    return `<span class="legend-item">
      <span class="swatch" style="background:${color}"></span>
      ${esc(s.name)} <span class="muted">${fmt(last)}</span>${stale}</span>`;
  }).join(" ");
  const span = Math.max(1, t1 - t0);
  return `<div class="chart"><h4>${esc(title)}</h4>
    <svg viewBox="0 0 ${W} ${H}" preserveAspectRatio="none">
      <line x1="${PAD}" y1="${H - 18}" x2="${W - 4}" y2="${H - 18}"
        class="axis"/>
      <line x1="${PAD}" y1="6" x2="${PAD}" y2="${H - 18}" class="axis"/>
      <text x="4" y="14" class="axis-label">${esc(fmt(vmax))}</text>
      <text x="4" y="${H - 22}" class="axis-label">0</text>
      <text x="${W - 4}" y="${H - 4}" class="axis-label"
        text-anchor="end">last ${(span).toFixed(0)}s</text>
      ${lines}
    </svg>
    <div class="legend">${legend}</div></div>`;
}

async function pageMetrics() {
  const data = await getJSON("/api/metrics_timeseries");
  const series = data.series || {};
  const staleAfter = data.stale_after_s || 15;
  const staleS = data.stale_s || {};
  const pick = (re) => Object.keys(series).filter((k) => re.test(k)).sort()
    .map((k) => ({name: k, points: series[k],
                  stale: staleS[k] > staleAfter
                    ? staleS[k].toFixed(0) : null}));
  const ms = (v) => `${(v * 1e3).toFixed(2)}ms`;
  const num = (v) => v >= 100 ? v.toFixed(0) : v.toFixed(2);
  const mib = (v) => `${(v / 2 ** 20).toFixed(1)}MiB`;
  const pct = (v) => `${num(v)}%`;
  // break chart lines at sampling gaps wider than the staleness bound
  const chart = (t, s, f) => svgChart(t, s, f, staleAfter);
  const charts = [
    chart("Task throughput (tasks/s)",
             pick(/^task_throughput$/), num),
    chart("Stage latency p50 (submit/queue/rpc/dispatch/execute/reply)",
             pick(/^stage_.*_p50$/), ms),
    chart("Stage latency p99", pick(/^stage_.*_p99$/), ms),
    chart("End-to-end task latency",
             pick(/^task_total_.*_p(50|90|99)$/), ms),
    chart("Object store used (arena / capacity / spilled)",
             pick(/^store_(used|capacity|spilled)_bytes$/), mib),
    chart("Object refs (owned / borrowed / pinned, cluster-wide)",
             pick(/^object_refs_/), num),
    chart("KV blocks (free / cached / active)",
             pick(/^kv_blocks_/), num),
    chart("Worker leases (active / queued)",
             pick(/^leases_/), num),
    chart("Node CPU %", pick(/^node_cpu_percent_/), pct),
    chart("LLM serving latency (TTFT / TPOT p50,p99)",
             pick(/^llm_t(tft|pot)_/), ms),
    chart("LLM queue depth (per engine replica)",
             pick(/^llm_queue_depth_/), num),
    chart("LLM batch occupancy", pick(/^llm_batch_occupancy_/), num),
    chart("Device step phases p50 (input_wait/h2d/compile/execute/reply)",
             pick(/^device_phase_.*_p50$/), ms),
    chart("Device step phases p99", pick(/^device_phase_.*_p99$/), ms),
    chart("Device MFU (per profiler)", pick(/^device_mfu_/), num),
    chart("HBM bytes (in use / peak, per device)",
             pick(/^hbm_(in_use|peak)_/), mib),
  ].join("");
  const smp = data.sampler || {};
  const banner = smp.healthy === false
    ? `<p class="error">sampler unhealthy: last successful sample
       ${smp.age_s != null ? smp.age_s + "s ago" : "never"}
       (${smp.consecutive_failures || 0} consecutive failures) —
       series below are STALE, not flat</p>` : "";
  return `<h2>Live metrics
    <span class="muted">(GCS health store, ${data.sample_period_s ?? 5}s
    cadence; stage series need task activity in the head's process)</span>
    </h2>${banner}<div class="charts">${charts}</div>`;
}

// ---- health (ISSUE 20: SLO scorecard + alerts + demand signals) ------------

const fmtNum = (v) => v == null ? "-"
  : Math.abs(v) >= 100 ? Number(v).toFixed(0) : Number(v).toFixed(3);

async function pageHealth() {
  let h;
  try { h = await getJSON("/api/health"); }
  catch (e) {
    return `<h2>Health</h2><p class="muted">health plane unavailable
      (GCS predating it, or unreachable): ${esc(e)}</p>`;
  }
  let hist = [];
  try { hist = (await getJSON("/api/alerts")).history || []; } catch {}
  const d = h.demand || {};
  const store = h.store || {};
  const tiles = [
    ["alerts firing", (h.alerts || []).length],
    ["nodes alive", d.nodes_alive ?? "-"],
    ["req rate /s", fmtNum((d.serve || {}).request_rate)],
    ["shed rate /s", fmtNum((d.serve || {}).shed_rate)],
    ["TTFT p99 s", fmtNum((d.serve || {}).ttft_p99_s)],
    ["metric series", store.series ?? "-"],
  ].map(([k, v]) => `<div class="tile"><div class="v">${v}</div>
      <div class="k">${k}</div></div>`).join("");
  const score = table(
    ["rule", "severity", "state", "value", "threshold", "description"],
    (h.scorecard || []).map((r) => [
      td(esc(r.rule), "mono"),
      td(esc(r.severity)),
      statusCell(r.firing ? "FIRING" : "OK"),
      td(fmtNum(r.value), "mono"),
      td(fmtNum(r.threshold), "mono"),
      td(esc(r.description || "")),
    ]));
  const hrows = hist.slice(-50).reverse().map((ev) => [
    td(new Date(ev.time * 1000).toLocaleTimeString()),
    statusCell(ev.type === "alert.firing" ? "FIRING" : "RESOLVED"),
    td(esc(ev.rule), "mono"),
    td(esc(ev.severity)),
    td(ev.duration_s != null ? `${fmtNum(ev.duration_s)}s`
       : fmtNum(ev.value), "mono"),
  ]);
  const pools = Object.entries(d.pools || {}).sort().map(([k, p]) =>
    meter(k, p.total - p.available, p.total)).join("");
  const pending = d.pending || {};
  const pushRows = Object.entries(h.push_sources || {}).sort().map(
    ([src, st]) => [
      td(esc(src), "mono"), td(st.pushed ?? 0),
      td(st.dropped ?? 0, st.dropped ? "dead" : ""),
      td(`${fmtNum(st.lag_s)}s`),
    ]);
  return `<h2>Health
      <span class="muted">(SLO scorecard · burn-rate alerts · demand
      signals)</span></h2>
    <div class="tiles">${tiles}</div>
    <h3>SLO scorecard</h3>${score}
    <h3>Alert history <span class="muted">(newest first)</span></h3>
    ${table(["time", "event", "rule", "severity", "value/duration"], hrows)}
    <h3>Demand signals</h3>
    <p class="muted">pending PG bundles:
      ${esc(JSON.stringify(pending.pg_bundles || []))} · task demands:
      ${esc(JSON.stringify(pending.task_demands || []))}</p>
    ${pools || '<p class="muted">no pool data</p>'}
    <h3>Metric push sources</h3>
    ${table(["source", "pushed", "dropped", "lag"], pushRows)}
    <p class="muted">store: ${store.series ?? 0} series
      (${store.series_dropped ?? 0} refused past the bound),
      ${store.points_ingested ?? 0} points ingested</p>`;
}

async function pageLogs() {
  const data = await getJSON("/api/logs?lines=200");
  const blocks = Object.entries(data.nodes || data || {}).map(
    ([node, files]) => {
      const inner = Object.entries(files || {}).map(
        ([f, text]) => `<h3 class="mono">${esc(f)}</h3>
          <pre class="logbox">${esc(
            Array.isArray(text) ? text.join("\n") : text)}</pre>`).join("");
      return `<h3>node ${esc(node.slice ? node.slice(0, 12) : node)}</h3>
        ${inner || '<p class="muted">no worker logs</p>'}`;
    }).join("");
  return `<h2>Worker logs <span class="muted">(last 200 lines)</span></h2>
    ${blocks || '<p class="muted">no logs</p>'}`;
}

// ---- router ----------------------------------------------------------------

const PAGES = {
  overview: pageOverview, nodes: pageNodes, actors: pageActors,
  tasks: pageTasks, jobs: pageJobs, pgs: pagePGs, serve: pageServe,
  logs: pageLogs, timeline: pageTimeline, metrics: pageMetrics,
  traces: pageTraces, health: pageHealth,
};
let timer = null;

async function render() {
  const page = (location.hash || "#overview").slice(1);
  const fn = page.startsWith("node-")
    ? () => pageNode(page.slice(5))
    : page.startsWith("traces-")
      ? pageTraces
      : PAGES[page] || pageOverview;
  document.querySelectorAll("#nav a").forEach((a) =>
    a.classList.toggle("active", a.hash === `#${page}` ||
      (a.hash === "#nodes" && page.startsWith("node-")) ||
      (a.hash === "#traces" && page.startsWith("traces-"))));
  try {
    const html = await fn();
    // jobs page holds form state + log/profile panes: skip auto-rerender
    // clobber (and never clobber while a profile is sampling)
    if ((location.hash || "#overview").slice(1) === page) {
      const active = document.activeElement;
      if (window._busy) { /* keep current DOM */ }
      else if (page !== "jobs" || !(active && active.tagName === "INPUT")) {
        $("#main").innerHTML = html;
      }
    }
    $("#refresh-state").textContent =
      `updated ${new Date().toLocaleTimeString()}`;
  } catch (e) {
    // same guards as the success path: a transient fetch error must not
    // clobber an in-flight profile pane or a page we've navigated off
    if (!window._busy &&
        (location.hash || "#overview").slice(1) === page) {
      $("#main").innerHTML = `<p class="error">${esc(e)}</p>`;
    }
  }
}

function loop() {
  clearInterval(timer);
  render();
  timer = setInterval(render, 5000);
}
window.addEventListener("hashchange", loop);
loop();
