"""Grafana dashboard generation (reference: ray
dashboard/modules/metrics/grafana_dashboard_factory.py — the dashboard
writes ready-to-import Grafana JSON for the cluster's Prometheus series).

Panels target the series the ray_tpu dashboard's /metrics endpoint
exposes: ray_tpu_cluster_resource_total/available{resource=...},
ray_tpu_cluster_nodes_alive, plus any user-defined util.metrics series.
Import via Grafana -> Dashboards -> Import, with a Prometheus data source
scraping the dashboard's /metrics.
"""

from __future__ import annotations

import json
from typing import List, Optional


def _panel(panel_id: int, title: str, exprs: List[dict], y: int,
           unit: str = "short") -> dict:
    return {
        "id": panel_id,
        "title": title,
        "type": "timeseries",
        "datasource": {"type": "prometheus", "uid": "${datasource}"},
        "gridPos": {"h": 8, "w": 12, "x": 12 * (panel_id % 2), "y": y},
        "fieldConfig": {"defaults": {"unit": unit}, "overrides": []},
        "targets": [
            {"expr": t["expr"], "legendFormat": t.get("legend", ""),
             "refId": chr(ord("A") + i)}
            for i, t in enumerate(exprs)
        ],
    }


# annotation overlay: every panel gets vertical firing/resolved marks
# wherever ray_tpu_alerts_firing flips — the Grafana-side mirror of the
# GCS SLO engine's alert.firing/alert.resolved events
_ALERT_ANNOTATIONS = {
    "list": [
        {
            "name": "SLO alerts",
            "datasource": {"type": "prometheus", "uid": "${datasource}"},
            "enable": True,
            "iconColor": "red",
            "expr": "ray_tpu_alerts_firing > 0",
            "titleFormat": "{{rule}} ({{severity}})",
            "useValueForTime": False,
        },
    ]
}


def generate_grafana_dashboard(
        extra_metric_names: Optional[List[str]] = None) -> dict:
    """-> importable Grafana dashboard dict for the core cluster series."""
    panels = [
        _panel(0, "Alive nodes",
               [{"expr": "ray_tpu_cluster_nodes_alive", "legend": "nodes"}],
               y=0),
        _panel(1, "CPU total vs available", [
            {"expr": 'ray_tpu_cluster_resource_total{resource="CPU"}',
             "legend": "total"},
            {"expr": 'ray_tpu_cluster_resource_available{resource="CPU"}',
             "legend": "available"},
        ], y=0),
        _panel(2, "TPU chips total vs available", [
            {"expr": 'ray_tpu_cluster_resource_total{resource="TPU"}',
             "legend": "total"},
            {"expr": 'ray_tpu_cluster_resource_available{resource="TPU"}',
             "legend": "available"},
        ], y=8),
        _panel(3, "Node heap memory resource (bytes)", [
            {"expr": 'ray_tpu_cluster_resource_total{resource="memory"}',
             "legend": "total"},
            {"expr": 'ray_tpu_cluster_resource_available{resource="memory"}',
             "legend": "available"},
        ], y=8, unit="bytes"),
        _panel(4, "Object store (per node)", [
            {"expr": "ray_tpu_object_store_used_bytes",
             "legend": "used {{node_id}}"},
            {"expr": "ray_tpu_object_store_capacity_bytes",
             "legend": "capacity {{node_id}}"},
            {"expr": "ray_tpu_object_store_spilled_bytes",
             "legend": "spilled {{node_id}}"},
        ], y=16, unit="bytes"),
        _panel(5, "Object references (cluster-wide)", [
            {"expr": "ray_tpu_object_refs", "legend": "{{kind}}"},
        ], y=16),
        _panel(6, "Paged-KV blocks", [
            {"expr": "ray_tpu_kv_blocks", "legend": "{{state}}"},
        ], y=24),
        # cluster health plane (ISSUE 20)
        _panel(7, "SLO alerts firing (per rule)", [
            {"expr": "ray_tpu_alerts_firing",
             "legend": "{{rule}} ({{severity}})"},
        ], y=24),
        _panel(8, "Serve requests by outcome (rate)", [
            {"expr": "rate(ray_tpu_serve_requests_total[5m])",
             "legend": "{{outcome}}"},
        ], y=32, unit="reqps"),
        _panel(9, "Serve availability burn rate "
                  "(5m error-frac / 0.1% objective)", [
            {"expr": "(1 - sum(rate(ray_tpu_serve_requests_total"
                     '{outcome="ok"}[5m])) / '
                     "sum(rate(ray_tpu_serve_requests_total[5m]))) "
                     "/ 0.001",
             "legend": "burn (fires >10)"},
        ], y=32),
        _panel(10, "Lifecycle events by type (rate)", [
            {"expr": "rate(ray_tpu_events_by_type_total[5m])",
             "legend": "{{type}}"},
        ], y=40),
        _panel(11, "Metric push health (pushes / drops)", [
            {"expr": "rate(ray_tpu_health_pushes_total[5m])",
             "legend": "pushes {{proc}}"},
            {"expr": "rate(ray_tpu_health_push_dropped_total[5m])",
             "legend": "drops {{proc}}"},
        ], y=40),
    ]
    next_id = 12
    for name in extra_metric_names or []:
        panels.append(_panel(next_id, name, [{"expr": name}],
                             y=48 + 8 * ((next_id - 12) // 2)))
        next_id += 1
    return {
        "title": "ray_tpu cluster",
        "uid": "ray-tpu-cluster",
        "schemaVersion": 36,
        "refresh": "10s",
        "time": {"from": "now-30m", "to": "now"},
        "templating": {"list": [{
            "name": "datasource", "type": "datasource",
            "query": "prometheus",
        }]},
        "annotations": _ALERT_ANNOTATIONS,
        "panels": panels,
    }


def write_grafana_dashboard(path: str,
                            extra_metric_names: Optional[List[str]] = None
                            ) -> str:
    with open(path, "w") as f:
        json.dump(generate_grafana_dashboard(extra_metric_names), f,
                  indent=2)
    return path
