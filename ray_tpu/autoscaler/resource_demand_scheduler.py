"""Bin-packing of unfulfilled resource demand onto node types.

Reference: ray python/ray/autoscaler/_private/resource_demand_scheduler.py —
given pending demand shapes and the config's node types, compute how many of
each type to launch. Strategy here mirrors the reference: first fit demands
onto the simulated free capacity of existing+planned nodes, then pick the
"best" (fewest-resources-that-fit) type for what remains, respecting
max_workers caps.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

Resources = Dict[str, float]


def _fits(avail: Resources, demand: Resources) -> bool:
    return all(avail.get(k, 0.0) >= v for k, v in demand.items() if v > 0)


def _subtract(avail: Resources, demand: Resources) -> None:
    for k, v in demand.items():
        avail[k] = avail.get(k, 0.0) - v


def get_nodes_to_launch(
    node_types: Dict[str, dict],
    existing_available: List[Resources],
    demands: List[Tuple],
    counts_by_type: Dict[str, int],
    existing_labels: Optional[List[dict]] = None,
) -> Dict[str, int]:
    """-> {node_type: count to launch}.

    node_types: {name: {"resources": {...}, "max_workers": int,
                        "labels": {...} (optional)}}
    existing_available: free resources of live nodes (simulated mutable)
    existing_labels: node labels parallel to existing_available (labeled
        demand only packs onto nodes whose labels match)
    demands: [(shape, count)] or [(shape, count, hard_labels)] — pending
        demand aggregated by shape; label-constrained demand only counts
        against matching existing/planned capacity or node types whose
        declared labels match.
    counts_by_type: current node count per type (for max_workers caps)
    """
    from ray_tpu.raylet.scheduling_policy import _labels_match

    sim = [dict(a) for a in existing_available]
    sim_labels: List[dict] = [dict(lbl) for lbl in (existing_labels or [])]
    sim_labels += [{}] * (len(sim) - len(sim_labels))
    planned: Dict[str, int] = {}

    flat: List[Tuple[Resources, Optional[dict]]] = []
    for entry in demands:
        shape, count = entry[0], entry[1]
        labels = entry[2] if len(entry) > 2 else None
        flat.extend([(shape, labels)] * min(count, 1000))
    # Pack big demands first — reduces fragmentation, like the reference's
    # sorted bin-packing.
    flat.sort(key=lambda d: -sum(d[0].values()))

    for demand, labels in flat:
        placed = False
        for i, avail in enumerate(sim):
            if labels and not _labels_match(sim_labels[i], labels):
                continue
            if _fits(avail, demand):
                _subtract(avail, demand)
                placed = True
                break
        if placed:
            continue
        # Choose the feasible type with the least total resources (cheapest
        # that fits), respecting max_workers and label constraints.
        best: Optional[str] = None
        best_size = float("inf")
        for name, cfg in node_types.items():
            res = cfg.get("resources") or {}
            cap = cfg.get("max_workers", 0)
            current = counts_by_type.get(name, 0) + planned.get(name, 0)
            if current >= cap:
                continue
            if labels and not _labels_match(cfg.get("labels") or {}, labels):
                continue
            if _fits(dict(res), demand):
                size = sum(res.values())
                if size < best_size:
                    best, best_size = name, size
        if best is None:
            continue  # infeasible demand: nothing in the config can host it
        planned[best] = planned.get(best, 0) + 1
        avail = dict(node_types[best].get("resources") or {})
        _subtract(avail, demand)
        sim.append(avail)
        sim_labels.append(dict(node_types[best].get("labels") or {}))
    return planned
