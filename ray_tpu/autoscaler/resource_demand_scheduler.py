"""Bin-packing of unfulfilled resource demand onto node types.

Reference: ray python/ray/autoscaler/_private/resource_demand_scheduler.py —
given pending demand shapes and the config's node types, compute how many of
each type to launch. Strategy here mirrors the reference: first fit demands
onto the simulated free capacity of existing+planned nodes, then pick the
"best" (fewest-resources-that-fit) type for what remains, respecting
max_workers caps.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

Resources = Dict[str, float]


def _fits(avail: Resources, demand: Resources) -> bool:
    return all(avail.get(k, 0.0) >= v for k, v in demand.items() if v > 0)


def _subtract(avail: Resources, demand: Resources) -> None:
    for k, v in demand.items():
        avail[k] = avail.get(k, 0.0) - v


def get_nodes_to_launch(
    node_types: Dict[str, dict],
    existing_available: List[Resources],
    demands: List[Tuple[Resources, int]],
    counts_by_type: Dict[str, int],
) -> Dict[str, int]:
    """-> {node_type: count to launch}.

    node_types: {name: {"resources": {...}, "max_workers": int}}
    existing_available: free resources of live nodes (simulated mutable)
    demands: [(shape, count)] pending demand aggregated by shape
    counts_by_type: current node count per type (for max_workers caps)
    """
    sim = [dict(a) for a in existing_available]
    planned: Dict[str, int] = {}

    flat: List[Resources] = []
    for shape, count in demands:
        flat.extend([shape] * min(count, 1000))
    # Pack big demands first — reduces fragmentation, like the reference's
    # sorted bin-packing.
    flat.sort(key=lambda d: -sum(d.values()))

    for demand in flat:
        placed = False
        for avail in sim:
            if _fits(avail, demand):
                _subtract(avail, demand)
                placed = True
                break
        if placed:
            continue
        # Choose the feasible type with the least total resources (cheapest
        # that fits), respecting max_workers.
        best: Optional[str] = None
        best_size = float("inf")
        for name, cfg in node_types.items():
            res = cfg.get("resources") or {}
            cap = cfg.get("max_workers", 0)
            current = counts_by_type.get(name, 0) + planned.get(name, 0)
            if current >= cap:
                continue
            if _fits(dict(res), demand):
                size = sum(res.values())
                if size < best_size:
                    best, best_size = name, size
        if best is None:
            continue  # infeasible demand: nothing in the config can host it
        planned[best] = planned.get(best, 0) + 1
        avail = dict(node_types[best].get("resources") or {})
        _subtract(avail, demand)
        sim.append(avail)
    return planned
