"""GCE TPU-VM node provider: creates/deletes real Cloud TPU slices.

Reference: ray python/ray/autoscaler/_private/gcp/node_provider.py:63
(GCPNodeProvider) and its TPU resource class (gcp/node.py) — here rebuilt
TPU-first: the provider's unit is a SLICE, not a VM. One provider node =
one Cloud TPU "node" resource (tpu.googleapis.com/v2), which for a
multi-host accelerator type (e.g. v5litepod-16) materializes a GANG of
host VMs sharing ICI. Topology therefore lives in the node type's config:

    node_types:
      v5e-16:
        node_config:
          acceleratorType: v5litepod-16
          runtimeVersion: tpu-ubuntu2204-base
        # resources the WHOLE slice gang contributes, pre-declared so the
        # bin-packer can match TPU/PG gang demand before the slice exists
        resources: {"TPU": 16.0, "TPU-v5litepod-16-head": 1.0}
        max_workers: 4

Scale-up = POST nodes (a long-running operation; the slice shows CREATING
until every host is provisioned), scale-down = DELETE of the whole slice —
there is no partial-slice scaling, matching how ICI topology works.

The REST transport is a tiny urllib wrapper authenticated from the GCE
metadata server; tests inject a fake with the same request() surface
(tests/test_gce_tpu_provider.py), mirroring the GKE provider's fake-K8s
pattern.
"""

from __future__ import annotations

import json
import logging
import re
import time
import uuid
from typing import Dict, List, Optional

from ray_tpu.autoscaler.node_provider import (
    STATUS_SETTING_UP,
    STATUS_UP,
    TAG_NODE_STATUS,
    TAG_NODE_TYPE,
    NodeProvider,
)

logger = logging.getLogger(__name__)

TPU_API = "https://tpu.googleapis.com/v2"
METADATA_TOKEN_URL = ("http://metadata.google.internal/computeMetadata/v1/"
                      "instance/service-accounts/default/token")

# GCE label keys/values: lowercase letters, digits, -, _; 63 chars max.
CLUSTER_LABEL = "ray-cluster-name"
TYPE_LABEL = "ray-node-type"

_READY_STATES = {"READY"}
_PENDING_STATES = {"CREATING", "STARTING", "RESTARTING", "REPAIRING"}
_GONE_STATES = {"DELETING", "TERMINATED", "STOPPED", "STOPPING", "PREEMPTED"}


def _gce_label(value: str) -> str:
    return re.sub(r"[^a-z0-9_-]", "-", value.lower())[:63]


class GceTpuApi:
    """Minimal Cloud TPU v2 REST client (metadata-server auth)."""

    def __init__(self, project: str, zone: str,
                 token: Optional[str] = None):
        self.base = f"/projects/{project}/locations/{zone}"
        self._token = token
        self._token_expiry = 0.0

    def _auth(self) -> str:
        import urllib.request

        if self._token and time.time() < self._token_expiry:
            return self._token
        req = urllib.request.Request(
            METADATA_TOKEN_URL, headers={"Metadata-Flavor": "Google"})
        with urllib.request.urlopen(req, timeout=10) as resp:
            payload = json.loads(resp.read().decode())
        self._token = payload["access_token"]
        self._token_expiry = time.time() + payload.get("expires_in", 300) - 60
        return self._token

    def request(self, method: str, path: str,
                body: Optional[dict] = None) -> dict:
        import urllib.request

        req = urllib.request.Request(
            TPU_API + path,
            data=json.dumps(body).encode() if body is not None else None,
            method=method,
            headers={
                "Authorization": f"Bearer {self._auth()}",
                "Content-Type": "application/json",
            })
        with urllib.request.urlopen(req, timeout=60) as resp:
            return json.loads(resp.read().decode() or "{}")


class GceTpuNodeProvider(NodeProvider):
    """provider_config: {"project": str, "zone": str}; optional "api" for
    tests. Node ids are the short TPU node names."""

    def __init__(self, provider_config: dict, cluster_name: str,
                 api: Optional[GceTpuApi] = None):
        super().__init__(provider_config, cluster_name)
        self.project = provider_config.get("project", "")
        self.zone = provider_config.get("zone", "")
        self.api = api or GceTpuApi(self.project, self.zone)
        self._nodes: Dict[str, dict] = {}  # name -> TPU node resource

    # -- helpers -------------------------------------------------------------

    def _refresh(self) -> None:
        reply = self.api.request("GET", f"{self.api.base}/nodes")
        out: Dict[str, dict] = {}
        for node in reply.get("nodes", []):
            labels = node.get("labels", {})
            if labels.get(CLUSTER_LABEL) != _gce_label(self.cluster_name):
                continue
            if node.get("state") in _GONE_STATES:
                continue
            name = node.get("name", "").rsplit("/", 1)[-1]
            out[name] = node
        self._nodes = out

    # -- NodeProvider API ----------------------------------------------------

    def non_terminated_nodes(self, tag_filters: Optional[dict] = None
                             ) -> List[str]:
        """READY slices only. Provisioning slices are reported through
        pending_nodes() instead — the autoscaler sums both as supply, so
        listing a CREATING slice in both would double-count it."""
        self._refresh()
        out = []
        for name, node in self._nodes.items():
            if node.get("state") in _PENDING_STATES:
                continue
            tags = self.node_tags(name)
            if tag_filters and any(tags.get(k) != v
                                   for k, v in tag_filters.items()):
                continue
            out.append(name)
        return sorted(out)

    def pending_nodes(self) -> Dict[str, int]:
        """Per-type counts of slices still provisioning (CREATING can
        take minutes for a multi-host gang; the autoscaler counts these
        as supply so it doesn't re-launch meanwhile)."""
        out: Dict[str, int] = {}
        for node in self._nodes.values():
            if node.get("state") in _PENDING_STATES:
                t = node.get("labels", {}).get(TYPE_LABEL, "")
                out[t] = out.get(t, 0) + 1
        return out

    def node_tags(self, node_id: str) -> dict:
        node = self._nodes.get(node_id, {})
        labels = node.get("labels", {})
        status = (STATUS_UP if node.get("state") in _READY_STATES
                  else STATUS_SETTING_UP)
        return {
            TAG_NODE_TYPE: labels.get(TYPE_LABEL, ""),
            TAG_NODE_STATUS: status,
        }

    def create_node(self, node_config: dict, tags: dict, count: int) -> None:
        node_type = tags.get(TAG_NODE_TYPE, "worker")
        for _ in range(count):
            # truncate the PREFIX, never the unique suffix: a 63-char cap
            # applied after the uuid would make long cluster/type names
            # collide on every create
            prefix = _gce_label(f"{self.cluster_name}-{node_type}")[:54]
            name = f"{prefix}-{uuid.uuid4().hex[:8]}"
            body = {
                "acceleratorType": node_config.get(
                    "acceleratorType", "v5litepod-8"),
                "runtimeVersion": node_config.get(
                    "runtimeVersion", "tpu-ubuntu2204-base"),
                "labels": {
                    CLUSTER_LABEL: _gce_label(self.cluster_name),
                    TYPE_LABEL: _gce_label(node_type),
                },
            }
            for key in ("networkConfig", "schedulingConfig", "metadata",
                        "serviceAccount", "tags", "dataDisks"):
                if key in node_config:
                    body[key] = node_config[key]
            logger.info("creating TPU slice %s (%s)", name,
                        body["acceleratorType"])
            self.api.request(
                "POST", f"{self.api.base}/nodes?nodeId={name}", body)

    def terminate_node(self, node_id: str) -> None:
        logger.info("deleting TPU slice %s", node_id)
        try:
            self.api.request(
                "DELETE", f"{self.api.base}/nodes/{node_id}")
        except Exception:  # noqa: BLE001 — already gone is fine
            logger.warning("delete of TPU slice %s failed", node_id,
                           exc_info=True)
        self._nodes.pop(node_id, None)

    def internal_ip(self, node_id: str) -> str:
        node = self._nodes.get(node_id, {})
        endpoints = node.get("networkEndpoints", [])
        return endpoints[0].get("ipAddress", "") if endpoints else ""

    def worker_ips(self, node_id: str) -> List[str]:
        """All host VMs of the slice gang (multi-host slices have one
        endpoint per worker; the cluster launcher starts a raylet on
        each)."""
        node = self._nodes.get(node_id, {})
        return [e.get("ipAddress", "")
                for e in node.get("networkEndpoints", [])]
