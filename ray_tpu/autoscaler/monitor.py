"""Monitor: the autoscaler's driver loop.

Reference: ray python/ray/autoscaler/_private/monitor.py:126 — a process on
the head node that periodically runs StandardAutoscaler.update. Here it is a
daemon thread owned by AutoscalingCluster / `ray-tpu start --head`.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from ray_tpu._private.rpc import EventLoopThread, RpcClient
from ray_tpu.autoscaler.autoscaler import StandardAutoscaler
from ray_tpu.autoscaler.node_provider import NodeProvider

logger = logging.getLogger(__name__)


class Monitor:
    def __init__(self, gcs_address: str, provider: NodeProvider, config: dict,
                 update_interval_s: float = 1.0):
        self._lt = EventLoopThread("autoscaler-monitor")
        self._gcs = RpcClient(gcs_address, self._lt)
        self.autoscaler = StandardAutoscaler(config, provider, self._gcs)
        self._interval = update_interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(
            target=self._run, name="autoscaler", daemon=True)
        self._thread.start()

    def _run(self):
        while not self._stop.wait(self._interval):
            try:
                self.autoscaler.update()
            except Exception:  # noqa: BLE001 — keep reconciling
                logger.exception("autoscaler update failed")

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._gcs.close()
        self._lt.stop()
