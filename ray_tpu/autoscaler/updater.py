"""NodeUpdater: bring one node from bare machine to running ray-tpu.

Reference: ray python/ray/autoscaler/_private/updater.py (NodeUpdater.run —
wait for SSH, sync file mounts, initialization_commands, setup_commands,
start_ray_commands) compressed to the parts that matter without docker.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional

from ray_tpu.autoscaler.command_runner import CommandRunnerInterface

logger = logging.getLogger(__name__)


class NodeUpdaterError(RuntimeError):
    pass


class NodeUpdater:
    def __init__(
        self,
        node_ip: str,
        runner: CommandRunnerInterface,
        file_mounts: Optional[Dict[str, str]] = None,
        initialization_commands: Optional[List[str]] = None,
        setup_commands: Optional[List[str]] = None,
        start_commands: Optional[List[str]] = None,
        env: Optional[Dict[str, str]] = None,
        ssh_wait_timeout: float = 120.0,
    ):
        self.node_ip = node_ip
        self.runner = runner
        self.file_mounts = file_mounts or {}
        self.initialization_commands = initialization_commands or []
        self.setup_commands = setup_commands or []
        self.start_commands = start_commands or []
        self.env = env or {}
        self.ssh_wait_timeout = ssh_wait_timeout

    def wait_ready(self) -> None:
        deadline = time.monotonic() + self.ssh_wait_timeout
        delay = 1.0
        last = ""
        while time.monotonic() < deadline:
            try:
                r = self.runner.run("uptime", timeout=15)
                if r.returncode == 0:
                    return
                last = r.stderr
            except Exception as e:  # noqa: BLE001 — ssh not up yet
                last = str(e)
            time.sleep(delay)
            delay = min(5.0, delay * 1.5)
        raise NodeUpdaterError(
            f"node {self.node_ip} never became reachable: {last}")

    def sync_file_mounts(self) -> None:
        for remote, local in self.file_mounts.items():
            self.runner.run(f"mkdir -p {remote}")
            # trailing slash: sync directory CONTENTS into the mount point
            src = local.rstrip("/") + "/"
            self.runner.run_rsync_up(src, remote.rstrip("/") + "/")

    def run_commands(self, commands: List[str], phase: str) -> None:
        for cmd in commands:
            r = self.runner.run(cmd, env=self.env, timeout=600)
            if r.returncode != 0:
                raise NodeUpdaterError(
                    f"{phase} command failed on {self.node_ip} "
                    f"(exit {r.returncode}): {cmd}\n"
                    f"stdout: {r.stdout}\nstderr: {r.stderr}")

    def update(self) -> None:
        logger.info("updating node %s", self.node_ip)
        self.wait_ready()
        self.run_commands(self.initialization_commands, "initialization")
        self.sync_file_mounts()
        self.run_commands(self.setup_commands, "setup")
        self.run_commands(self.start_commands, "start")
        logger.info("node %s up", self.node_ip)
