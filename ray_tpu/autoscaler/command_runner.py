"""Command runners: how the cluster launcher reaches a node.

Reference: ray python/ray/autoscaler/_private/command_runner.py (SSH options,
rsync invocation, the CommandRunnerInterface contract in
autoscaler/command_runner.py:9). SSH is subprocess `ssh`/`rsync` — no
paramiko-style dependency — so a fake `ssh` on PATH substitutes cleanly in
tests (and rsync rides the same transport via `-e`).
"""

from __future__ import annotations

import logging
import os
import subprocess
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)

_SSH_OPTS = [
    "-o", "ConnectTimeout=10s",
    "-o", "StrictHostKeyChecking=no",
    "-o", "UserKnownHostsFile=/dev/null",
    "-o", "LogLevel=ERROR",
    # control-master connection reuse: one TCP+auth handshake per node,
    # every later command multiplexes (the reference does the same,
    # command_runner.py:110)
    "-o", "ControlMaster=auto",
    "-o", "ControlPersist=60s",
]


class CommandRunnerInterface:
    def run(self, cmd: str, *, env: Optional[Dict[str, str]] = None,
            timeout: Optional[float] = None,
            capture: bool = True) -> subprocess.CompletedProcess:
        raise NotImplementedError

    def run_rsync_up(self, source: str, target: str) -> None:
        raise NotImplementedError

    def run_rsync_down(self, source: str, target: str) -> None:
        raise NotImplementedError

    def remote_shell_argv(self) -> List[str]:
        """argv for an INTERACTIVE shell on the node (`attach`)."""
        raise NotImplementedError


def _export_prefix(env: Optional[Dict[str, str]]) -> str:
    if not env:
        return ""
    import shlex

    return "".join(f"export {k}={shlex.quote(str(v))}; "
                   for k, v in env.items())


class SSHCommandRunner(CommandRunnerInterface):
    def __init__(self, node_ip: str, auth: dict,
                 ssh_binary: str = "ssh", rsync_binary: str = "rsync"):
        self.node_ip = node_ip
        self.ssh_user = auth.get("ssh_user") or os.environ.get("USER", "root")
        self.ssh_key = auth.get("ssh_private_key")
        self.ssh_port = auth.get("ssh_port")
        self.ssh_binary = ssh_binary
        self.rsync_binary = rsync_binary

    def _ssh_base(self) -> List[str]:
        cmd = [self.ssh_binary] + _SSH_OPTS
        if self.ssh_key:
            cmd += ["-i", os.path.expanduser(self.ssh_key)]
        if self.ssh_port:
            cmd += ["-p", str(self.ssh_port)]
        return cmd

    def _target(self) -> str:
        return f"{self.ssh_user}@{self.node_ip}"

    def run(self, cmd: str, *, env=None, timeout=None, capture=True):
        full = self._ssh_base() + [self._target(),
                                   f"bash -c {_sq(_export_prefix(env) + cmd)}"]
        logger.debug("ssh %s: %s", self.node_ip, cmd)
        return subprocess.run(
            full, capture_output=capture, text=True, timeout=timeout)

    def run_rsync_up(self, source: str, target: str) -> None:
        source = os.path.expanduser(source)
        if self._have_rsync():
            self._rsync(source, f"{self._target()}:{target}")
            return
        # tar-over-ssh fallback (this image ships no rsync): stream a
        # gzipped tar through the same transport
        if os.path.isdir(source):
            data = _tar_dir_bytes(source)
            self._run_with_input(
                f"mkdir -p {_sq(target)} && tar -C {_sq(target)} -xzf -",
                data)
        else:
            with open(source, "rb") as f:
                data = f.read()
            self._run_with_input(
                f"mkdir -p $(dirname {_sq(target)}) && cat > {_sq(target)}",
                data)

    def run_rsync_down(self, source: str, target: str) -> None:
        target = os.path.expanduser(target)
        if self._have_rsync():
            self._rsync(f"{self._target()}:{source}", target)
            return
        probe = self.run(f"test -d {_sq(source)}")
        if probe.returncode == 0:
            data = self._run_capture_bytes(
                f"tar -C {_sq(source)} -czf - .")
            _untar_bytes(data, target)
        else:
            data = self._run_capture_bytes(f"cat {_sq(source)}")
            os.makedirs(os.path.dirname(target) or ".", exist_ok=True)
            with open(target, "wb") as f:
                f.write(data)

    def _have_rsync(self) -> bool:
        import shutil

        return shutil.which(self.rsync_binary) is not None

    def _run_with_input(self, cmd: str, data: bytes) -> None:
        full = self._ssh_base() + [self._target(), f"bash -c {_sq(cmd)}"]
        r = subprocess.run(full, input=data, capture_output=True)
        if r.returncode != 0:
            raise RuntimeError(
                f"transfer failed ({r.returncode}): {r.stderr.decode()}")

    def _run_capture_bytes(self, cmd: str) -> bytes:
        full = self._ssh_base() + [self._target(), f"bash -c {_sq(cmd)}"]
        r = subprocess.run(full, capture_output=True)
        if r.returncode != 0:
            raise RuntimeError(
                f"transfer failed ({r.returncode}): {r.stderr.decode()}")
        return r.stdout

    def _rsync(self, src: str, dst: str) -> None:
        ssh_cmd = " ".join(self._ssh_base())
        cmd = [self.rsync_binary, "-az", "--delete", "-e", ssh_cmd, src, dst]
        r = subprocess.run(cmd, capture_output=True, text=True)
        if r.returncode != 0:
            raise RuntimeError(f"rsync failed ({r.returncode}): {r.stderr}")

    def remote_shell_argv(self) -> List[str]:
        return self._ssh_base() + ["-tt", self._target()]


class LocalCommandRunner(CommandRunnerInterface):
    """Runs "node" commands as local subprocesses (provider head_ip on this
    machine, or single-box clusters — no SSH round trip)."""

    def __init__(self, node_ip: str = "127.0.0.1"):
        self.node_ip = node_ip

    def run(self, cmd: str, *, env=None, timeout=None, capture=True):
        full_env = dict(os.environ)
        full_env.update({k: str(v) for k, v in (env or {}).items()})
        return subprocess.run(
            ["bash", "-c", cmd], capture_output=capture, text=True,
            timeout=timeout, env=full_env)

    def run_rsync_up(self, source: str, target: str) -> None:
        self._copy(source, target)

    def run_rsync_down(self, source: str, target: str) -> None:
        self._copy(source, target)

    @staticmethod
    def _copy(src: str, dst: str) -> None:
        import shutil

        src = os.path.expanduser(src)
        dst = os.path.expanduser(dst)
        if os.path.isdir(src.rstrip("/")):
            shutil.copytree(src.rstrip("/"), dst.rstrip("/"),
                            dirs_exist_ok=True)
        else:
            os.makedirs(os.path.dirname(dst) or ".", exist_ok=True)
            shutil.copy2(src, dst)

    def remote_shell_argv(self) -> List[str]:
        return ["bash", "-i"]


def _sq(s: str) -> str:
    import shlex

    return shlex.quote(s)


def _tar_dir_bytes(src_dir: str) -> bytes:
    import io
    import tarfile

    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as tf:
        for name in sorted(os.listdir(src_dir)):
            tf.add(os.path.join(src_dir, name), arcname=name)
    return buf.getvalue()


def _untar_bytes(data: bytes, dst_dir: str) -> None:
    import io
    import tarfile

    os.makedirs(dst_dir, exist_ok=True)
    with tarfile.open(fileobj=io.BytesIO(data), mode="r:gz") as tf:
        tf.extractall(dst_dir, filter="data")


def make_command_runner(node_ip: str, config: dict) -> CommandRunnerInterface:
    """Pick the runner for a node from the cluster config. `ssh_binary`
    override (provider.ssh_binary or RT_SSH_BINARY) lets tests route
    "ssh" through a local stub."""
    provider = config.get("provider", {})
    if provider.get("type") == "subprocess" or node_ip in (
            "127.0.0.1", "localhost"):
        return LocalCommandRunner(node_ip)
    ssh_binary = (os.environ.get("RT_SSH_BINARY")
                  or provider.get("ssh_binary") or "ssh")
    rsync_binary = (os.environ.get("RT_RSYNC_BINARY")
                    or provider.get("rsync_binary") or "rsync")
    return SSHCommandRunner(node_ip, config.get("auth", {}),
                            ssh_binary=ssh_binary, rsync_binary=rsync_binary)
