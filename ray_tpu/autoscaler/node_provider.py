"""NodeProvider plugin interface + the in-process fake provider.

Reference: ray python/ray/autoscaler/node_provider.py:13 (NodeProvider
abstract API: create_node/terminate_node/non_terminated_nodes/node_tags) and
the fake multi-node provider used to test autoscaling without a cloud
(_private/fake_multi_node/node_provider.py:237).

LocalNodeProvider starts REAL in-process raylets (same machinery as
cluster_utils.Cluster), so autoscaler tests exercise true node
registration/heartbeat/scheduling paths.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

TAG_NODE_TYPE = "node-type"
TAG_NODE_STATUS = "node-status"
STATUS_UP = "up-to-date"
STATUS_SETTING_UP = "setting-up"


class NodeProvider:
    """Cloud abstraction. Implementations: LocalNodeProvider (in-process,
    tests), and deploy-specific providers (GKE TPU pods) configured by the
    cluster YAML."""

    def __init__(self, provider_config: dict, cluster_name: str):
        self.provider_config = provider_config
        self.cluster_name = cluster_name

    def non_terminated_nodes(self, tag_filters: Optional[dict] = None) -> List[str]:
        raise NotImplementedError

    def node_tags(self, node_id: str) -> dict:
        raise NotImplementedError

    def create_node(self, node_config: dict, tags: dict, count: int) -> None:
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError

    def is_terminated(self, node_id: str) -> bool:
        return node_id not in self.non_terminated_nodes()

    def internal_ip(self, node_id: str) -> str:
        return node_id

    def shutdown(self) -> None:
        pass


class LocalNodeProvider(NodeProvider):
    """Fake multi-node provider: each "cloud node" is an in-process Raylet
    registered with the shared GCS. `raylet_node_id(pid)` maps a provider
    node to its GCS NodeID so the autoscaler can join provider state with
    cluster load."""

    def __init__(self, gcs_address: str, provider_config: Optional[dict] = None,
                 cluster_name: str = "local"):
        super().__init__(provider_config or {}, cluster_name)
        self.gcs_address = gcs_address
        self._lock = threading.Lock()
        self._next_id = 0
        self._nodes: Dict[str, dict] = {}  # provider id -> {raylet, tags}

    def non_terminated_nodes(self, tag_filters: Optional[dict] = None) -> List[str]:
        with self._lock:
            out = []
            for nid, rec in self._nodes.items():
                tags = rec["tags"]
                if all(tags.get(k) == v for k, v in (tag_filters or {}).items()):
                    out.append(nid)
            return out

    def node_tags(self, node_id: str) -> dict:
        with self._lock:
            rec = self._nodes.get(node_id)
            return dict(rec["tags"]) if rec else {}

    def create_node(self, node_config: dict, tags: dict, count: int) -> None:
        from ray_tpu.raylet.raylet import Raylet

        for _ in range(count):
            with self._lock:
                pid = f"fake-{self._next_id}"
                self._next_id += 1
            raylet = Raylet(
                gcs_address=self.gcs_address,
                resources=dict(node_config.get("resources") or {}),
                labels=dict(node_config.get("labels") or {}),
            )
            raylet.start(0)
            with self._lock:
                self._nodes[pid] = {
                    "raylet": raylet,
                    "tags": {**tags, TAG_NODE_STATUS: STATUS_UP},
                    "created": time.time(),
                }

    def terminate_node(self, node_id: str) -> None:
        with self._lock:
            rec = self._nodes.pop(node_id, None)
        if rec is not None:
            rec["raylet"].stop()

    def raylet_node_id(self, node_id: str) -> Optional[str]:
        with self._lock:
            rec = self._nodes.get(node_id)
            return rec["raylet"].node_id.hex() if rec else None

    def shutdown(self) -> None:
        with self._lock:
            nodes = list(self._nodes.values())
            self._nodes.clear()
        for rec in nodes:
            rec["raylet"].stop()
