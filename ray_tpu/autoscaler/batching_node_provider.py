"""Declarative batching NodeProvider base.

Reference behavior: ray python/ray/autoscaler/batching_node_provider.py:1 —
imperative create/terminate calls from the autoscaler collect into ONE
scale request per reconcile cycle, submitted as a declarative patch (the
kuberay pattern: set each worker group's replica count + the precise pods
to delete, let the operator converge). This suits cloud APIs where node
lifecycle is owned by a controller rather than by individual VM calls —
GKE TPU slices especially, where a multi-host slice scales as one unit.

Subclasses implement two methods:
- get_node_data() -> {node_id: NodeData}: current cloud view.
- submit_scale_request(req): apply the desired counts + deletions.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Dict, List, Optional, Set

from ray_tpu.autoscaler.node_provider import (
    STATUS_UP,
    TAG_NODE_STATUS,
    TAG_NODE_TYPE,
    NodeProvider,
)

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class NodeData:
    node_type: str
    status: str = STATUS_UP
    ip: str = ""


@dataclasses.dataclass
class ScaleRequest:
    desired: Dict[str, int] = dataclasses.field(default_factory=dict)
    workers_to_delete: Set[str] = dataclasses.field(default_factory=set)


class BatchingNodeProvider(NodeProvider):
    def __init__(self, provider_config: dict, cluster_name: str):
        super().__init__(provider_config, cluster_name)
        self._node_data: Dict[str, NodeData] = {}
        self._scale: ScaleRequest = ScaleRequest()
        self._dirty = False
        # last SUBMITTED desired counts: the declarative intent the cloud
        # controller is still converging toward. Fresh scale requests start
        # from this, not from observed pods — otherwise a scan between
        # submit and pod creation would read 0 observed and the next flush
        # would cancel the in-flight scale-up (TPU slices provision in
        # minutes; the reconcile period is seconds).
        self._submitted_desired: Optional[Dict[str, int]] = None

    # -- abstract ------------------------------------------------------------

    def get_node_data(self) -> Dict[str, NodeData]:
        raise NotImplementedError

    def submit_scale_request(self, req: ScaleRequest) -> None:
        raise NotImplementedError

    # -- NodeProvider API ----------------------------------------------------

    def non_terminated_nodes(self, tag_filters: Optional[dict] = None
                             ) -> List[str]:
        # Submit the previous cycle's accumulated request as one batch,
        # then refresh the view (reference: flush-on-next-scan semantics).
        if self._dirty:
            logger.info("submitting scale request: desired=%s delete=%s",
                        self._scale.desired,
                        sorted(self._scale.workers_to_delete))
            self.submit_scale_request(self._scale)
            self._submitted_desired = dict(self._scale.desired)
            self._dirty = False
        self._node_data = self.get_node_data()
        base = (dict(self._submitted_desired)
                if self._submitted_desired is not None
                else self._count_types())
        # deletions already converged drop out of the carry-over set
        pending_delete = {
            nid for nid in self._scale.workers_to_delete
            if nid in self._node_data}
        self._scale = ScaleRequest(desired=base,
                                   workers_to_delete=pending_delete)
        out = []
        for nid, data in self._node_data.items():
            tags = {TAG_NODE_TYPE: data.node_type,
                    TAG_NODE_STATUS: data.status}
            if all(tags.get(k) == v
                   for k, v in (tag_filters or {}).items()):
                out.append(nid)
        return out

    def _count_types(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for data in self._node_data.values():
            counts[data.node_type] = counts.get(data.node_type, 0) + 1
        return counts

    def node_tags(self, node_id: str) -> dict:
        data = self._node_data.get(node_id)
        if data is None:
            return {}
        return {TAG_NODE_TYPE: data.node_type,
                TAG_NODE_STATUS: data.status}

    def internal_ip(self, node_id: str) -> str:
        data = self._node_data.get(node_id)
        return data.ip if data else node_id

    def create_node(self, node_config: dict, tags: dict, count: int) -> None:
        node_type = tags.get(TAG_NODE_TYPE, "")
        self._scale.desired[node_type] = (
            self._scale.desired.get(node_type, 0) + count)
        self._dirty = True

    def terminate_node(self, node_id: str) -> None:
        data = self._node_data.get(node_id)
        if data is None:
            return
        self._scale.desired[data.node_type] = max(
            0, self._scale.desired.get(data.node_type, 0) - 1)
        self._scale.workers_to_delete.add(node_id)
        self._dirty = True

    def pending_nodes(self) -> Dict[str, int]:
        """Nodes requested but not yet observed (cloud still provisioning)
        — the autoscaler counts these as upcoming supply so a slow TPU
        slice isn't re-launched every cycle while it boots."""
        observed = self._count_types()
        out: Dict[str, int] = {}
        for t, want in self._scale.desired.items():
            pending = want - observed.get(t, 0)
            if pending > 0:
                out[t] = pending
        return out

    def flush(self) -> None:
        """Force-submit any pending request (shutdown path)."""
        if self._dirty:
            self.submit_scale_request(self._scale)
            self._dirty = False
