"""StandardAutoscaler: one reconciler step per update().

Reference: ray python/ray/autoscaler/_private/autoscaler.py
(StandardAutoscaler.update :172/:374): read load -> enforce min/max ->
launch for unfulfilled demand -> terminate idle nodes. The v2 redesign
(v2/instance_manager/reconciler.py:53) folds this into a single
state-diffing step, which is the shape used here.

TPU gang semantics: a node type whose resources include "TPU" is a slice;
idle-termination requires the WHOLE node idle (available == total), never
partial — and pending PG bundles (gang demand) count as demand so a
STRICT_SPREAD gang triggers multi-node scale-up at once.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, Optional

from ray_tpu.autoscaler.node_provider import (
    STATUS_UP,
    TAG_NODE_STATUS,
    TAG_NODE_TYPE,
    NodeProvider,
)
from ray_tpu.autoscaler.resource_demand_scheduler import get_nodes_to_launch

logger = logging.getLogger(__name__)


class StandardAutoscaler:
    def __init__(self, config: dict, provider: NodeProvider, gcs_client,
                 idle_timeout_s: Optional[float] = None):
        """config: {"max_workers": int, "idle_timeout_s": float,
        "node_types": {name: {"resources": {...}, "min_workers": int,
        "max_workers": int}}}"""
        self.config = config
        self.provider = provider
        self.gcs = gcs_client
        self.idle_timeout_s = (
            idle_timeout_s if idle_timeout_s is not None
            else config.get("idle_timeout_s", 60.0))
        self._idle_since: Dict[str, float] = {}  # provider node id -> ts

    # -- helpers -------------------------------------------------------------

    def _counts_by_type(self, alive_ids) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for nid in alive_ids:
            t = self.provider.node_tags(nid).get(TAG_NODE_TYPE, "")
            counts[t] = counts.get(t, 0) + 1
        return counts

    def _launch(self, node_type: str, count: int):
        cfg = self.config["node_types"][node_type]
        logger.info("autoscaler launching %d x %s", count, node_type)
        # the type's cloud node_config (machine/accelerator shape) rides
        # along with the scheduling metadata; cloud providers read it,
        # the local provider reads resources/labels
        node_config = dict(cfg.get("node_config") or {})
        node_config.setdefault("resources", cfg.get("resources") or {})
        node_config.setdefault("labels", cfg.get("labels") or {})
        self.provider.create_node(
            node_config,
            {TAG_NODE_TYPE: node_type, TAG_NODE_STATUS: STATUS_UP},
            count,
        )

    # -- the reconciler step -------------------------------------------------

    def update(self) -> None:
        load = self.gcs.call("get_cluster_load", {})
        nodes = load["nodes"]
        # demand entries: (shape, count, hard_labels_or_None), normalized at
        # the GCS boundary — labeled demand only counts against nodes/types
        # with matching labels
        demands = [(dict(s), c, lbl) for s, c, lbl in load.get("demands", [])]
        for bundle in load.get("pending_pg_bundles", []):
            demands.append((dict(bundle), 1, None))

        # ONE provider scan per reconcile cycle (batching providers flush
        # their previous cycle's request on scan — a second scan mid-cycle
        # would submit half-built intent)
        alive_ids = self.provider.non_terminated_nodes()
        counts = self._counts_by_type(alive_ids)
        # in-flight launches (declarative providers): count as supply so a
        # slice that takes minutes to boot isn't re-launched every cycle
        pending_fn = getattr(self.provider, "pending_nodes", None)
        pending: Dict[str, int] = pending_fn() if pending_fn else {}
        pending_avail = []
        pending_avail_labels = []
        for t, num in pending.items():
            counts[t] = counts.get(t, 0) + num
            cfg = self.config.get("node_types", {}).get(t, {})
            res = cfg.get("resources") or {}
            pending_avail.extend(dict(res) for _ in range(num))
            pending_avail_labels.extend(
                dict(cfg.get("labels") or {}) for _ in range(num))

        # 1. min_workers floor per type.
        for name, cfg in self.config.get("node_types", {}).items():
            deficit = cfg.get("min_workers", 0) - counts.get(name, 0)
            if deficit > 0:
                self._launch(name, deficit)
                counts[name] = counts.get(name, 0) + deficit

        # 2. demand-driven scale-up (bin-packing over free capacity,
        #    including the capacity of nodes still provisioning; labeled
        #    demand packs only onto label-matching nodes).
        if demands:
            live = [n for n in nodes.values() if n["alive"]]
            avail = [dict(n["available"]) for n in live]
            avail_labels = [dict(n.get("labels") or {}) for n in live]
            avail.extend(pending_avail)
            avail_labels.extend(pending_avail_labels)
            to_launch = get_nodes_to_launch(
                self.config.get("node_types", {}), avail, demands, counts,
                existing_labels=avail_labels)
            total_cap = self.config.get("max_workers", 2**31)
            total_now = sum(counts.values())
            for name, count in to_launch.items():
                count = min(count, max(0, total_cap - total_now))
                if count > 0:
                    self._launch(name, count)
                    counts[name] = counts.get(name, 0) + count
                    total_now += count

        # 3. idle-node termination (whole-node idle only; respects
        #    min_workers; never touches the head node — provider nodes only).
        now = time.monotonic()
        by_gcs_id = {}
        raylet_id = getattr(self.provider, "raylet_node_id", None)
        # cloud providers can't map pods to GCS nodes directly; raylets on
        # k8s advertise their pod name as a node label (ray.io/pod-name),
        # TPU-VM raylets their slice name (ray.io/tpu-slice-name, set by
        # the TPU accelerator detector from the metadata server), and
        # custom providers may set provider-node-id — all join here
        by_pod_label = {}
        for gid, info in nodes.items():
            labels = info.get("labels", {})
            for key in ("ray.io/pod-name", "ray.io/tpu-slice-name",
                        "provider-node-id"):
                if labels.get(key):
                    by_pod_label[labels[key]] = gid
        for pid in alive_ids:
            gid = raylet_id(pid) if raylet_id else None
            if gid is None:
                gid = by_pod_label.get(pid)
            if gid is not None:
                by_gcs_id[pid] = gid
        for pid in alive_ids:
            gid = by_gcs_id.get(pid)
            info = nodes.get(gid) if gid else None
            if info is None or not info["alive"]:
                continue
            idle = (info["available"] == info["total"]) and not demands
            if not idle:
                self._idle_since.pop(pid, None)
                continue
            start = self._idle_since.setdefault(pid, now)
            if now - start < self.idle_timeout_s:
                continue
            t = self.provider.node_tags(pid).get(TAG_NODE_TYPE, "")
            cfg = self.config.get("node_types", {}).get(t, {})
            if counts.get(t, 0) <= cfg.get("min_workers", 0):
                continue
            logger.info("autoscaler terminating idle node %s (%s)", pid, t)
            self.provider.terminate_node(pid)
            counts[t] = counts.get(t, 0) - 1
            self._idle_since.pop(pid, None)
