"""Programmatic autoscaler API.

Reference: ray.autoscaler.sdk.request_resources
(python/ray/autoscaler/sdk/sdk.py) — ask the cluster to scale to fit a
set of resource bundles immediately, without queueing tasks that need
them. Each call REPLACES the previous request; an empty call cancels it.
The request is standing demand: matching nodes are launched (and kept —
requested capacity never idle-terminates) until overridden.
"""

from __future__ import annotations

from typing import Dict, List, Optional


def request_resources(num_cpus: Optional[int] = None,
                      bundles: Optional[List[Dict[str, float]]] = None
                      ) -> int:
    """Request the cluster scale to fit `num_cpus` CPUs and/or the given
    resource bundles (e.g. ``[{"TPU": 4.0}] * 2``). Returns the number of
    standing demand shapes now registered."""
    from ray_tpu._raylet import get_core_worker

    shapes: List[Dict[str, float]] = []
    if num_cpus:
        shapes.append({"CPU": float(num_cpus)})
    for b in bundles or []:
        if b:
            shapes.append({k: float(v) for k, v in b.items()})
    cw = get_core_worker()
    return cw._gcs.call("request_resources", {"shapes": shapes}, timeout=30)
