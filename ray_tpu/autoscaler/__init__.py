"""Autoscaler: demand-driven cluster scaling.

Reference: ray python/ray/autoscaler — StandardAutoscaler.update loop
(_private/autoscaler.py:172,374) reading GCS load (load_metrics.py),
bin-packing demand (resource_demand_scheduler.py), launching/terminating via
a pluggable NodeProvider (node_provider.py:13); v2 reconciler design
(v2/instance_manager/reconciler.py:53) driven by GCS autoscaler state.

This implementation follows the v2 shape: a single reconciler step
(StandardAutoscaler.update) diffs observed cluster state (GCS
get_cluster_load) against the config's node-type bounds, launches via the
provider, and terminates idle nodes. TPU twist: a node type with a `TPU`
resource is a SLICE (gang) — scale-up adds whole slices, and scale-down only
removes a slice when it is fully idle (no per-chip elasticity inside a mesh,
SURVEY §7 hard parts).
"""

from ray_tpu.autoscaler.autoscaler import StandardAutoscaler  # noqa: F401
from ray_tpu.autoscaler.monitor import Monitor  # noqa: F401
from ray_tpu.autoscaler.node_provider import (  # noqa: F401
    LocalNodeProvider,
    NodeProvider,
)
from ray_tpu.autoscaler.resource_demand_scheduler import (  # noqa: F401
    get_nodes_to_launch,
)
