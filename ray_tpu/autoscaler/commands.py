"""Cluster launcher: `ray-tpu up / down / exec / attach / rsync`.

Reference: ray python/ray/autoscaler/_private/commands.py
(create_or_update_cluster:707, teardown_cluster:807, exec_cluster:1313,
attach_cluster:1281, rsync:1410) and scripts.py:1282 (`ray up`). The
provider here is the on-prem shape (static head_ip + worker_ips reached
over SSH, like the reference's "local" provider,
autoscaler/_private/local/node_provider.py); cloud-managed TPU pods go
through the GKE/KubeRay provider instead (gke_node_provider.py), where the
operator owns node lifecycle and `up` is a `kubectl apply`.

Cluster state (which IP serves which role) persists in
``~/.ray_tpu/clusters/<name>.json`` (override dir: RT_CLUSTER_STATE_DIR)
so `down`/`exec` work from a fresh shell.
"""

from __future__ import annotations

import json
import logging
import os
import subprocess
import time
from typing import Dict, List, Optional

from ray_tpu.autoscaler.command_runner import make_command_runner
from ray_tpu.autoscaler.updater import NodeUpdater, NodeUpdaterError

logger = logging.getLogger(__name__)

DEFAULT_HEAD_PORT = 7001

_REQUIRED_KEYS = ("cluster_name", "provider")
_KNOWN_KEYS = {
    "cluster_name", "max_workers", "min_workers", "provider", "auth",
    "file_mounts", "initialization_commands", "setup_commands",
    "head_setup_commands", "worker_setup_commands",
    "head_start_ray_commands", "worker_start_ray_commands",
    "stop_ray_commands", "env",
}


def load_cluster_config(path: str) -> dict:
    import yaml

    with open(os.path.expanduser(path)) as f:
        config = yaml.safe_load(f)
    validate_cluster_config(config)
    return config


def validate_cluster_config(config: dict) -> None:
    for key in _REQUIRED_KEYS:
        if key not in config:
            raise ValueError(f"cluster config missing required key: {key}")
    unknown = set(config) - _KNOWN_KEYS
    if unknown:
        raise ValueError(f"unknown cluster config keys: {sorted(unknown)}")
    provider = config["provider"]
    ptype = provider.get("type")
    if ptype in ("local", "subprocess"):
        if not provider.get("head_ip"):
            raise ValueError("provider.head_ip is required for "
                             f"type: {ptype}")
    elif ptype == "gke":
        raise ValueError(
            "provider type 'gke' clusters are operator-managed: apply the "
            "RayCluster CR (see ray_tpu.autoscaler.gke_node_provider) "
            "instead of `ray-tpu up`")
    elif ptype == "gce_tpu":
        for key in ("project", "zone"):
            if not provider.get(key):
                raise ValueError(
                    f"provider.{key} is required for type: gce_tpu")
    else:
        raise ValueError(f"unknown provider.type: {ptype!r} "
                         "(expected 'local', 'subprocess', or 'gce_tpu')")


# ---- cluster state ----------------------------------------------------------

def _state_dir() -> str:
    d = os.environ.get("RT_CLUSTER_STATE_DIR") or os.path.expanduser(
        "~/.ray_tpu/clusters")
    os.makedirs(d, exist_ok=True)
    return d


def _state_path(cluster_name: str) -> str:
    return os.path.join(_state_dir(), f"{cluster_name}.json")


def _load_state(cluster_name: str) -> dict:
    try:
        with open(_state_path(cluster_name)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {"head": None, "workers": []}


def _save_state(cluster_name: str, state: dict) -> None:
    with open(_state_path(cluster_name), "w") as f:
        json.dump(state, f, indent=2)


def _delete_state(cluster_name: str) -> None:
    try:
        os.remove(_state_path(cluster_name))
    except OSError:
        pass


# ---- commands ---------------------------------------------------------------

def _head_address(config: dict) -> str:
    provider = config["provider"]
    port = provider.get("head_port", DEFAULT_HEAD_PORT)
    return f"{provider['head_ip']}:{port}"


def _updater_for(config: dict, ip: str, is_head: bool,
                 restart: bool = True) -> NodeUpdater:
    runner = make_command_runner(ip, config)
    env = dict(config.get("env") or {})
    env["RAY_HEAD_IP"] = config["provider"]["head_ip"]
    env["RT_HEAD_ADDRESS"] = _head_address(config)
    start_key = ("head_start_ray_commands" if is_head
                 else "worker_start_ray_commands")
    setup_key = "head_setup_commands" if is_head else "worker_setup_commands"
    start = config.get(start_key)
    if start is None:
        port = config["provider"].get("head_port", DEFAULT_HEAD_PORT)
        start = ([f"python -m ray_tpu start --head --port={port} --block "
                  "> /tmp/rt_head.log 2>&1 & sleep 2"] if is_head else
                 ["python -m ray_tpu start --address=$RT_HEAD_ADDRESS "
                  "--block > /tmp/rt_worker_$$.log 2>&1 & sleep 2"])
    return NodeUpdater(
        ip, runner,
        file_mounts=config.get("file_mounts"),
        initialization_commands=config.get("initialization_commands"),
        setup_commands=(config.get("setup_commands", [])
                        + config.get(setup_key, [])),
        start_commands=start if restart else [],
        env=env,
    )


def create_or_update_cluster(config_path: str, *, no_restart: bool = False,
                             min_workers: Optional[int] = None) -> dict:
    """`ray-tpu up`: bring the head (and min_workers workers) to running.
    Idempotent — re-running re-syncs mounts and re-runs setup; pass
    no_restart to keep the running ray-tpu processes."""
    config = load_cluster_config(config_path)
    name = config["cluster_name"]
    provider = config["provider"]
    state = _load_state(name)

    head_ip = provider["head_ip"]
    head_running = state.get("head") == head_ip
    _updater_for(config, head_ip, is_head=True,
                 restart=not (no_restart and head_running)).update()
    state["head"] = head_ip
    _save_state(name, state)

    want = min_workers
    if want is None:
        want = config.get("min_workers", len(provider.get("worker_ips", [])))
    worker_ips = list(provider.get("worker_ips", []))[:want]
    failed: List[str] = []
    for ip in worker_ips:
        already = ip in state.get("workers", [])
        try:
            _updater_for(config, ip, is_head=False,
                         restart=not (no_restart and already)).update()
            if not already:
                state.setdefault("workers", []).append(ip)
        except NodeUpdaterError as e:
            logger.error("worker %s failed to start: %s", ip, e)
            failed.append(ip)
        _save_state(name, state)
    logger.info("cluster %s up: head=%s workers=%s%s", name, head_ip,
                state.get("workers", []),
                f" FAILED={failed}" if failed else "")
    return {"head": head_ip, "workers": state.get("workers", []),
            "failed": failed, "address": _head_address(config)}


def teardown_cluster(config_path: str,
                     workers_only: bool = False) -> None:
    """`ray-tpu down`: stop ray-tpu on every node and forget the cluster."""
    config = load_cluster_config(config_path)
    name = config["cluster_name"]
    state = _load_state(name)
    stop_cmds = config.get("stop_ray_commands") or [
        "python -m ray_tpu stop || true"]
    nodes = list(state.get("workers", []))
    if not workers_only and state.get("head"):
        nodes.append(state["head"])
    for ip in nodes:
        runner = make_command_runner(ip, config)
        for cmd in stop_cmds:
            try:
                runner.run(cmd, timeout=60)
            except Exception as e:  # noqa: BLE001 — dead node: nothing to stop
                logger.warning("stop on %s failed: %s", ip, e)
    if workers_only:
        state["workers"] = []
        _save_state(name, state)
    else:
        _delete_state(name)
    logger.info("cluster %s torn down (%d nodes)", name, len(nodes))


def exec_cluster(config_path: str, cmd: str,
                 run_env: Optional[Dict[str, str]] = None) -> int:
    """`ray-tpu exec`: run a shell command on the head node, streaming
    output. Returns the remote exit code."""
    config = load_cluster_config(config_path)
    state = _load_state(config["cluster_name"])
    head = state.get("head") or config["provider"]["head_ip"]
    runner = make_command_runner(head, config)
    env = dict(config.get("env") or {})
    env["RT_HEAD_ADDRESS"] = _head_address(config)
    env.update(run_env or {})
    r = runner.run(cmd, env=env, timeout=None)
    if r.stdout:
        print(r.stdout, end="")
    if r.stderr:
        import sys

        print(r.stderr, end="", file=sys.stderr)
    return r.returncode


def attach_cluster(config_path: str) -> int:
    """`ray-tpu attach`: interactive shell on the head node."""
    config = load_cluster_config(config_path)
    state = _load_state(config["cluster_name"])
    head = state.get("head") or config["provider"]["head_ip"]
    runner = make_command_runner(head, config)
    return subprocess.call(runner.remote_shell_argv())


def rsync(config_path: str, source: str, target: str, *,
          down: bool = False) -> None:
    """`ray-tpu rsync-up/-down` between the local machine and the head."""
    config = load_cluster_config(config_path)
    state = _load_state(config["cluster_name"])
    head = state.get("head") or config["provider"]["head_ip"]
    runner = make_command_runner(head, config)
    if down:
        runner.run_rsync_down(source, target)
    else:
        runner.run_rsync_up(source, target)


def get_head_node_ip(config_path: str) -> str:
    config = load_cluster_config(config_path)
    state = _load_state(config["cluster_name"])
    return state.get("head") or config["provider"]["head_ip"]
