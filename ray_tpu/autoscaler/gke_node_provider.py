"""GKE/KubeRay-style node provider: scales a RayCluster custom resource.

Reference behavior: ray python/ray/autoscaler/_private/kuberay/
node_provider.py — worker pods carry `ray.io/cluster` / `ray.io/group`
labels; scaling = one declarative PATCH of the RayCluster CR setting each
workerGroupSpec's `replicas` plus `scaleStrategy.workersToDelete`; the
KubeRay operator converges pods to that spec. On GKE TPU, a worker group
maps to a TPU slice node pool, so one replica = one slice host gang.

The Kubernetes API client is a tiny urllib wrapper (in-cluster service
account auth); tests inject a fake with the same request() surface.
"""

from __future__ import annotations

import json
import logging
import os
import ssl
from typing import Dict, Optional

from ray_tpu.autoscaler.batching_node_provider import (
    BatchingNodeProvider,
    NodeData,
    ScaleRequest,
)

logger = logging.getLogger(__name__)

CLUSTER_LABEL = "ray.io/cluster"
GROUP_LABEL = "ray.io/group"
HEAD_GROUP = "headgroup"


class KubernetesApi:
    """Minimal in-cluster Kubernetes API client (service-account auth)."""

    TOKEN_PATH = "/var/run/secrets/kubernetes.io/serviceaccount/token"  # noqa: S105
    CA_PATH = "/var/run/secrets/kubernetes.io/serviceaccount/ca.crt"

    def __init__(self, host: Optional[str] = None,
                 token: Optional[str] = None):
        self.host = host or (
            "https://" + os.environ.get("KUBERNETES_SERVICE_HOST", "")
            + ":" + os.environ.get("KUBERNETES_SERVICE_PORT", "443"))
        if token is None and os.path.exists(self.TOKEN_PATH):
            with open(self.TOKEN_PATH) as f:
                token = f.read().strip()
        self.token = token
        self._ssl = (ssl.create_default_context(cafile=self.CA_PATH)
                     if os.path.exists(self.CA_PATH)
                     else ssl.create_default_context())

    def request(self, method: str, path: str, body: Optional[dict] = None,
                content_type: str = "application/json") -> dict:
        import urllib.request

        req = urllib.request.Request(
            self.host + path,
            data=json.dumps(body).encode() if body is not None else None,
            method=method,
            headers={
                "Authorization": f"Bearer {self.token}",
                "Content-Type": content_type,
                "Accept": "application/json",
            })
        with urllib.request.urlopen(req, timeout=30,
                                    context=self._ssl) as resp:
            return json.loads(resp.read().decode() or "{}")


class GkeNodeProvider(BatchingNodeProvider):
    """provider_config: {"namespace": str, "ray_cluster_name": str}.
    `api` injection point is for tests (recorded/fake HTTP)."""

    def __init__(self, provider_config: dict, cluster_name: str,
                 api: Optional[KubernetesApi] = None):
        super().__init__(provider_config, cluster_name)
        self.namespace = provider_config.get("namespace", "default")
        self.ray_cluster_name = provider_config.get(
            "ray_cluster_name", cluster_name)
        self.api = api or KubernetesApi()

    # -- BatchingNodeProvider hooks ------------------------------------------

    def get_node_data(self) -> Dict[str, NodeData]:
        pods = self.api.request(
            "GET",
            f"/api/v1/namespaces/{self.namespace}/pods"
            f"?labelSelector={CLUSTER_LABEL}={self.ray_cluster_name}")
        out: Dict[str, NodeData] = {}
        for pod in pods.get("items", []):
            meta = pod.get("metadata", {})
            labels = meta.get("labels", {})
            group = labels.get(GROUP_LABEL, "")
            if group == HEAD_GROUP:
                continue  # the autoscaler never scales the head
            phase = pod.get("status", {}).get("phase", "Pending")
            if phase in ("Succeeded", "Failed"):
                continue
            out[meta["name"]] = NodeData(
                node_type=group,
                status="up-to-date" if phase == "Running" else "setting-up",
                ip=pod.get("status", {}).get("podIP", ""),
            )
        return out

    def submit_scale_request(self, req: ScaleRequest) -> None:
        path = (f"/apis/ray.io/v1/namespaces/{self.namespace}"
                f"/rayclusters/{self.ray_cluster_name}")
        cr = self.api.request("GET", path)
        groups = cr.get("spec", {}).get("workerGroupSpecs", [])
        # RFC 7386 merge-patch replaces ARRAYS wholesale, so the patch must
        # carry the FULL group objects (template, rayStartParams, ...) with
        # only replicas/scaleStrategy mutated — skeleton entries would wipe
        # every other field from the CR and strand the operator.
        for group in groups:
            name = group.get("groupName", "")
            group["replicas"] = req.desired.get(
                name, group.get("replicas", 0))
            to_delete = sorted(
                nid for nid in req.workers_to_delete
                if self._node_data.get(nid)
                and self._node_data[nid].node_type == name)
            # ALWAYS set scaleStrategy: the GET above may carry a stale
            # workersToDelete list from a prior cycle, and re-PATCHing it
            # verbatim on a later scale-up would re-delete recovered pods.
            # An empty list clears stale entries.
            group["scaleStrategy"] = {"workersToDelete": to_delete}
        self.api.request(
            "PATCH", path, {"spec": {"workerGroupSpecs": groups}},
            content_type="application/merge-patch+json")

    def raylet_node_id(self, node_id: str) -> Optional[str]:
        # pods join the GCS view by the ray.io/pod-name node label instead
        # (see StandardAutoscaler.update's label join)
        return None
