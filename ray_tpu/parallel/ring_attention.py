"""Ring attention: exact attention over sequence-sharded Q/K/V.

Sequence/context parallelism the reference does not implement natively
(SURVEY.md §5 "Long-context / sequence parallelism": Ray only provides the
substrate — NCCL p2p channels — and points users at external Torch libraries).
Here it is a first-class op: K/V blocks rotate around the `sp` mesh axis via
`jax.lax.ppermute` (XLA lowers to ICI collective-permute) while each device
accumulates flash-style online-softmax partial results for its resident Q
block. Communication overlaps compute across ring steps; memory stays
O(S_local) per device, enabling sequences sp× longer than a single chip holds.

Use inside `shard_map` over the `sp` axis (see `ring_attention_sharded` for
the wrapped version).
"""

from __future__ import annotations

import functools
from typing import Optional

NEG_INF = -1e30


def _block_attn_update(q, k, v, o, m, l, q_pos, k_pos, scale, causal):
    """One flash-attention accumulation step against a K/V block.

    q: [B, Sq, H, D]; k, v: [B, Sk, H, D]; o: [B, Sq, H, D];
    m, l: [B, H, Sq] running max / normalizer; *_pos: global token positions.
    """
    import jax.numpy as jnp

    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale  # [B,H,Sq,Sk]
    if causal:
        mask = q_pos[:, None] >= k_pos[None, :]           # [Sq, Sk]
        scores = jnp.where(mask[None, None], scores, NEG_INF)
    m_new = jnp.maximum(m, jnp.max(scores, axis=-1))      # [B,H,Sq]
    # Guard fully-masked rows (m_new == NEG_INF) against NaNs.
    m_safe = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
    p = jnp.exp(scores - m_safe[..., None])
    p = jnp.where(scores <= NEG_INF / 2, 0.0, p)
    correction = jnp.exp(jnp.where(m <= NEG_INF / 2, NEG_INF, m) - m_safe)
    correction = jnp.where(m <= NEG_INF / 2, 0.0, correction)
    l_new = l * correction + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    o_new = o * correction.transpose(0, 2, 1)[..., None] + pv
    return o_new, m_new, l_new


def ring_attention(q, k, v, axis_name: str = "sp", causal: bool = True,
                   scale: Optional[float] = None):
    """Exact attention where q/k/v are the local sequence shard.

    Must run inside shard_map/with an active mesh axis `axis_name`.
    Shapes: q, k, v: [B, S_local, H, D] (GQA: repeat kv heads beforehand).
    Returns [B, S_local, H, D].
    """
    import jax
    import jax.numpy as jnp

    b, s_loc, h, d = q.shape
    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    if scale is None:
        scale = d ** -0.5

    q_pos = my_idx * s_loc + jnp.arange(s_loc)
    o = jnp.zeros_like(q, dtype=jnp.float32)
    m = jnp.full((b, h, s_loc), NEG_INF, dtype=jnp.float32)
    l = jnp.zeros((b, h, s_loc), dtype=jnp.float32)

    # Ring: at step s, the local buffer holds K/V originally from device
    # (my_idx - s) mod n; ppermute sends to the right neighbor each step.
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(s, carry):
        o, m, l, k_cur, v_cur = carry
        src = (my_idx - s) % n
        k_pos = src * s_loc + jnp.arange(s_loc)

        def do_update(oml):
            o, m, l = oml
            return _block_attn_update(
                q.astype(jnp.float32), k_cur.astype(jnp.float32),
                v_cur.astype(jnp.float32), o, m, l, q_pos, k_pos, scale,
                causal,
            )

        if causal:
            # Source shards entirely in the future are fully masked — skip
            # their score blocks (roughly halves compute on the sp axis);
            # K/V still rotate so later steps see them.
            o, m, l = jax.lax.cond(
                src <= my_idx, do_update, lambda oml: oml, (o, m, l)
            )
        else:
            o, m, l = do_update((o, m, l))
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return o, m, l, k_nxt, v_nxt

    o, m, l, _, _ = jax.lax.fori_loop(0, n, step, (o, m, l, k, v))
    l = jnp.maximum(l, 1e-20)
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh, axis_name: str = "sp",
                           causal: bool = True):
    """shard_map-wrapped ring attention over sequence-sharded global arrays.

    q/k/v: global [B, S, H, D] logically sharded on S over `axis_name`.
    """
    import jax
    from jax.sharding import PartitionSpec as P
    from ray_tpu._private.jax_compat import shard_map

    # Shard batch over every data-parallel axis (incl. the inter-slice dcn
    # axis of multi-slice meshes) and heads over tp — replicating those
    # dims would all-gather the activations (across DCN, for dcn!) and
    # redo attention on every shard, defeating the O(S_local) point.
    batch_axes = tuple(a for a in ("dcn", "dp", "fsdp")
                       if mesh.shape.get(a, 1) > 1)
    bdiv = 1
    for a in batch_axes:
        bdiv *= mesh.shape[a]
    if q.shape[0] % max(bdiv, 1) != 0:
        batch_axes = ()
    head_axis = ("tp" if mesh.shape.get("tp", 1) > 1
                 and q.shape[2] % mesh.shape["tp"] == 0 else None)
    spec = P(batch_axes or None, axis_name, head_axis, None)
    fn = functools.partial(ring_attention, axis_name=axis_name, causal=causal)
    return shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )(q, k, v)
