"""Compiled-HLO collective report: what a sharded program actually moves.

VERDICT r3 weak #8: the parallel layer's fsdp/tp/sp/pp configs validate
numerically on a virtual mesh, but nothing bounded their COMMUNICATION.
This module compiles a jitted function for a mesh config and parses the
optimized HLO for collective ops — counts and bytes moved per kind — so
tests can pin each mesh config's collective signature (dp → gradient
all-reduce of ~param bytes; fsdp → all-gather + reduce-scatter; tp →
activation all-reduces; sp → collective-permute ring hops) and catch
sharding regressions that would silently multiply traffic.

The "How to Scale Your Model" workflow in tool form: pick a mesh,
annotate shardings, let XLA insert collectives, then LOOK at what it
inserted.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Dict

# optimized-HLO instruction kinds we account
_COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter",
                "collective-permute", "all-to-all")

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "bf16": 2, "f16": 2, "f8": 1,
    "s64": 8, "s32": 4, "s16": 2, "s8": 1,
    "u64": 8, "u32": 4, "u16": 2, "u8": 1, "pred": 1,
    "c64": 8, "c128": 16,
}

# "%all-gather.3 = bf16[8,128,256]{...} all-gather(" — also matches tuple
# shapes by scanning each "dtype[dims]" in the line's result type.
_INSTR_RE = re.compile(
    r"=\s*(?:\()?\s*([a-z0-9]+)\[([0-9,]*)\][^=]*?\s("
    + "|".join(_COLLECTIVES) + r")(?:-start|-done)?\(")
_SHAPE_RE = re.compile(r"([a-z0-9]+)\[([0-9,]*)\]")


def _shape_bytes(dtype: str, dims: str) -> int:
    size = _DTYPE_BYTES.get(dtype)
    if size is None:
        return 0
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n * size


def collective_report(fn: Callable, *args,
                      static_argnames=None) -> Dict[str, Dict[str, int]]:
    """Compile `fn(*args)` and account its collectives.

    -> {kind: {"count": n, "bytes": total_result_bytes}} plus a "total"
    entry. Bytes are the collectives' RESULT buffer sizes — a consistent
    proxy for traffic (exact wire bytes depend on algorithm/topology).
    """
    import jax

    # Pre-jitted callables (and make_train_step's wrapper, whose state
    # argument is a plain dataclass that only ITS .lower knows how to
    # pytree-ify) advertise a .lower hook — prefer it over re-jitting.
    lower = getattr(fn, "lower", None)
    if lower is not None:
        lowered = lower(*args)
    else:
        lowered = jax.jit(fn, static_argnames=static_argnames).lower(*args)
    hlo = lowered.compile().as_text()
    report: Dict[str, Dict[str, int]] = {
        k: {"count": 0, "bytes": 0} for k in _COLLECTIVES}
    for line in hlo.splitlines():
        m = _INSTR_RE.search(line)
        if m is None:
            continue
        kind = m.group(3)
        if kind.endswith("-done"):
            continue  # paired with its -start; count once
        # result type may be a tuple (async pairs): sum every shape
        # between '=' and the op kind (NOT from line start — the
        # instruction NAME also contains the kind, e.g. %all-reduce.1)
        eq = line.find("=")
        lhs = line[eq:m.start(3)] if eq >= 0 else ""
        nbytes = sum(_shape_bytes(d, dims)
                     for d, dims in _SHAPE_RE.findall(lhs))
        report[kind]["count"] += 1
        report[kind]["bytes"] += nbytes
    report["total"] = {
        "count": sum(v["count"] for v in report.values()),
        "bytes": sum(v["bytes"] for v in report.values()),
    }
    return report
