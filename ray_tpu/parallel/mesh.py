"""Device mesh construction + multi-host bootstrap.

The TPU-native replacement for the reference's NCCL process-group bootstrap
(ray: python/ray/train/torch/config.py:112 _setup_torch_process_group, and
ray/util/collective's NCCL groups): instead of exchanging NCCL unique ids,
worker gangs call `initialize_distributed` (a thin `jax.distributed` wrapper
whose coordinator is the rank-0 worker), then every process builds the same
`jax.sharding.Mesh` over the global device set and runs the same jit program
— collectives are emitted by XLA over ICI/DCN (SURVEY.md §5 "Distributed
communication backend").

Mesh axes (outer → inner, DCN-ish → ICI-ish): pp, dp, fsdp, ep, sp, tp.
TP innermost so its collectives ride the fastest ICI links.
"""

from __future__ import annotations

import dataclasses
import logging
import math
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

AXIS_ORDER = ("pp", "dp", "fsdp", "ep", "sp", "tp")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Degrees for each parallelism axis; -1 on dp means 'fill remaining'."""

    dp: int = -1      # data parallel (pure replication of params)
    fsdp: int = 1     # fully-sharded data parallel (params sharded on batch axis)
    tp: int = 1       # tensor (Megatron) parallel
    sp: int = 1       # sequence/context parallel (ring attention)
    pp: int = 1       # pipeline parallel
    ep: int = 1       # expert parallel (MoE)

    def resolved(self, n_devices: int) -> "MeshConfig":
        known = self.fsdp * self.tp * self.sp * self.pp * self.ep
        dp = self.dp
        if dp == -1:
            if n_devices % known != 0:
                raise ValueError(
                    f"device count {n_devices} not divisible by "
                    f"fsdp*tp*sp*pp*ep={known}"
                )
            dp = n_devices // known
        if dp * known != n_devices:
            raise ValueError(
                f"mesh {self} needs {dp * known} devices, have {n_devices}"
            )
        return dataclasses.replace(self, dp=dp)

    def axis_sizes(self) -> Dict[str, int]:
        return {
            "pp": self.pp, "dp": self.dp, "fsdp": self.fsdp,
            "ep": self.ep, "sp": self.sp, "tp": self.tp,
        }


def build_mesh(config: MeshConfig = MeshConfig(), devices=None):
    """Build a jax.sharding.Mesh over the (global) device set."""
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    config = config.resolved(len(devices))
    sizes = config.axis_sizes()
    shape = tuple(sizes[a] for a in AXIS_ORDER)
    import numpy as np

    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, AXIS_ORDER)


def build_multislice_mesh(config: MeshConfig = MeshConfig(),
                          num_slices: int = 1, devices=None):
    """Mesh spanning multiple TPU slices: a leading `dcn` axis maps onto
    the slow inter-slice network, and the per-slice MeshConfig axes map
    onto each slice's ICI torus.

    Layout doctrine (SURVEY §7 "Multi-slice (DCN) collectives"): only DATA
    parallelism crosses slices — its per-step collective is one gradient
    all-reduce, which XLA's multi-slice lowering runs hierarchically
    (reduce-scatter on ICI per slice -> small cross-slice DCN all-reduce ->
    all-gather on ICI). Model axes (tp/sp/fsdp/pp/ep) stay inside a slice,
    so their frequent collectives never touch DCN. Sharding rules map the
    batch axis over ("dcn", "dp", "fsdp") — size-1 axes drop out, so the
    same model code runs on single-slice meshes unchanged.

    Device order: on real multi-slice TPU, jax.devices() groups by
    slice_index; `jax.experimental.mesh_utils.create_hybrid_device_mesh`
    orders granules DCN-outer. Where slice structure is unavailable (CPU
    tests, single-slice), a plain reshape produces the same logical layout.
    """
    import jax
    import numpy as np
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    if num_slices <= 1:
        return build_mesh(config, devices=devices)
    if len(devices) % num_slices != 0:
        raise ValueError(
            f"{len(devices)} devices not divisible by {num_slices} slices")
    per_slice = len(devices) // num_slices
    config = config.resolved(per_slice)
    sizes = config.axis_sizes()
    ici_shape = tuple(sizes[a] for a in AXIS_ORDER)
    axes = ("dcn",) + AXIS_ORDER
    if getattr(devices[0], "slice_index", None) is not None:
        # real multi-slice hardware: the hybrid util orders granules by
        # slice. Errors here are REAL config mistakes (num_slices vs the
        # actual slice count, granule mismatch) and must propagate — a
        # silent reshape fallback would run tp/sp collectives over DCN.
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_hybrid_device_mesh(
            (1,) + ici_shape,  # per-granule (per-slice) ICI shape
            (num_slices,) + (1,) * len(AXIS_ORDER),  # DCN split: dcn axis
            devices=devices)
        dev_array = np.asarray(dev_array).reshape(
            (num_slices,) + ici_shape)
    else:
        # no slice metadata (CPU tests / single-slice): plain reshape
        # yields the same logical layout
        dev_array = np.asarray(devices).reshape((num_slices,) + ici_shape)
    return Mesh(dev_array, axes)


def local_device_mesh(config: Optional[MeshConfig] = None):
    """Mesh over this process's local devices only (single-host)."""
    import jax

    return build_mesh(config or MeshConfig(), devices=jax.local_devices())


def initialize_distributed(
    coordinator_address: str, num_processes: int, process_id: int
) -> None:
    """Multi-host rendezvous: the mesh-collective equivalent of NCCL init.

    Called by every worker in a gang (see ray_tpu.train's backend setup);
    rank 0's address is distributed through the actor gang the same way the
    reference broadcasts the master address (torch/config.py:112).

    Idempotent: re-initializing an already-connected process with the same
    (coordinator, world, rank) is a no-op — a gang restarted inside a
    surviving worker process must not crash on double-init. A DIFFERENT
    binding (a re-formed gang with a new rank-0 coordinator) shuts the old
    client down first, so the process never stays silently bound to a dead
    coordinator. Limitation: a coordinator that died and RESTARTED at the
    same fixed address is indistinguishable from a live one by address
    alone — pin coordinator_port only when worker processes cannot outlive
    a gang incarnation (the default random-port path never collides).
    """
    import jax

    try:  # jax 0.4.x: no public is_initialized — inspect the global client
        from jax._src import distributed as _dist

        state = _dist.global_state
        if getattr(state, "client", None) is not None:
            if (state.coordinator_address == coordinator_address
                    and state.num_processes == num_processes
                    and state.process_id == process_id):
                logger.info(
                    "jax.distributed already initialized for this gang; "
                    "skipping")
                return
            logger.warning(
                "jax.distributed bound to %s (world=%s rank=%s); "
                "re-initializing for %s (world=%s rank=%s)",
                state.coordinator_address, state.num_processes,
                state.process_id, coordinator_address, num_processes,
                process_id)
            state.shutdown()
    except ImportError:  # pragma: no cover — future jax moves the module
        pass
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def best_mesh_for(n_devices: int, model_axis_max: int = 8) -> MeshConfig:
    """Heuristic default: TP within a chip-group bound, rest data parallel."""
    tp = math.gcd(n_devices, model_axis_max)
    return MeshConfig(dp=n_devices // tp, tp=tp)
