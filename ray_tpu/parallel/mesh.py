"""Device mesh construction + multi-host bootstrap.

The TPU-native replacement for the reference's NCCL process-group bootstrap
(ray: python/ray/train/torch/config.py:112 _setup_torch_process_group, and
ray/util/collective's NCCL groups): instead of exchanging NCCL unique ids,
worker gangs call `initialize_distributed` (a thin `jax.distributed` wrapper
whose coordinator is the rank-0 worker), then every process builds the same
`jax.sharding.Mesh` over the global device set and runs the same jit program
— collectives are emitted by XLA over ICI/DCN (SURVEY.md §5 "Distributed
communication backend").

Mesh axes (outer → inner, DCN-ish → ICI-ish): pp, dp, fsdp, ep, sp, tp.
TP innermost so its collectives ride the fastest ICI links.
"""

from __future__ import annotations

import dataclasses
import logging
import math
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

AXIS_ORDER = ("pp", "dp", "fsdp", "ep", "sp", "tp")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Degrees for each parallelism axis; -1 on dp means 'fill remaining'."""

    dp: int = -1      # data parallel (pure replication of params)
    fsdp: int = 1     # fully-sharded data parallel (params sharded on batch axis)
    tp: int = 1       # tensor (Megatron) parallel
    sp: int = 1       # sequence/context parallel (ring attention)
    pp: int = 1       # pipeline parallel
    ep: int = 1       # expert parallel (MoE)

    def resolved(self, n_devices: int) -> "MeshConfig":
        known = self.fsdp * self.tp * self.sp * self.pp * self.ep
        dp = self.dp
        if dp == -1:
            if n_devices % known != 0:
                raise ValueError(
                    f"device count {n_devices} not divisible by "
                    f"fsdp*tp*sp*pp*ep={known}"
                )
            dp = n_devices // known
        if dp * known != n_devices:
            raise ValueError(
                f"mesh {self} needs {dp * known} devices, have {n_devices}"
            )
        return dataclasses.replace(self, dp=dp)

    def axis_sizes(self) -> Dict[str, int]:
        return {
            "pp": self.pp, "dp": self.dp, "fsdp": self.fsdp,
            "ep": self.ep, "sp": self.sp, "tp": self.tp,
        }


def build_mesh(config: MeshConfig = MeshConfig(), devices=None):
    """Build a jax.sharding.Mesh over the (global) device set."""
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    config = config.resolved(len(devices))
    sizes = config.axis_sizes()
    shape = tuple(sizes[a] for a in AXIS_ORDER)
    import numpy as np

    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, AXIS_ORDER)


def local_device_mesh(config: Optional[MeshConfig] = None):
    """Mesh over this process's local devices only (single-host)."""
    import jax

    return build_mesh(config or MeshConfig(), devices=jax.local_devices())


def initialize_distributed(
    coordinator_address: str, num_processes: int, process_id: int
) -> None:
    """Multi-host rendezvous: the mesh-collective equivalent of NCCL init.

    Called by every worker in a gang (see ray_tpu.train's backend setup);
    rank 0's address is distributed through the actor gang the same way the
    reference broadcasts the master address (torch/config.py:112).
    """
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def best_mesh_for(n_devices: int, model_axis_max: int = 8) -> MeshConfig:
    """Heuristic default: TP within a chip-group bound, rest data parallel."""
    tp = math.gcd(n_devices, model_axis_max)
    return MeshConfig(dp=n_devices // tp, tp=tp)
