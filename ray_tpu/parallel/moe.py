"""Mixture-of-Experts dispatch with expert parallelism.

EP capability absent from the reference (SURVEY.md §5): top-k routing with
capacity, dispatch/combine as einsums against an expert-sharded weight stack.
Under pjit, annotating the expert dim with the `ep` mesh axis makes XLA emit
the all-to-alls; `moe_shard_map` offers the explicit `lax.all_to_all` form
for when manual control wins.
"""

from __future__ import annotations

from typing import Callable, Tuple


def top_k_gating(logits, k: int, capacity: int):
    """Compute dispatch/combine tensors for top-k routing with capacity.

    logits: [T, E]. Returns (dispatch [T, E, C] one-hot-ish, combine
    [T, E, C] weights, aux_loss scalar).
    """
    import jax
    import jax.numpy as jnp

    t, e = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    gate_vals, gate_idx = jax.lax.top_k(probs, k)            # [T, k]
    # Load-balancing auxiliary loss (Switch-style).
    me = jnp.mean(probs, axis=0)                             # [E]
    top1 = jax.nn.one_hot(gate_idx[:, 0], e)
    ce = jnp.mean(top1, axis=0)
    aux_loss = e * jnp.sum(me * ce)

    # Position of each token within its expert's buffer. Slots are assigned
    # in priority order (all slot-0 choices first, then slot-1, ...) with a
    # running per-expert offset so a token picking expert E as 1st choice and
    # another picking E as 2nd choice never collide in the same capacity slot.
    dispatch = jnp.zeros((t, e, capacity), dtype=jnp.float32)
    combine = jnp.zeros((t, e, capacity), dtype=jnp.float32)
    expert_counts = jnp.zeros((e,), dtype=jnp.float32)
    for slot in range(k):
        idx = gate_idx[:, slot]                              # [T]
        onehot = jax.nn.one_hot(idx, e)                      # [T, E]
        pos = (jnp.cumsum(onehot, axis=0) - onehot + expert_counts) * onehot
        pos_in_expert = jnp.sum(pos, axis=-1).astype(jnp.int32)  # [T]
        expert_counts = expert_counts + jnp.sum(onehot, axis=0)
        keep = pos_in_expert < capacity
        cap_onehot = jax.nn.one_hot(pos_in_expert, capacity)  # [T, C]
        d = onehot[:, :, None] * cap_onehot[:, None, :] * keep[:, None, None]
        dispatch = dispatch + d
        combine = combine + d * gate_vals[:, slot][:, None, None]
    return dispatch, combine, aux_loss


def moe_layer(x, gate_w, expert_fn: Callable, expert_params,
              k: int = 2, capacity_factor: float = 1.25):
    """Apply an MoE layer. x: [T, D]; gate_w: [D, E]; expert_params leaves
    lead with the expert dim E (annotate it with the `expert` logical axis so
    pjit shards it over `ep`). Returns ([T, D], aux_loss)."""
    import jax.numpy as jnp
    import jax

    t, d = x.shape
    e = gate_w.shape[1]
    capacity = max(1, int(capacity_factor * t * max(k, 1) / e))
    logits = x.astype(jnp.float32) @ gate_w.astype(jnp.float32)
    dispatch, combine, aux = top_k_gating(logits, k, capacity)
    # [E, C, D]: per-expert token buffers.
    expert_in = jnp.einsum("tec,td->ecd", dispatch, x.astype(jnp.float32))
    expert_out = jax.vmap(expert_fn)(expert_params, expert_in.astype(x.dtype))
    out = jnp.einsum("tec,ecd->td", combine, expert_out.astype(jnp.float32))
    return out.astype(x.dtype), aux


def moe_shard_map(x, gate_w, expert_fn, expert_params, mesh,
                  axis_name: str = "ep", k: int = 2,
                  capacity_factor: float = 1.25):
    """Explicit-collective variant: experts sharded over `axis_name`, token
    buffers exchanged with lax.all_to_all."""
    import jax
    import jax.numpy as jnp
    from ray_tpu._private.jax_compat import shard_map
    from jax.sharding import PartitionSpec as P

    n_exp_total = gate_w.shape[1]

    def local_fn(x_loc, gate_w_full, params_loc):
        t, d = x_loc.shape
        n_shards = jax.lax.psum(1, axis_name)
        capacity = max(1, int(capacity_factor * t * max(k, 1) / n_exp_total))
        logits = x_loc.astype(jnp.float32) @ gate_w_full.astype(jnp.float32)
        dispatch, combine, aux = top_k_gating(logits, k, capacity)
        buf = jnp.einsum("tec,td->ecd", dispatch, x_loc.astype(jnp.float32))
        # [E, C, D] -> exchange so each shard holds its experts' tokens from
        # every shard: split E across shards.
        buf = buf.reshape(n_shards, n_exp_total // n_shards, capacity, d)
        buf = jax.lax.all_to_all(buf, axis_name, 0, 0, tiled=False)
        # buf: [n_shards(src), E_local, C, D] -> merge src into capacity dim
        e_loc = n_exp_total // n_shards
        buf = buf.transpose(1, 0, 2, 3).reshape(e_loc, n_shards * capacity, d)
        out = jax.vmap(expert_fn)(params_loc, buf.astype(x_loc.dtype))
        out = out.reshape(e_loc, n_shards, capacity, d).transpose(1, 0, 2, 3)
        out = jax.lax.all_to_all(out, axis_name, 0, 0, tiled=False)
        out = out.reshape(n_exp_total, capacity, d)
        y = jnp.einsum("tec,ecd->td", combine, out.astype(jnp.float32))
        # aux is computed from this shard's tokens only; the result is
        # declared replicated (out_specs=P()), so it must actually BE the
        # global mean, not one shard's local value.
        return y.astype(x_loc.dtype), jax.lax.pmean(aux, axis_name)

    pspec = jax.tree.map(lambda _: P(axis_name), expert_params)
    return shard_map(
        local_fn, mesh=mesh,
        in_specs=(P(axis_name), P(), pspec),
        out_specs=(P(axis_name), P()),
        check_vma=False,
    )(x, gate_w, expert_params)
