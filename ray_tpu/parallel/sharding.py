"""Logical-axis sharding rules (t5x/flax-style, re-implemented).

Model code annotates arrays with LOGICAL axis names ("batch", "seq", "embed",
"heads", "mlp", "vocab", "kv", "expert", "layers"); a rule table maps logical
names to physical mesh axes. This is the Megatron-style TP + FSDP layer the
reference has no native equivalent of (SURVEY.md §5): XLA inserts the
all-gathers/reduce-scatters implied by the shardings.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple, Union

PhysicalAxes = Union[None, str, Tuple[str, ...]]

# Default rule table: logical axis -> mesh axis (or tuple). dp x fsdp x tp.
# Parameter axes ("embed", "heads", ...) and activation axes ("act_*") are
# distinct namespaces: under FSDP the parameter embed dim shards over `fsdp`
# while the activation batch dim also uses `fsdp` — a single array may not
# map one mesh axis twice, so activations never reuse parameter rules.
DEFAULT_RULES: List[Tuple[str, PhysicalAxes]] = [
    # activations
    # batch over ALL data-parallel axes, incl. the inter-slice `dcn` axis
    # of multi-slice meshes (absent/size-1 axes drop out, so single-slice
    # meshes are unaffected). Only dp crosses DCN: its one gradient
    # all-reduce per step lowers hierarchically (ICI reduce-scatter ->
    # DCN all-reduce -> ICI all-gather); model axes stay on ICI.
    ("batch", ("dcn", "dp", "fsdp")),
    ("seq", "sp"),               # sequence/context parallel
    ("act_embed", None),         # activations: embed replicated
    ("act_heads", "tp"),         # attention activations: heads over TP
    ("act_kv", None),
    ("act_mlp", "tp"),           # MLP activations: hidden over TP
    ("act_vocab", "tp"),         # logits: vocab over TP
    # parameters
    ("embed", "fsdp"),           # params: embed dim sharded for FSDP
    ("heads", "tp"),             # attention heads: tensor parallel
    ("kv", None),                # per-head dim: replicated
    ("mlp", "tp"),               # MLP hidden: tensor parallel
    ("vocab", "tp"),             # vocab dim: tensor parallel
    ("expert", "ep"),            # MoE experts
    ("layers", None),            # scanned layer dim: replicated (pp handles)
    ("stage", "pp"),             # pipeline stage dim
]


class LogicalAxisRules:
    def __init__(self, rules: Optional[Sequence[Tuple[str, PhysicalAxes]]] = None):
        self._rules: Dict[str, PhysicalAxes] = dict(rules if rules is not None else DEFAULT_RULES)

    def to_physical(self, logical_axes: Sequence[Optional[str]], mesh=None):
        """Map logical axis names to a PartitionSpec, dropping mesh axes of
        size 1 (so the same model code runs on any mesh shape)."""
        from jax.sharding import PartitionSpec

        sizes = dict(mesh.shape) if mesh is not None else None

        def resolve(name: Optional[str]):
            if name is None:
                return None
            phys = self._rules.get(name)
            if phys is None:
                return None
            axes = (phys,) if isinstance(phys, str) else tuple(phys)
            if sizes is not None:
                axes = tuple(a for a in axes if sizes.get(a, 1) > 1)
            if not axes:
                return None
            return axes if len(axes) > 1 else axes[0]

        return PartitionSpec(*[resolve(n) for n in logical_axes])

    def replace(self, **kwargs: PhysicalAxes) -> "LogicalAxisRules":
        new = LogicalAxisRules(list(self._rules.items()))
        new._rules.update(kwargs)
        return new


def logical_sharding(mesh, logical_axes: Sequence[Optional[str]],
                     rules: Optional[LogicalAxisRules] = None):
    from jax.sharding import NamedSharding

    rules = rules or LogicalAxisRules()
    return NamedSharding(mesh, rules.to_physical(logical_axes, mesh))


def with_logical_constraint(x, logical_axes: Sequence[Optional[str]],
                            mesh=None, rules: Optional[LogicalAxisRules] = None):
    """Annotate an intermediate value inside jit with a logical sharding."""
    import jax

    if mesh is None:
        # Ambient mesh: prefer the new jax.set_mesh context, fall back to the
        # legacy `with mesh:` context (thread_resources — deprecated but the
        # only way to see `with mesh:` users; warning suppressed).
        mesh = None
        try:
            from jax.sharding import get_abstract_mesh

            am = get_abstract_mesh()
            if am is not None and not am.empty:
                mesh = am
        except Exception:  # noqa: BLE001
            pass
        if mesh is None:
            import warnings

            with warnings.catch_warnings():
                warnings.simplefilter("ignore", DeprecationWarning)
                from jax.interpreters import pxla

                legacy = pxla.thread_resources.env.physical_mesh
            if legacy.empty:
                return x
            mesh = legacy
    rules = rules or LogicalAxisRules()
    return jax.lax.with_sharding_constraint(
        x, logical_sharding(mesh, logical_axes, rules)
    )


def shard_params(params, param_logical_axes, mesh,
                 rules: Optional[LogicalAxisRules] = None):
    """device_put a parameter pytree according to per-leaf logical axes.

    `param_logical_axes` is a matching pytree whose leaves are tuples of
    logical axis names (or None for replicated).
    """
    import jax

    rules = rules or LogicalAxisRules()

    def place(x, axes):
        sharding = logical_sharding(mesh, axes if axes is not None else [None] * x.ndim, rules)
        return jax.device_put(x, sharding)

    return jax.tree.map(place, params, param_logical_axes,
                        is_leaf=lambda x: x is None)


def param_shardings(param_logical_axes, mesh, rules=None):
    """Pytree of NamedShardings from a pytree of logical-axes tuples."""
    rules = rules or LogicalAxisRules()

    def make(axes):
        return logical_sharding(mesh, axes if axes is not None else [], rules)

    import jax

    return jax.tree.map(
        make, param_logical_axes,
        is_leaf=lambda x: x is None or (isinstance(x, tuple) and all(
            a is None or isinstance(a, str) for a in x)),
    )
