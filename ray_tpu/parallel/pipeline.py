"""Pipeline parallelism: GPipe-style microbatch schedule inside one jit.

The compiled-graph/aDAG capability of the reference (ray:
python/ray/dag/compiled_dag_node.py — static actor pipelines over
mutable-object channels with NCCL sends) re-designed the TPU way: stages are
shards of a `pp` mesh axis, microbatch activations move between stages with
`jax.lax.ppermute` (ICI collective-permute), and the whole schedule is a
`lax.scan` the XLA scheduler can overlap. No channels, no actors in the inner
loop — the pipeline IS the program.

Layout convention: layer parameters are stacked on a leading `stage` axis of
size pp (each stage holds its own slice); inputs arrive as [num_microbatches,
microbatch, ...] sharded so every stage sees all microbatches.
"""

from __future__ import annotations

import functools
from typing import Any, Callable


def pipeline_apply(
    stage_fn: Callable,           # (stage_params, x) -> y, one stage's compute
    stage_params: Any,            # pytree; leaves lead with the local stage dim
    microbatches,                 # [M, mb, ...] identical on every stage
    axis_name: str = "pp",
):
    """Run the GPipe schedule; returns [M, mb, ...] final-stage outputs
    (valid on every device — the result is broadcast back around the ring)."""
    import jax
    import jax.numpy as jnp

    n_stages = jax.lax.psum(1, axis_name)
    stage_id = jax.lax.axis_index(axis_name)
    m = microbatches.shape[0]
    total_steps = m + n_stages - 1
    perm_fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    mb_shape = microbatches.shape[1:]

    def step(carry, t):
        buf, outputs = carry
        # Stage 0 injects microbatch t (when valid); others take the buffer
        # that arrived from the left neighbor last step.
        mb_index = jnp.clip(t, 0, m - 1)
        inject = microbatches[mb_index]
        x = jnp.where(stage_id == 0, inject, buf)
        y = stage_fn(stage_params, x)
        # The last stage's output for microbatch (t - n_stages + 1) is ready.
        out_index = t - n_stages + 1
        valid = (out_index >= 0) & (out_index < m)
        outputs = jax.lax.cond(
            valid,
            lambda o: o.at[jnp.clip(out_index, 0, m - 1)].set(
                jnp.where(stage_id == n_stages - 1, y, o[jnp.clip(out_index, 0, m - 1)])
            ),
            lambda o: o,
            outputs,
        )
        buf_next = jax.lax.ppermute(y, axis_name, perm_fwd)
        return (buf_next, outputs), None

    buf0 = jnp.zeros(mb_shape, dtype=microbatches.dtype)
    outputs0 = jnp.zeros((m,) + mb_shape, dtype=microbatches.dtype)
    (_, outputs), _ = jax.lax.scan(
        step, (buf0, outputs0), jnp.arange(total_steps)
    )
    # Only the last stage holds real outputs; broadcast them to all stages so
    # downstream (loss) code is SPMD-uniform. psum of masked outputs = select.
    mask = (stage_id == n_stages - 1).astype(outputs.dtype)
    outputs = jax.lax.psum(outputs * mask, axis_name)
    return outputs


def pipeline_sharded(stage_fn, mesh, axis_name: str = "pp"):
    """shard_map wrapper: params lead with a [pp, ...] stage axis, inputs are
    replicated microbatches; returns final outputs replicated."""
    import jax
    from ray_tpu._private.jax_compat import shard_map
    from jax.sharding import PartitionSpec as P

    def wrapped(stacked_params, microbatches):
        fn = functools.partial(pipeline_apply, stage_fn, axis_name=axis_name)
        param_specs = jax.tree.map(lambda _: P(axis_name), stacked_params)
        return shard_map(
            fn, mesh=mesh,
            in_specs=(param_specs, P()),
            out_specs=P(),
            check_vma=False,
        )(stacked_params, microbatches)

    return wrapped
