"""TPU-native parallelism layer: mesh construction, sharding rules, ring
attention (SP), pipeline stages (PP), and MoE dispatch (EP).

This is capability the reference delegates to external Torch ecosystems
(SURVEY.md §5 "Long-context / sequence parallelism": DeepSpeed/Accelerate/FSDP
integrations under ray python/ray/train/) — here it is first-class: DP/FSDP
via NamedSharding, TP via Megatron-style PartitionSpecs, SP via ring attention
over `ppermute`, PP via staged shard_map, EP via sharded expert dispatch.

Imports JAX lazily at module level only inside submodules — `import ray_tpu`
never pulls JAX in.
"""

from ray_tpu.parallel.mesh import (  # noqa: F401
    MeshConfig,
    build_mesh,
    local_device_mesh,
)
from ray_tpu.parallel.sharding import (  # noqa: F401
    LogicalAxisRules,
    logical_sharding,
    shard_params,
    with_logical_constraint,
)
