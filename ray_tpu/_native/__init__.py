"""Native (C++) components of ray_tpu.

The reference implements its runtime hot paths in C++ (plasma store,
raylet, core worker — SURVEY §2.1); ray_tpu keeps the same split: JAX/XLA
is the TPU compute path, and node-local runtime services live in C++ here,
bound into Python with ctypes (no pybind11 in the image).

Libraries are compiled on demand with g++ and cached next to the sources
(keyed by a source hash), so the repo carries sources, not binaries.
"""

from __future__ import annotations

import hashlib
import os
import subprocess
import threading
from typing import Optional

_SRC_DIR = os.path.join(os.path.dirname(__file__), "src")
_BUILD_DIR = os.path.join(os.path.dirname(__file__), "build")
_lock = threading.Lock()
_built: dict = {}


class NativeBuildError(RuntimeError):
    pass


def build_library(name: str, sources: Optional[list] = None) -> str:
    """Compile ray_tpu/_native/src/<name>.cc into a cached .so; return path.

    RT_NATIVE_SANITIZE=thread|address builds with the matching
    -fsanitize flag (reference: the TSAN/ASAN bazel configs,
    .bazelrc:104-121); sanitized builds cache under a distinct tag and
    report races/UB on the processes' stderr at runtime.
    """
    sources = sources or [os.path.join(_SRC_DIR, f"{name}.cc")]
    sanitize = os.environ.get("RT_NATIVE_SANITIZE", "")
    with _lock:
        key = (name, sanitize)
        if key in _built:
            return _built[key]
        h = hashlib.sha256(sanitize.encode())
        for s in sources:
            with open(s, "rb") as f:
                h.update(f.read())
        tag = h.hexdigest()[:16]
        out = os.path.join(_BUILD_DIR, f"lib{name}-{tag}.so")
        if not os.path.exists(out):
            os.makedirs(_BUILD_DIR, exist_ok=True)
            tmp = out + f".tmp.{os.getpid()}"
            extra = []
            if sanitize in ("thread", "address"):
                extra = [f"-fsanitize={sanitize}", "-fno-omit-frame-pointer",
                         "-O1"]
            cmd = [
                "g++", "-O2", "-g", "-std=c++17", "-shared", "-fPIC",
                "-pthread", *extra, "-o", tmp, *sources,
            ]
            proc = subprocess.run(cmd, capture_output=True, text=True)
            if proc.returncode != 0:
                raise NativeBuildError(
                    f"g++ failed for {name}:\n{proc.stderr[-4000:]}")
            os.replace(tmp, out)
        _built[key] = out
        return out


def try_build_library(name: str) -> Optional[str]:
    """build_library, or None when no toolchain is available."""
    try:
        return build_library(name)
    except (NativeBuildError, FileNotFoundError, OSError):
        return None
