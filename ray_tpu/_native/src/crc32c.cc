// CRC32C (Castagnoli) — slice-by-8 table implementation for the TFRecord
// codec (data/_internal/tfrecords.py). The reference's TFRecord path rides
// tensorflow's native CRC; this is the ray_tpu-native equivalent so bulk
// record IO never drops into a per-byte Python loop.
//
// Exposed C ABI:
//   uint32_t rtcrc_crc32c(const uint8_t* data, uint64_t n, uint32_t init);
// `init` is the running CRC state (0 for a fresh buffer), pre/post
// inversion handled inside, so chained calls compose:
//   crc = rtcrc_crc32c(a, na, 0); crc = rtcrc_crc32c(b, nb, crc);

#include <cstdint>
#include <cstddef>

namespace {

constexpr uint32_t kPoly = 0x82F63B78u;

struct Tables {
  uint32_t t[8][256];
  Tables() {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) c = (c & 1) ? (c >> 1) ^ kPoly : c >> 1;
      t[0][i] = c;
    }
    for (uint32_t i = 0; i < 256; i++)
      for (int s = 1; s < 8; s++)
        t[s][i] = (t[s - 1][i] >> 8) ^ t[0][t[s - 1][i] & 0xFF];
  }
};

const Tables kTables;

}  // namespace

extern "C" uint32_t rtcrc_crc32c(const uint8_t* data, uint64_t n,
                                 uint32_t init) {
  const auto& t = kTables.t;
  uint32_t crc = ~init;
  // head: align to 8 bytes
  while (n && (reinterpret_cast<uintptr_t>(data) & 7u)) {
    crc = t[0][(crc ^ *data++) & 0xFF] ^ (crc >> 8);
    n--;
  }
  while (n >= 8) {
    uint64_t w = *reinterpret_cast<const uint64_t*>(data) ^ crc;
    crc = t[7][w & 0xFF] ^ t[6][(w >> 8) & 0xFF] ^ t[5][(w >> 16) & 0xFF] ^
          t[4][(w >> 24) & 0xFF] ^ t[3][(w >> 32) & 0xFF] ^
          t[2][(w >> 40) & 0xFF] ^ t[1][(w >> 48) & 0xFF] ^
          t[0][(w >> 56) & 0xFF];
    data += 8;
    n -= 8;
  }
  while (n--) crc = t[0][(crc ^ *data++) & 0xFF] ^ (crc >> 8);
  return ~crc;
}
