// TPU-native shared-memory object store ("plasma" equivalent).
//
// Role of the reference's Plasma store (ray: src/ray/object_manager/plasma/
// store.h:55, client.cc, protocol over Unix socket + fd passing fling.cc):
// a per-node immutable object store in shared memory so every worker process
// on the node reads object payloads zero-copy.  TPU twist: payloads are the
// flat SerializedObject wire format, so a worker can wrap a stored numpy/jax
// host buffer as a jax.Array input without copies (mmap -> device_put).
//
// Design (not a translation of plasma):
//   * one shm arena per node created with memfd_create, passed to clients
//     over SCM_RIGHTS during the socket handshake (like plasma's fling.cc,
//     but a single arena instead of per-object mmaps)
//   * server-side first-fit free-list allocator with coalescing (plasma
//     vendors dlmalloc; an in-server allocator keeps all metadata private)
//   * thread-per-connection control plane guarded by one mutex + condvar;
//     the data plane never touches the server (clients read/write the
//     mapped arena directly)
//   * objects are PRIMARY (owner payload: never auto-evicted, listed for
//     disk spilling like raylet/local_object_manager.h:41) or CACHE
//     (remote-fetch copies: LRU auto-evicted under memory pressure like
//     plasma/eviction_policy.cc)
//   * per-connection reference counts; a dying client auto-releases
//     (plasma client disconnect semantics)
//
// Exposed as a C API (rtps_*) for ctypes binding from Python
// (ray_tpu/_private/shm_store.py).

#define _GNU_SOURCE 1

#include <errno.h>
#include <fcntl.h>
#include <pthread.h>
#include <stdint.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/un.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <iterator>
#include <stdexcept>
#include <string>
#include <condition_variable>
#include <cstdio>
#include <cstdlib>
#include <list>
#include <map>
#include <memory>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

// ---------------------------------------------------------------- protocol

constexpr uint64_t kMagic = 0x52545053484d3031ULL;  // "RTPSHM01"
constexpr uint64_t kAlign = 64;

enum Op : uint8_t {
  OP_CREATE = 1,
  OP_SEAL = 2,
  OP_GET = 3,
  OP_RELEASE = 4,
  OP_DELETE = 5,
  OP_CONTAINS = 6,
  OP_STATS = 7,
  OP_LIST = 8,   // a: max ids, b: 1 = spillable primaries, 0 = evictable caches
  OP_ABORT = 9,  // abort an unsealed create
  OP_FREE_INFO = 10,  // free-list shape: status=holes, offset=largest, size=total
};

enum Status : int64_t {
  ST_OK = 0,
  ST_FULL = -1,
  ST_EXISTS = -2,
  ST_NOT_FOUND = -3,
  ST_TIMEOUT = -4,
  ST_NOT_SEALED = -5,
  ST_ERR = -6,
};

struct Request {
  uint8_t op;
  uint8_t pad[7];
  uint8_t id[16];
  uint64_t a;  // CREATE: size, GET: timeout_ms (UINT64_MAX = infinite), LIST: max
  uint64_t b;  // CREATE: flags (1 = primary), LIST: 1 = primaries
};

struct Response {
  int64_t status;
  uint64_t offset;
  uint64_t size;
};

struct ObjectId {
  uint8_t b[16];
  bool operator==(const ObjectId& o) const { return memcmp(b, o.b, 16) == 0; }
};

struct IdHash {
  size_t operator()(const ObjectId& id) const {
    uint64_t h;
    memcpy(&h, id.b, 8);
    return static_cast<size_t>(h * 0x9E3779B97F4A7C15ULL);
  }
};

bool ReadFull(int fd, void* buf, size_t n) {
  auto* p = static_cast<uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = read(fd, p, n);
    if (r <= 0) {
      if (r < 0 && errno == EINTR) continue;
      return false;
    }
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool WriteFull(int fd, const void* buf, size_t n) {
  const auto* p = static_cast<const uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = write(fd, p, n);
    if (r <= 0) {
      if (r < 0 && errno == EINTR) continue;
      return false;
    }
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

// Send the arena fd + capacity in one message (SCM_RIGHTS, cf. plasma fling.cc).
bool SendHandshake(int sock, int arena_fd, uint64_t capacity) {
  uint64_t payload[2] = {kMagic, capacity};
  struct iovec iov = {payload, sizeof(payload)};
  char cmsgbuf[CMSG_SPACE(sizeof(int))];
  memset(cmsgbuf, 0, sizeof(cmsgbuf));
  struct msghdr msg;
  memset(&msg, 0, sizeof(msg));
  msg.msg_iov = &iov;
  msg.msg_iovlen = 1;
  msg.msg_control = cmsgbuf;
  msg.msg_controllen = sizeof(cmsgbuf);
  struct cmsghdr* cmsg = CMSG_FIRSTHDR(&msg);
  cmsg->cmsg_level = SOL_SOCKET;
  cmsg->cmsg_type = SCM_RIGHTS;
  cmsg->cmsg_len = CMSG_LEN(sizeof(int));
  memcpy(CMSG_DATA(cmsg), &arena_fd, sizeof(int));
  // MSG_NOSIGNAL: the peer may already be gone (e.g. Stop()'s throwaway
  // wake connection) — surface EPIPE as a failed handshake, not SIGPIPE.
  return sendmsg(sock, &msg, MSG_NOSIGNAL) == sizeof(payload);
}

bool RecvHandshake(int sock, int* arena_fd, uint64_t* capacity) {
  uint64_t payload[2] = {0, 0};
  struct iovec iov = {payload, sizeof(payload)};
  char cmsgbuf[CMSG_SPACE(sizeof(int))];
  struct msghdr msg;
  memset(&msg, 0, sizeof(msg));
  msg.msg_iov = &iov;
  msg.msg_iovlen = 1;
  msg.msg_control = cmsgbuf;
  msg.msg_controllen = sizeof(cmsgbuf);
  if (recvmsg(sock, &msg, 0) != sizeof(payload)) return false;
  if (payload[0] != kMagic) return false;
  struct cmsghdr* cmsg = CMSG_FIRSTHDR(&msg);
  if (cmsg == nullptr || cmsg->cmsg_type != SCM_RIGHTS) return false;
  memcpy(arena_fd, CMSG_DATA(cmsg), sizeof(int));
  *capacity = payload[1];
  return true;
}

// ---------------------------------------------------------------- allocator

// First-fit free list with coalescing over arena offsets.
class Arena {
 public:
  explicit Arena(uint64_t capacity) : capacity_(capacity) {
    free_[0] = capacity;
  }

  // Returns false if no contiguous block fits.
  bool Alloc(uint64_t size, uint64_t* offset) {
    uint64_t need = (size + kAlign - 1) & ~(kAlign - 1);
    if (need == 0) need = kAlign;
    for (auto it = free_.begin(); it != free_.end(); ++it) {
      if (it->second >= need) {
        *offset = it->first;
        uint64_t rem = it->second - need;
        uint64_t tail = it->first + need;
        free_.erase(it);
        if (rem > 0) free_[tail] = rem;
        used_ += need;
        sizes_[*offset] = need;
        return true;
      }
    }
    return false;
  }

  void Free(uint64_t offset) {
    auto sit = sizes_.find(offset);
    if (sit == sizes_.end()) return;
    uint64_t size = sit->second;
    sizes_.erase(sit);
    used_ -= size;
    auto it = free_.emplace(offset, size).first;
    // Coalesce with next block.
    auto next = std::next(it);
    if (next != free_.end() && it->first + it->second == next->first) {
      it->second += next->second;
      free_.erase(next);
    }
    // Coalesce with previous block.
    if (it != free_.begin()) {
      auto prev = std::prev(it);
      if (prev->first + prev->second == it->first) {
        prev->second += it->second;
        free_.erase(it);
      }
    }
  }

  uint64_t used() const { return used_; }
  uint64_t capacity() const { return capacity_; }

  // Free-list shape for fragmentation accounting: a put needs ONE
  // contiguous hole, so `largest` (not the total) bounds the biggest
  // allocatable object.
  void FreeInfo(uint64_t* holes, uint64_t* largest, uint64_t* total) const {
    for (const auto& kv : free_) {
      ++*holes;
      *total += kv.second;
      if (kv.second > *largest) *largest = kv.second;
    }
  }

 private:
  uint64_t capacity_;
  uint64_t used_ = 0;
  std::map<uint64_t, uint64_t> free_;             // offset -> size
  std::unordered_map<uint64_t, uint64_t> sizes_;  // offset -> allocated size
};

// ------------------------------------------------------------------ server

struct ObjectEntry {
  uint64_t offset = 0;
  uint64_t size = 0;
  bool sealed = false;
  bool primary = false;
  bool pending_delete = false;
  int creator_conn = -1;  // connection that created it (for abort-on-death)
  uint64_t refcount = 0;  // across all connections
  uint64_t lru_tick = 0;  // last-touched tick for CACHE eviction order
};

class StoreServer {
 public:
  StoreServer(const char* socket_path, uint64_t capacity)
      : path_(socket_path), arena_(capacity) {
    arena_fd_ = memfd_create("ray_tpu_store", MFD_CLOEXEC);
    if (arena_fd_ < 0) throw std::runtime_error("memfd_create failed");
    if (ftruncate(arena_fd_, static_cast<off_t>(capacity)) != 0) {
      close(arena_fd_);
      throw std::runtime_error("ftruncate failed");
    }
    base_ = static_cast<uint8_t*>(mmap(nullptr, capacity,
                                       PROT_READ | PROT_WRITE, MAP_SHARED,
                                       arena_fd_, 0));
    if (base_ == MAP_FAILED) {
      close(arena_fd_);
      throw std::runtime_error("mmap failed");
    }

    listen_fd_ = socket(AF_UNIX, SOCK_STREAM | SOCK_CLOEXEC, 0);
    if (listen_fd_ < 0) throw std::runtime_error("socket failed");
    struct sockaddr_un addr;
    memset(&addr, 0, sizeof(addr));
    addr.sun_family = AF_UNIX;
    snprintf(addr.sun_path, sizeof(addr.sun_path), "%s", socket_path);
    unlink(socket_path);
    if (bind(listen_fd_, reinterpret_cast<struct sockaddr*>(&addr),
             sizeof(addr)) != 0 ||
        listen(listen_fd_, 128) != 0) {
      close(listen_fd_);
      throw std::runtime_error("bind/listen failed");
    }
    accept_thread_ = std::thread([this] { AcceptLoop(); });

    // Opt-in whole-arena pre-fault (RT_STORE_PREFAULT=1): see
    // StoreClient::Prefault for why this must never be the default --
    // populating the full capacity on every cluster init melts a test
    // farm of short-lived clusters.
#ifdef MADV_POPULATE_WRITE
    const char* pf = getenv("RT_STORE_PREFAULT");
    if (pf != nullptr && strcmp(pf, "1") == 0) {
      uint64_t cap = arena_.capacity();
      prefault_thread_ = std::thread([this, cap] {
        madvise(base_, cap, MADV_POPULATE_WRITE);
      });
    }
#endif
  }

  ~StoreServer() { Stop(); }

  void Stop() {
    bool expected = false;
    if (!stopping_.compare_exchange_strong(expected, true)) return;
    // Wake a blocked accept4 with a throwaway self-connect BEFORE tearing
    // the listen socket down: on some kernels (gVisor/runsc sandboxes)
    // neither shutdown() nor close() of a listening unix socket wakes a
    // blocked accept, and the join below would hang the host process
    // forever. The connect completes against the backlog regardless of
    // whether accept ever returns it; AcceptLoop re-checks stopping_
    // before blocking again.
    int wake_fd = socket(AF_UNIX, SOCK_STREAM | SOCK_CLOEXEC, 0);
    if (wake_fd >= 0) {
      struct sockaddr_un wake_addr;
      memset(&wake_addr, 0, sizeof(wake_addr));
      wake_addr.sun_family = AF_UNIX;
      snprintf(wake_addr.sun_path, sizeof(wake_addr.sun_path), "%s",
               path_.c_str());
      connect(wake_fd, reinterpret_cast<struct sockaddr*>(&wake_addr),
              sizeof(wake_addr));
      close(wake_fd);
    }
    shutdown(listen_fd_, SHUT_RDWR);
    close(listen_fd_);
    unlink(path_.c_str());
    {
      std::lock_guard<std::mutex> g(mu_);
      for (int fd : conn_fds_) shutdown(fd, SHUT_RDWR);
      cv_.notify_all();
    }
    if (accept_thread_.joinable()) accept_thread_.join();
    if (prefault_thread_.joinable()) prefault_thread_.join();
    std::vector<std::unique_ptr<Conn>> conns;
    {
      std::lock_guard<std::mutex> g(mu_);
      conns.swap(conn_threads_);
    }
    for (auto& c : conns) {
      if (c->thread.joinable()) c->thread.join();
    }
    munmap(base_, arena_.capacity());
    close(arena_fd_);
  }

 private:
  void AcceptLoop() {
    int conn_id = 0;
    while (!stopping_.load()) {
      int fd = accept4(listen_fd_, nullptr, nullptr, SOCK_CLOEXEC);
      if (fd < 0) {
        if (errno == EINTR) continue;
        break;  // listen socket closed => shutting down
      }
      if (!SendHandshake(fd, arena_fd_, arena_.capacity())) {
        close(fd);
        continue;
      }
      try {
        RegisterConn(fd, conn_id++);
      } catch (...) {
        // Allocation failure under host memory pressure: refuse the
        // connection rather than std::terminate the host process.
        close(fd);
      }
    }
  }

  void RegisterConn(int fd, int id) {
    std::lock_guard<std::mutex> g(mu_);
    // Stop() may have run between accept4 and here; registering now
    // would miss its shutdown pass and leave a Serve thread blocked in
    // read() forever (deadlocking Stop's join).
    if (stopping_.load()) {
      close(fd);
      return;
    }
    ReapFinishedLocked();
    conn_fds_.push_back(fd);
    Conn* c = nullptr;
    try {
      conn_threads_.emplace_back(new Conn{std::thread(), {false}});
      c = conn_threads_.back().get();
    } catch (...) {
      // Roll the fd registration back before rethrowing to AcceptLoop's
      // close(fd): a registered-but-threadless fd would later have
      // Stop() shutdown() a possibly-reused descriptor number.
      conn_fds_.erase(
          std::remove(conn_fds_.begin(), conn_fds_.end(), fd),
          conn_fds_.end());
      throw;
    }
    try {
      c->thread = std::thread([this, fd, id, c] {
      // An exception escaping a thread body is std::terminate — and
      // this store runs INSIDE the raylet host process, so that
      // would abort the whole node (seen once as a pytest SIGABRT
      // under the OOM-killer tests' memory pressure: bad_alloc in a
      // map insert). Drop the connection instead; the client sees a
      // closed socket and its pins auto-release.
      try {
        Serve(fd, id);
      } catch (...) {
        // Serve's own Cleanup closes the fd on every unwind path; if
        // even Cleanup threw, fd ownership is ambiguous — leak the
        // descriptor rather than risk closing a reused one.
      }
      c->done.store(true);
      });
    } catch (...) {
      conn_threads_.pop_back();
      conn_fds_.erase(
          std::remove(conn_fds_.begin(), conn_fds_.end(), fd),
          conn_fds_.end());
      throw;
    }
  }

  // Join threads whose Serve() has exited (bounds conn_threads_ growth under
  // connection churn). Caller holds mu_.
  void ReapFinishedLocked() {
    for (auto it = conn_threads_.begin(); it != conn_threads_.end();) {
      if ((*it)->done.load()) {
        if ((*it)->thread.joinable()) (*it)->thread.join();
        it = conn_threads_.erase(it);
      } else {
        ++it;
      }
    }
  }

  void Serve(int fd, int conn_id) {
    std::unordered_map<ObjectId, uint64_t, IdHash> held;  // id -> refs
    ServeLoop(fd, conn_id, &held);
    Cleanup(fd, conn_id, held);
  }

  // The request loop, separated so an exception (bad_alloc under host
  // memory pressure) unwinds into Serve's cleanup instead of
  // std::terminate-ing the host process.
  void ServeLoop(int fd, int conn_id,
                 std::unordered_map<ObjectId, uint64_t, IdHash>* held_p) {
    auto& held = *held_p;
    Request req;
    try {
    while (ReadFull(fd, &req, sizeof(req))) {
      Response rsp = {ST_ERR, 0, 0};
      std::vector<uint8_t> extra;
      ObjectId id;
      memcpy(id.b, req.id, 16);
      switch (req.op) {
        case OP_CREATE:
          rsp = Create(id, req.a, req.b, conn_id, &held);
          break;
        case OP_SEAL:
          rsp = Seal(id);
          break;
        case OP_GET:
          rsp = Get(id, req.a, &held);
          break;
        case OP_RELEASE:
          rsp = Release(id, &held);
          break;
        case OP_DELETE:
          rsp = Delete(id);
          break;
        case OP_ABORT:
          rsp = Abort(id, &held);
          break;
        case OP_CONTAINS: {
          std::lock_guard<std::mutex> g(mu_);
          auto it = objects_.find(id);
          rsp.status =
              (it != objects_.end() && it->second.sealed) ? ST_OK : ST_NOT_FOUND;
          if (rsp.status == ST_OK) rsp.size = it->second.size;
          break;
        }
        case OP_STATS: {
          std::lock_guard<std::mutex> g(mu_);
          rsp.status = static_cast<int64_t>(objects_.size());
          rsp.offset = arena_.used();
          rsp.size = arena_.capacity();
          break;
        }
        case OP_LIST:
          rsp = List(req.a, req.b != 0, &extra);
          break;
        case OP_FREE_INFO: {
          std::lock_guard<std::mutex> g(mu_);
          uint64_t holes = 0, largest = 0, total = 0;
          arena_.FreeInfo(&holes, &largest, &total);
          rsp.status = static_cast<int64_t>(holes);
          rsp.offset = largest;
          rsp.size = total;
          break;
        }
        default:
          rsp.status = ST_ERR;
      }
      if (!WriteFull(fd, &rsp, sizeof(rsp))) break;
      if (!extra.empty() && !WriteFull(fd, extra.data(), extra.size())) break;
    }
    } catch (...) {
      // bad_alloc under host memory pressure mid-request: fall through
      // to Cleanup with whatever `held` recorded so far.
    }
  }

  void Cleanup(int fd, int conn_id,
               std::unordered_map<ObjectId, uint64_t, IdHash>& held) {
    // Client died or disconnected: release everything it held, abort its
    // unsealed creates (plasma disconnect semantics).
    {
      std::lock_guard<std::mutex> g(mu_);
      for (auto& kv : held) {
        auto it = objects_.find(kv.first);
        if (it == objects_.end()) continue;
        ObjectEntry& e = it->second;
        e.refcount -= std::min(e.refcount, kv.second);
        if (!e.sealed && e.creator_conn == conn_id) {
          arena_.Free(e.offset);
          objects_.erase(it);
        } else if (e.refcount == 0 && e.pending_delete) {
          arena_.Free(e.offset);
          objects_.erase(it);
        }
      }
      // Forget this connection's fd so Stop() never calls shutdown() on an
      // fd number the process may have reused for an unrelated socket.
      conn_fds_.erase(std::remove(conn_fds_.begin(), conn_fds_.end(), fd),
                      conn_fds_.end());
      cv_.notify_all();
    }
    close(fd);
  }

  // Evict the single least-recently-used sealed, unreferenced CACHE object.
  // Returns false when none is evictable. Caller holds mu_.
  bool EvictOneCache() {
    ObjectId victim;
    uint64_t best_tick = UINT64_MAX;
    bool found = false;
    for (auto& kv : objects_) {
      ObjectEntry& e = kv.second;
      if (e.sealed && !e.primary && e.refcount == 0 && e.lru_tick < best_tick) {
        best_tick = e.lru_tick;
        victim = kv.first;
        found = true;
      }
    }
    if (!found) return false;
    auto it = objects_.find(victim);
    arena_.Free(it->second.offset);
    objects_.erase(it);
    return true;
  }

  Response Create(const ObjectId& id, uint64_t size, uint64_t flags,
                  int conn_id,
                  std::unordered_map<ObjectId, uint64_t, IdHash>* held) {
    std::lock_guard<std::mutex> g(mu_);
    if (objects_.count(id)) return {ST_EXISTS, 0, 0};
    uint64_t offset = 0;
    // Allocation needs a CONTIGUOUS block, so evicting "enough bytes" is not
    // enough under fragmentation: evict LRU caches one at a time (freed
    // neighbours coalesce) and retry until the block fits or nothing is left.
    while (!arena_.Alloc(size, &offset)) {
      if (!EvictOneCache()) return {ST_FULL, arena_.used(), size};
    }
    ObjectEntry e;
    e.offset = offset;
    e.size = size;
    e.primary = (flags & 1) != 0;
    e.creator_conn = conn_id;
    e.refcount = 1;  // creator holds a ref until release
    e.lru_tick = tick_++;
    objects_[id] = e;
    (*held)[id] += 1;
    return {ST_OK, offset, size};
  }

  Response Seal(const ObjectId& id) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = objects_.find(id);
    if (it == objects_.end()) return {ST_NOT_FOUND, 0, 0};
    it->second.sealed = true;
    cv_.notify_all();
    return {ST_OK, it->second.offset, it->second.size};
  }

  Response Get(const ObjectId& id, uint64_t timeout_ms,
               std::unordered_map<ObjectId, uint64_t, IdHash>* held) {
    std::unique_lock<std::mutex> lk(mu_);
    auto sealed = [&]() -> ObjectEntry* {
      auto it = objects_.find(id);
      return (it != objects_.end() && it->second.sealed) ? &it->second : nullptr;
    };
    ObjectEntry* e = sealed();
    if (e == nullptr && timeout_ms > 0) {
      auto pred = [&] { return stopping_.load() || sealed() != nullptr; };
      if (timeout_ms == UINT64_MAX) {
        cv_.wait(lk, pred);
      } else {
        cv_.wait_for(lk, std::chrono::milliseconds(timeout_ms), pred);
      }
      e = sealed();
    }
    if (e == nullptr) return {ST_TIMEOUT, 0, 0};
    e->refcount += 1;
    e->lru_tick = tick_++;
    (*held)[id] += 1;
    return {ST_OK, e->offset, e->size};
  }

  Response Release(const ObjectId& id,
                   std::unordered_map<ObjectId, uint64_t, IdHash>* held) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = objects_.find(id);
    auto hit = held->find(id);
    if (it == objects_.end() || hit == held->end()) return {ST_NOT_FOUND, 0, 0};
    if (--hit->second == 0) held->erase(hit);
    ObjectEntry& e = it->second;
    if (e.refcount > 0) e.refcount -= 1;
    if (e.refcount == 0 && e.pending_delete) {
      arena_.Free(e.offset);
      objects_.erase(it);
    }
    return {ST_OK, 0, 0};
  }

  Response Delete(const ObjectId& id) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = objects_.find(id);
    if (it == objects_.end()) return {ST_NOT_FOUND, 0, 0};
    ObjectEntry& e = it->second;
    if (e.refcount > 0) {
      e.pending_delete = true;
      return {ST_OK, 0, 1};  // size=1: deferred
    }
    arena_.Free(e.offset);
    objects_.erase(it);
    return {ST_OK, 0, 0};
  }

  Response Abort(const ObjectId& id,
                 std::unordered_map<ObjectId, uint64_t, IdHash>* held) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = objects_.find(id);
    if (it == objects_.end()) return {ST_NOT_FOUND, 0, 0};
    if (it->second.sealed) return {ST_ERR, 0, 0};
    arena_.Free(it->second.offset);
    objects_.erase(it);
    held->erase(id);
    return {ST_OK, 0, 0};
  }

  Response List(uint64_t max_ids, bool primaries, std::vector<uint8_t>* extra) {
    std::lock_guard<std::mutex> g(mu_);
    // Oldest-first so the spiller drains cold objects (LRU spill order).
    std::vector<std::pair<uint64_t, const ObjectId*>> order;
    for (auto& kv : objects_) {
      const ObjectEntry& e = kv.second;
      if (e.sealed && e.refcount == 0 && e.primary == primaries) {
        order.emplace_back(e.lru_tick, &kv.first);
      }
    }
    std::sort(order.begin(), order.end(),
              [](const auto& a, const auto& b) { return a.first < b.first; });
    uint64_t n = std::min<uint64_t>(order.size(), max_ids);
    extra->resize(n * 16);
    for (uint64_t i = 0; i < n; ++i) {
      memcpy(extra->data() + i * 16, order[i].second->b, 16);
    }
    return {static_cast<int64_t>(n), 0, 0};
  }

  struct Conn {
    std::thread thread;
    std::atomic<bool> done;
  };

  std::string path_;
  Arena arena_;
  int arena_fd_ = -1;
  uint8_t* base_ = nullptr;
  int listen_fd_ = -1;
  std::thread accept_thread_;
  std::thread prefault_thread_;
  std::vector<std::unique_ptr<Conn>> conn_threads_;
  std::vector<int> conn_fds_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::atomic<bool> stopping_{false};
  std::unordered_map<ObjectId, ObjectEntry, IdHash> objects_;
  uint64_t tick_ = 0;
};

// ------------------------------------------------------------------ client

class StoreClient {
 public:
  explicit StoreClient(const char* socket_path) {
    fd_ = socket(AF_UNIX, SOCK_STREAM | SOCK_CLOEXEC, 0);
    if (fd_ < 0) throw std::runtime_error("socket failed");
    struct sockaddr_un addr;
    memset(&addr, 0, sizeof(addr));
    addr.sun_family = AF_UNIX;
    snprintf(addr.sun_path, sizeof(addr.sun_path), "%s", socket_path);
    if (connect(fd_, reinterpret_cast<struct sockaddr*>(&addr),
                sizeof(addr)) != 0) {
      close(fd_);
      throw std::runtime_error("connect failed");
    }
    int arena_fd = -1;
    if (!RecvHandshake(fd_, &arena_fd, &capacity_)) {
      close(fd_);
      throw std::runtime_error("handshake failed");
    }
    base_ = static_cast<uint8_t*>(
        mmap(nullptr, capacity_, PROT_READ | PROT_WRITE, MAP_SHARED,
             arena_fd, 0));
    close(arena_fd);
    if (base_ == MAP_FAILED) {
      close(fd_);
      throw std::runtime_error("client mmap failed");
    }
  }

  ~StoreClient() {
    CloseSocket();
    if (prefault_thread_.joinable()) prefault_thread_.join();
    if (base_ != MAP_FAILED && base_ != nullptr) munmap(base_, capacity_);
  }

  void CloseSocket() {
    std::lock_guard<std::mutex> g(mu_);
    if (fd_ >= 0) {
      close(fd_);
      fd_ = -1;
    }
  }

  int64_t Call(uint8_t op, const uint8_t id[16], uint64_t a, uint64_t b,
               uint64_t* offset, uint64_t* size, uint8_t* extra,
               uint64_t extra_cap) {
    std::lock_guard<std::mutex> g(mu_);
    Request req;
    memset(&req, 0, sizeof(req));
    req.op = op;
    if (id != nullptr) memcpy(req.id, id, 16);
    req.a = a;
    req.b = b;
    if (!WriteFull(fd_, &req, sizeof(req))) return ST_ERR;
    Response rsp;
    if (!ReadFull(fd_, &rsp, sizeof(rsp))) return ST_ERR;
    if (offset != nullptr) *offset = rsp.offset;
    if (size != nullptr) *size = rsp.size;
    if (op == OP_LIST && rsp.status > 0) {
      uint64_t want = static_cast<uint64_t>(rsp.status) * 16;
      if (want > extra_cap || !ReadFull(fd_, extra, want)) return ST_ERR;
    }
    return rsp.status;
  }

  // Fault the whole arena into THIS process's page table in the
  // background (opt-in: RT_STORE_PREFAULT=1). Zero-fill of fresh shmem
  // pages runs at ~1 GB/s on the CI host no matter how it is triggered,
  // so per-allocation populate cannot beat plain write faults; paying
  // the cost ONCE per long-lived process in the background is the only
  // real win (first big put then runs at memcpy speed). Default-off
  // because populating object_store_memory_bytes on every cluster init
  // melts a test farm that starts hundreds of short-lived clusters.
  // madvise-only: POPULATE_WRITE installs pages/PTEs without writing
  // data, so it cannot race live objects (a touch loop would).
  void Prefault() {
#ifdef MADV_POPULATE_WRITE
    bool expected = false;
    if (!prefault_started_.compare_exchange_strong(expected, true)) return;
    prefault_thread_ = std::thread([this] {
      madvise(base_, capacity_, MADV_POPULATE_WRITE);
    });
#endif
  }

  uint8_t* base() const { return base_; }
  uint64_t capacity() const { return capacity_; }

 private:
  int fd_ = -1;
  uint8_t* base_ = nullptr;
  uint64_t capacity_ = 0;
  std::mutex mu_;
  std::atomic<bool> prefault_started_{false};
  std::thread prefault_thread_;
};

// ------------------------------------------------------- SPSC shm channels
//
// Compiled-DAG actor->actor edges (reference: mutable shared-memory objects
// src/ray/core_worker/experimental_mutable_object_manager.h:37 and
// python/ray/experimental/channel/shared_memory_channel.py:157).  A channel
// region lives INSIDE a sealed store object, so discovery/cleanup rides the
// normal object lifecycle; all per-message synchronization is client-side
// atomics on the mapped arena — zero server round trips on the data path.
//
// Single-producer single-consumer ring: `write_seq` counts published
// messages, `read_seq` consumed ones.  The writer waits while the ring is
// full (write_seq - read_seq == n_slots), publishes with a release store;
// the reader waits for write_seq > read_seq with an acquire load, and
// releases the slot by bumping read_seq.  Waiting spins briefly then
// sleeps 50us per poll (channel latency stays ~us-scale, idle channels
// cost nothing measurable).

constexpr uint64_t kChanMagic = 0x525443484e303153ULL;  // "RTCHN0:S"

struct ChanHeader {
  uint64_t magic;
  uint64_t slot_size;
  uint64_t n_slots;
  alignas(64) std::atomic<uint64_t> write_seq;
  alignas(64) std::atomic<uint64_t> read_seq;
  alignas(64) std::atomic<uint64_t> closed;
};

constexpr uint64_t kChanHeaderSize =
    (sizeof(ChanHeader) + kAlign - 1) & ~(kAlign - 1);

// Each slot carries an 8-byte length prefix.
uint64_t ChanSlotStride(uint64_t slot_size) {
  return (slot_size + 8 + kAlign - 1) & ~(kAlign - 1);
}

ChanHeader* ChanAt(StoreClient* cli, uint64_t offset) {
  auto* h = reinterpret_cast<ChanHeader*>(cli->base() + offset);
  return (h->magic == kChanMagic) ? h : nullptr;
}

uint8_t* ChanSlot(StoreClient* cli, uint64_t offset, ChanHeader* h,
                  uint64_t seq) {
  return cli->base() + offset + kChanHeaderSize +
         (seq % h->n_slots) * ChanSlotStride(h->slot_size);
}

// Wait until pred() or deadline. timeout_ms UINT64_MAX = forever.
// Three phases: spin (cheap, catches back-to-back traffic), sched_yield
// (hands the core to the peer — on loaded single-core hosts nanosleep's
// ~50us timer slack would dominate every hop), then a capped sleep so an
// idle channel doesn't burn the CPU.
template <typename Pred>
bool ChanWait(uint64_t timeout_ms, Pred pred) {
  for (int i = 0; i < 1024; ++i) {
    if (pred()) return true;
#if defined(__x86_64__)
    __builtin_ia32_pause();
#endif
  }
  // A short yield phase hands the core to the peer; keeping it short
  // matters on loaded single-core hosts, where N polling processes
  // yield-spinning against each other would thrash the scheduler.
  for (int i = 0; i < 64; ++i) {
    if (pred()) return true;
    std::this_thread::yield();
  }
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  while (!pred()) {
    if (timeout_ms != UINT64_MAX &&
        std::chrono::steady_clock::now() >= deadline) {
      return false;
    }
    std::this_thread::sleep_for(std::chrono::microseconds(100));
  }
  return true;
}

}  // namespace

// ------------------------------------------------------------------- C API

extern "C" {

void* rtps_server_start(const char* socket_path, uint64_t capacity) {
  try {
    return new StoreServer(socket_path, capacity);
  } catch (...) {
    return nullptr;
  }
}

void rtps_server_stop(void* srv) {
  auto* s = static_cast<StoreServer*>(srv);
  s->Stop();
  delete s;
}

void* rtps_client_connect(const char* socket_path) {
  try {
    return new StoreClient(socket_path);
  } catch (...) {
    return nullptr;
  }
}

void rtps_client_disconnect(void* cli) {
  delete static_cast<StoreClient*>(cli);
}


void rtps_client_prefault(void* cli) {
  static_cast<StoreClient*>(cli)->Prefault();
}

// Close only the control socket (server releases this client's refs) while
// LEAVING the arena mapped: user code may still hold zero-copy views into it,
// so unmapping would turn those into SIGSEGV. The mapping lives until process
// exit (plasma clients behave the same way). The handle leaks ~100 bytes.
void rtps_client_close_socket(void* cli) {
  static_cast<StoreClient*>(cli)->CloseSocket();
}

uint8_t* rtps_client_base(void* cli) {
  return static_cast<StoreClient*>(cli)->base();
}

int64_t rtps_create(void* cli, const uint8_t* id, uint64_t size,
                    uint64_t flags, uint64_t* offset) {
  return static_cast<StoreClient*>(cli)->Call(OP_CREATE, id, size, flags,
                                              offset, nullptr, nullptr, 0);
}

int64_t rtps_seal(void* cli, const uint8_t* id) {
  return static_cast<StoreClient*>(cli)->Call(OP_SEAL, id, 0, 0, nullptr,
                                              nullptr, nullptr, 0);
}

int64_t rtps_get(void* cli, const uint8_t* id, uint64_t timeout_ms,
                 uint64_t* offset, uint64_t* size) {
  return static_cast<StoreClient*>(cli)->Call(OP_GET, id, timeout_ms, 0,
                                              offset, size, nullptr, 0);
}

int64_t rtps_release(void* cli, const uint8_t* id) {
  return static_cast<StoreClient*>(cli)->Call(OP_RELEASE, id, 0, 0, nullptr,
                                              nullptr, nullptr, 0);
}

int64_t rtps_delete(void* cli, const uint8_t* id) {
  return static_cast<StoreClient*>(cli)->Call(OP_DELETE, id, 0, 0, nullptr,
                                              nullptr, nullptr, 0);
}

int64_t rtps_abort(void* cli, const uint8_t* id) {
  return static_cast<StoreClient*>(cli)->Call(OP_ABORT, id, 0, 0, nullptr,
                                              nullptr, nullptr, 0);
}

int64_t rtps_contains(void* cli, const uint8_t* id, uint64_t* size) {
  return static_cast<StoreClient*>(cli)->Call(OP_CONTAINS, id, 0, 0, nullptr,
                                              size, nullptr, 0);
}

int64_t rtps_stats(void* cli, uint64_t* used, uint64_t* capacity) {
  return static_cast<StoreClient*>(cli)->Call(OP_STATS, nullptr, 0, 0, used,
                                              capacity, nullptr, 0);
}

int64_t rtps_list(void* cli, uint64_t max_ids, uint64_t primaries,
                  uint8_t* ids_out) {
  return static_cast<StoreClient*>(cli)->Call(OP_LIST, nullptr, max_ids,
                                              primaries, nullptr, nullptr,
                                              ids_out, max_ids * 16);
}

int64_t rtps_free_info(void* cli, uint64_t* largest, uint64_t* total) {
  return static_cast<StoreClient*>(cli)->Call(OP_FREE_INFO, nullptr, 0, 0,
                                              largest, total, nullptr, 0);
}

// ---- channels (client-side atomics on the mapped arena; see ChanHeader)

uint64_t rtps_chan_region_size(uint64_t slot_size, uint64_t n_slots) {
  return kChanHeaderSize + n_slots * ChanSlotStride(slot_size);
}

int64_t rtps_chan_init(void* cli, uint64_t offset, uint64_t slot_size,
                       uint64_t n_slots) {
  if (slot_size == 0 || n_slots == 0) return ST_ERR;
  auto* h = reinterpret_cast<ChanHeader*>(
      static_cast<StoreClient*>(cli)->base() + offset);
  h->slot_size = slot_size;
  h->n_slots = n_slots;
  new (&h->write_seq) std::atomic<uint64_t>(0);
  new (&h->read_seq) std::atomic<uint64_t>(0);
  new (&h->closed) std::atomic<uint64_t>(0);
  std::atomic_thread_fence(std::memory_order_release);
  h->magic = kChanMagic;
  return ST_OK;
}

// Blocks while the ring is full. ST_FULL on timeout, ST_ERR on oversized
// payload / bad channel, ST_NOT_FOUND if the channel is closed. `kind` is
// the 1-byte message type prefix (written by the store so Python never
// has to concatenate kind+payload into a fresh buffer).
int64_t rtps_chan_send(void* cli, uint64_t offset, uint64_t kind,
                       const uint8_t* data, uint64_t len,
                       uint64_t timeout_ms) {
  auto* c = static_cast<StoreClient*>(cli);
  ChanHeader* h = ChanAt(c, offset);
  if (h == nullptr || len + 1 > h->slot_size) return ST_ERR;
  uint64_t w = h->write_seq.load(std::memory_order_relaxed);
  bool ok = ChanWait(timeout_ms, [&] {
    return h->closed.load(std::memory_order_relaxed) != 0 ||
           h->read_seq.load(std::memory_order_acquire) + h->n_slots > w;
  });
  if (h->closed.load(std::memory_order_relaxed) != 0) return ST_NOT_FOUND;
  if (!ok) return ST_FULL;
  uint8_t* slot = ChanSlot(c, offset, h, w);
  uint64_t total = len + 1;
  memcpy(slot, &total, 8);
  slot[8] = static_cast<uint8_t>(kind);
  if (len > 0) memcpy(slot + 9, data, len);
  h->write_seq.store(w + 1, std::memory_order_release);
  return ST_OK;
}

// Waits for the next message; on ST_OK *payload_offset/*len describe the
// slot IN the arena (zero-copy read). The slot stays owned by the reader
// until rtps_chan_recv_release. ST_TIMEOUT on timeout, ST_NOT_FOUND when
// the channel is closed and drained.
int64_t rtps_chan_recv_acquire(void* cli, uint64_t offset,
                               uint64_t timeout_ms, uint64_t* payload_offset,
                               uint64_t* len) {
  auto* c = static_cast<StoreClient*>(cli);
  ChanHeader* h = ChanAt(c, offset);
  if (h == nullptr) return ST_ERR;
  uint64_t r = h->read_seq.load(std::memory_order_relaxed);
  ChanWait(timeout_ms, [&] {
    return h->write_seq.load(std::memory_order_acquire) > r ||
           h->closed.load(std::memory_order_relaxed) != 0;
  });
  if (h->write_seq.load(std::memory_order_acquire) <= r) {
    // closed-and-drained reads as EOF; otherwise we simply timed out
    return h->closed.load(std::memory_order_relaxed) != 0 ? ST_NOT_FOUND
                                                          : ST_TIMEOUT;
  }
  uint8_t* slot = ChanSlot(c, offset, h, r);
  memcpy(len, slot, 8);
  *payload_offset = static_cast<uint64_t>(slot + 8 - c->base());
  return ST_OK;
}

// One-call receive for the hot path: wait, read the kind byte, copy the
// payload into `buf`, and release the slot — one FFI crossing instead of
// three. EXCEPTION: kind==1 (spilled object ref) returns WITHOUT
// releasing (out_released=0) — the caller must resolve the ref first and
// then call rtps_chan_recv_release, because the sender unpins the spilled
// object as soon as the slot recycles. ST_ERR if the payload exceeds cap.
int64_t rtps_chan_recv(void* cli, uint64_t offset, uint64_t timeout_ms,
                       uint8_t* buf, uint64_t cap, uint64_t* out_len,
                       uint64_t* out_kind, uint64_t* out_released) {
  auto* c = static_cast<StoreClient*>(cli);
  ChanHeader* h = ChanAt(c, offset);
  if (h == nullptr) return ST_ERR;
  uint64_t r = h->read_seq.load(std::memory_order_relaxed);
  ChanWait(timeout_ms, [&] {
    return h->write_seq.load(std::memory_order_acquire) > r ||
           h->closed.load(std::memory_order_relaxed) != 0;
  });
  if (h->write_seq.load(std::memory_order_acquire) <= r) {
    return h->closed.load(std::memory_order_relaxed) != 0 ? ST_NOT_FOUND
                                                          : ST_TIMEOUT;
  }
  uint8_t* slot = ChanSlot(c, offset, h, r);
  uint64_t total;
  memcpy(&total, slot, 8);
  if (total < 1) return ST_ERR;
  *out_kind = slot[8];
  *out_len = total - 1;
  if (*out_kind == 1) {  // spilled: hand back the slot un-released
    if (total - 1 > cap) return ST_ERR;
    memcpy(buf, slot + 9, total - 1);
    *out_released = 0;
    return ST_OK;
  }
  if (total - 1 > cap) return ST_ERR;
  if (total > 1) memcpy(buf, slot + 9, total - 1);
  h->read_seq.store(r + 1, std::memory_order_release);
  *out_released = 1;
  return ST_OK;
}

int64_t rtps_chan_recv_release(void* cli, uint64_t offset) {
  auto* c = static_cast<StoreClient*>(cli);
  ChanHeader* h = ChanAt(c, offset);
  if (h == nullptr) return ST_ERR;
  h->read_seq.fetch_add(1, std::memory_order_release);
  return ST_OK;
}

// Read the ring's true geometry from its header (attaching endpoints must
// NOT assume the creator used default sizes).
int64_t rtps_chan_geometry(void* cli, uint64_t offset, uint64_t* slot_size,
                           uint64_t* n_slots) {
  auto* c = static_cast<StoreClient*>(cli);
  ChanHeader* h = ChanAt(c, offset);
  if (h == nullptr) return ST_ERR;
  *slot_size = h->slot_size;
  *n_slots = h->n_slots;
  return ST_OK;
}

int64_t rtps_chan_close(void* cli, uint64_t offset) {
  auto* c = static_cast<StoreClient*>(cli);
  ChanHeader* h = ChanAt(c, offset);
  if (h == nullptr) return ST_ERR;
  h->closed.store(1, std::memory_order_release);
  return ST_OK;
}

}  // extern "C"
