// Native data loader: multi-threaded ordered file reader.
//
// Role of the reference's native IO paths (ray's C++ data plane reads file
// chunks off the Python thread; Ray Data's performance depends on it —
// SURVEY §2.1 lists the runtime around the compute path as native).  Python
// file loops serialize on the GIL; this loader keeps N reader threads ahead
// of the consumer and hands buffers back IN SUBMISSION ORDER so dataset
// iteration stays deterministic while IO overlaps compute — the host-side
// ingest path that keeps a TPU input pipeline fed.
//
// C API (rtdl_*) bound via ctypes in ray_tpu/data/_internal/native_loader.py.

#include <errno.h>
#include <fcntl.h>
#include <stdint.h>
#include <string.h>
#include <sys/stat.h>
#include <unistd.h>

#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <cstdlib>
#include <deque>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Job {
  uint64_t seq;
  std::string path;
};

struct Result {
  uint8_t* data = nullptr;  // malloc'd; freed by rtdl_release / destructor
  uint64_t size = 0;
  int error = 0;            // errno on failure
  std::string path;
};

class Loader {
 public:
  Loader(int num_threads, int max_ahead)
      : max_ahead_(max_ahead < 1 ? 1 : max_ahead) {
    if (num_threads < 1) num_threads = 1;
    for (int i = 0; i < num_threads; ++i) {
      threads_.emplace_back([this] { Work(); });
    }
  }

  ~Loader() {
    {
      std::lock_guard<std::mutex> g(mu_);
      stopping_ = true;
      cv_.notify_all();
    }
    for (auto& t : threads_) t.join();
    for (auto& kv : done_) std::free(kv.second.data);
  }

  uint64_t Submit(const char* path) {
    std::lock_guard<std::mutex> g(mu_);
    uint64_t seq = next_seq_++;
    queue_.push_back(Job{seq, path});
    cv_.notify_one();
    return seq;
  }

  // Blocks until the NEXT sequential result is ready (ordered delivery).
  // Returns 0 ok, -1 timeout, -2 nothing outstanding, >0 errno for the item.
  int Next(uint8_t** data, uint64_t* size, char* path_out, uint64_t path_cap,
           int64_t timeout_ms) {
    std::unique_lock<std::mutex> lk(mu_);
    if (consume_seq_ >= next_seq_) return -2;
    auto ready = [&] { return done_.count(consume_seq_) > 0; };
    if (timeout_ms < 0) {
      cv_done_.wait(lk, ready);
    } else if (!cv_done_.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                                  ready)) {
      return -1;
    }
    auto it = done_.find(consume_seq_);
    Result r = std::move(it->second);
    done_.erase(it);
    consume_seq_++;
    cv_.notify_all();  // reader threads may resume (look-ahead window)
    lk.unlock();
    if (path_out != nullptr && path_cap > 0) {
      snprintf(path_out, path_cap, "%s", r.path.c_str());
    }
    if (r.error != 0) {
      std::free(r.data);
      *data = nullptr;
      *size = 0;
      return r.error;
    }
    *data = r.data;  // ownership to caller (free via rtdl_release)
    *size = r.size;
    return 0;
  }

  uint64_t Pending() {
    std::lock_guard<std::mutex> g(mu_);
    return next_seq_ - consume_seq_;
  }

 private:
  void Work() {
    for (;;) {
      Job job;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [&] {
          // Look-ahead bound: don't read more than max_ahead_ items past
          // the consumer (keeps memory bounded on huge file lists).
          return stopping_ ||
                 (!queue_.empty() &&
                  queue_.front().seq < consume_seq_ + max_ahead_);
        });
        if (stopping_) return;
        job = std::move(queue_.front());
        queue_.pop_front();
      }
      Result r;
      r.path = job.path;
      ReadFile(job.path, &r);
      {
        std::lock_guard<std::mutex> g(mu_);
        done_[job.seq] = std::move(r);
        cv_done_.notify_all();
      }
    }
  }

  static void ReadFile(const std::string& path, Result* r) {
    int fd = open(path.c_str(), O_RDONLY | O_CLOEXEC);
    if (fd < 0) {
      r->error = errno ? errno : EIO;
      return;
    }
    struct stat st;
    if (fstat(fd, &st) != 0) {
      r->error = errno ? errno : EIO;
      close(fd);
      return;
    }
    // st_size is only a capacity HINT: virtual files (procfs/sysfs, some
    // FUSE) report 0 yet stream real content, and files can grow between
    // stat and read — always read to EOF, growing the buffer as needed.
    uint64_t cap = static_cast<uint64_t>(st.st_size);
    if (cap < 4096) cap = 4096;
    uint8_t* buf = static_cast<uint8_t*>(std::malloc(cap));
    if (buf == nullptr) {
      r->error = ENOMEM;
      close(fd);
      return;
    }
    uint64_t off = 0;
    for (;;) {
      if (off == cap) {
        cap *= 2;
        uint8_t* grown = static_cast<uint8_t*>(std::realloc(buf, cap));
        if (grown == nullptr) {
          r->error = ENOMEM;
          break;
        }
        buf = grown;
      }
      ssize_t n = read(fd, buf + off, cap - off);
      if (n < 0) {
        if (errno == EINTR) continue;
        r->error = errno;
        break;
      }
      if (n == 0) break;  // EOF
      off += static_cast<uint64_t>(n);
    }
    close(fd);
    if (r->error != 0) {
      std::free(buf);
      return;
    }
    r->data = buf;
    r->size = off;
  }

  int max_ahead_;
  std::vector<std::thread> threads_;
  std::mutex mu_;
  std::condition_variable cv_;       // reader threads wait here
  std::condition_variable cv_done_;  // consumer waits here
  std::deque<Job> queue_;
  std::map<uint64_t, Result> done_;
  uint64_t next_seq_ = 0;
  uint64_t consume_seq_ = 0;
  bool stopping_ = false;
};

}  // namespace

extern "C" {

void* rtdl_create(int num_threads, int max_ahead) {
  return new Loader(num_threads, max_ahead);
}

void rtdl_destroy(void* h) { delete static_cast<Loader*>(h); }

uint64_t rtdl_submit(void* h, const char* path) {
  return static_cast<Loader*>(h)->Submit(path);
}

int rtdl_next(void* h, uint8_t** data, uint64_t* size, char* path_out,
              uint64_t path_cap, int64_t timeout_ms) {
  return static_cast<Loader*>(h)->Next(data, size, path_out, path_cap,
                                       timeout_ms);
}

void rtdl_release(uint8_t* data) { std::free(data); }

uint64_t rtdl_pending(void* h) { return static_cast<Loader*>(h)->Pending(); }

}  // extern "C"
