"""Reinforcement-learning library.

Reference counterpart: RLlib new API stack (ray: rllib/ — Algorithm
algorithms/algorithm.py:213, AlgorithmConfig algorithm_config.py, EnvRunner
actors env/single_agent_env_runner.py:124, RLModule core/rl_module/,
Learner/LearnerGroup core/learner/) rebuilt as JAX: the RLModule is a pure
params-pytree + apply functions, the Learner's update is one jit with
donated buffers, and multi-learner data parallelism is a mesh sharding
(pmap-style) instead of DDP.
"""

from ray_tpu.rllib.algorithm import Algorithm  # noqa: F401
from ray_tpu.rllib.algorithm_config import AlgorithmConfig  # noqa: F401
from ray_tpu.rllib.dataflow import (  # noqa: F401
    DecoupledDataflow,
    RolloutFleet,
    SampleQueueActor,
)
from ray_tpu.rllib.env import MultiAgentEnv  # noqa: F401
from ray_tpu.rllib.episode import SingleAgentEpisode  # noqa: F401
from ray_tpu.rllib.multi_agent import (  # noqa: F401
    MultiAgentEpisode,
    MultiAgentPPO,
    MultiAgentPPOConfig,
)
from ray_tpu.rllib.replay_buffer import (  # noqa: F401
    PrioritizedReplayBuffer,
    ReplayBuffer,
)

__all__ = [
    "Algorithm",
    "AlgorithmConfig",
    "DecoupledDataflow",
    "MultiAgentEnv",
    "MultiAgentEpisode",
    "MultiAgentPPO",
    "MultiAgentPPOConfig",
    "PrioritizedReplayBuffer",
    "ReplayBuffer",
    "RolloutFleet",
    "SampleQueueActor",
    "SingleAgentEpisode",
]
