"""Atari / image-observation env helpers.

Reference: ray rllib's Atari pipeline (benchmark_atari_ppo.py builds envs
with gymnasium's AtariPreprocessing: grayscale, 84x84 resize, frame-skip 4,
max-pooled frames) + the frame-stacking env-to-module connector
(rllib/connectors/env_to_module/frame_stacking.py). Here preprocessing is
env-side gymnasium wrappers: the stacked uint8 frames flow straight into
the jitted CNN forward, which normalizes on-device (a host-side float32
conversion would quadruple the sample-transport bytes).

Real Atari needs ale_py (import-gated, like every optional integration);
`SyntheticImageEnv` provides a CPU-only image env with learnable structure
for CI and benchmarks on machines without ROMs.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def make_atari_env(env_id: str, *, frame_stack: int = 4,
                   screen_size: int = 84, frameskip: int = 4,
                   env_config: Optional[dict] = None):
    """Standard Atari pipeline: AtariPreprocessing + frame stack.

    -> obs uint8 [screen_size, screen_size, frame_stack]
    """
    import gymnasium as gym

    try:
        import ale_py  # noqa: F401 — registers ALE-prefixed envs
        gym.register_envs(ale_py)
    except ImportError as e:
        raise ImportError(
            "Atari environments require the 'ale-py' package") from e
    env = gym.make(env_id, frameskip=1, **(env_config or {}))
    env = gym.wrappers.AtariPreprocessing(
        env, frame_skip=frameskip, screen_size=screen_size,
        grayscale_obs=True, grayscale_newaxis=False, scale_obs=False)
    env = gym.wrappers.FrameStackObservation(env, stack_size=frame_stack)
    # FrameStackObservation emits [stack, H, W]; the CNN expects
    # channels-last [H, W, stack].
    env = gym.wrappers.TransformObservation(
        env, lambda obs: np.moveaxis(obs, 0, -1),
        observation_space=gym.spaces.Box(
            0, 255, (screen_size, screen_size, frame_stack), np.uint8))
    return env


def _gym_env_base():
    import gymnasium as gym

    return gym.Env


class SyntheticImageEnv(_gym_env_base()):
    """Tiny image-obs env with learnable optimal policy, for CI/bench.

    Each step shows a HxWx1 uint8 image with one bright quadrant; the
    action matching the quadrant index scores +1, else 0. Optimal return
    over an episode of length T is T. A conv policy must actually read the
    image to beat the 1/num_quadrants random baseline — this is the
    CPU-testable stand-in for Atari learning regressions (reference uses
    tuned_examples thresholds the same way).
    """

    metadata = {"render_modes": []}

    def __init__(self, size: int = 16, episode_len: int = 32,
                 seed: Optional[int] = None):
        import gymnasium as gym

        self.size = size
        self.episode_len = episode_len
        self.observation_space = gym.spaces.Box(
            0, 255, (size, size, 1), np.uint8)
        self.action_space = gym.spaces.Discrete(4)
        self._rng = np.random.default_rng(seed)
        self._t = 0
        self._target = 0

    def _obs(self):
        img = np.zeros((self.size, self.size, 1), np.uint8)
        h = self.size // 2
        r, c = divmod(self._target, 2)
        img[r * h:(r + 1) * h, c * h:(c + 1) * h, 0] = 255
        return img

    def reset(self, *, seed=None, options=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._t = 0
        self._target = int(self._rng.integers(4))
        return self._obs(), {}

    def step(self, action):
        reward = 1.0 if int(action) == self._target else 0.0
        self._t += 1
        self._target = int(self._rng.integers(4))
        terminated = False
        truncated = self._t >= self.episode_len
        return self._obs(), reward, terminated, truncated, {}

    def close(self):
        pass


def register_synthetic_env() -> str:
    """Register ray_tpu/SyntheticImage-v0 with gymnasium (idempotent);
    returns the env id. make_env auto-registers it on first use."""
    import gymnasium as gym

    env_id = "ray_tpu/SyntheticImage-v0"
    if env_id not in gym.registry:
        gym.register(id=env_id, entry_point=SyntheticImageEnv)
    return env_id
