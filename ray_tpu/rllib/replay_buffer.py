"""Replay buffers (reference: ray rllib/utils/replay_buffers/replay_buffer.py:66
uniform ring buffer; prioritized_episode variant — here a proportional
prioritized buffer with sum-tree-free numpy sampling, adequate to ~1M)."""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np


class ReplayBuffer:
    def __init__(self, capacity: int = 100_000, seed: Optional[int] = None):
        self.capacity = capacity
        self._storage: List[Dict[str, Any]] = []
        self._next_idx = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return len(self._storage)

    def add(self, transition: Dict[str, Any]) -> None:
        if len(self._storage) < self.capacity:
            self._storage.append(transition)
        else:
            self._storage[self._next_idx] = transition
        self._next_idx = (self._next_idx + 1) % self.capacity

    def add_batch(self, batch: Dict[str, np.ndarray]) -> None:
        n = len(next(iter(batch.values())))
        for i in range(n):
            self.add({k: v[i] for k, v in batch.items()})

    def sample(self, num_items: int) -> Dict[str, np.ndarray]:
        idx = self._rng.integers(0, len(self._storage), size=num_items)
        return self._stack([self._storage[i] for i in idx])

    @staticmethod
    def _stack(items: List[Dict[str, Any]]) -> Dict[str, np.ndarray]:
        keys = items[0].keys()
        return {k: np.stack([it[k] for it in items]) for k in keys}


class PrioritizedReplayBuffer(ReplayBuffer):
    def __init__(self, capacity: int = 100_000, alpha: float = 0.6,
                 beta: float = 0.4, seed: Optional[int] = None):
        super().__init__(capacity, seed)
        self.alpha = alpha
        self.beta = beta
        self._priorities = np.zeros(capacity, dtype=np.float64)
        self._max_priority = 1.0

    def add(self, transition: Dict[str, Any]) -> None:
        idx = self._next_idx
        super().add(transition)
        self._priorities[idx] = self._max_priority ** self.alpha

    def sample(self, num_items: int) -> Dict[str, np.ndarray]:
        n = len(self._storage)
        prios = self._priorities[:n]
        probs = prios / prios.sum()
        idx = self._rng.choice(n, size=num_items, p=probs)
        weights = (n * probs[idx]) ** (-self.beta)
        weights /= weights.max()
        batch = self._stack([self._storage[i] for i in idx])
        batch["weights"] = weights.astype(np.float32)
        batch["batch_indexes"] = idx
        return batch

    def update_priorities(self, indexes: np.ndarray,
                          priorities: np.ndarray) -> None:
        priorities = np.abs(priorities) + 1e-6
        self._priorities[indexes] = priorities ** self.alpha
        self._max_priority = max(self._max_priority, priorities.max())
