"""Episode container (reference: ray rllib/env/single_agent_episode.py —
append-per-step storage, cut on done, to-batch conversion)."""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np


class SingleAgentEpisode:
    def __init__(self):
        self.obs: List[np.ndarray] = []
        self.actions: List[Any] = []
        self.rewards: List[float] = []
        self.infos: List[dict] = []
        self.extra: Dict[str, List[Any]] = {}
        self.is_done = False
        self.is_truncated = False
        # True for fragments cut at a sample() boundary (the episode keeps
        # running in the env) — distinct from ENV truncation (TimeLimit),
        # whose return is complete and counts toward episode_return_mean.
        self.is_boundary_fragment = False

    def add_env_reset(self, obs) -> None:
        self.obs.append(np.asarray(obs))

    def add_env_step(self, obs, action, reward, *, terminated=False,
                     truncated=False, info=None, **extra) -> None:
        self.obs.append(np.asarray(obs))
        self.actions.append(action)
        self.rewards.append(float(reward))
        self.infos.append(info or {})
        for k, v in extra.items():
            self.extra.setdefault(k, []).append(v)
        self.is_done = bool(terminated)
        self.is_truncated = bool(truncated)

    def __len__(self) -> int:
        return len(self.actions)

    @property
    def total_reward(self) -> float:
        return float(sum(self.rewards))

    def to_batch(self) -> Dict[str, np.ndarray]:
        batch = {
            "obs": np.stack(self.obs[:-1]) if len(self.obs) > 1
            else np.empty((0,)),
            "next_obs": np.stack(self.obs[1:]) if len(self.obs) > 1
            else np.empty((0,)),
            "actions": np.asarray(self.actions),
            "rewards": np.asarray(self.rewards, dtype=np.float32),
            "terminateds": np.zeros(len(self.actions), dtype=bool),
            "truncateds": np.zeros(len(self.actions), dtype=bool),
        }
        if self.actions:
            batch["terminateds"][-1] = self.is_done
            batch["truncateds"][-1] = self.is_truncated
        for k, v in self.extra.items():
            batch[k] = np.asarray(v)
        return batch
