"""Algorithm base (reference: ray rllib/algorithms/algorithm.py:213 —
a Tune Trainable whose step() (:818) runs one training_step and returns a
result dict; save/restore via checkpoint dirs)."""

from __future__ import annotations

import os
import pickle
from collections import deque
from typing import Any, Dict, Optional

from ray_tpu.rllib.algorithm_config import AlgorithmConfig


class Algorithm:
    def __init__(self, config: AlgorithmConfig):
        from ray_tpu.rllib.callbacks import make_callbacks

        self.config = config
        self.iteration = 0
        self._num_env_steps_sampled_lifetime = 0
        self._episode_returns = deque(maxlen=100)
        self.callbacks = make_callbacks(
            getattr(config, "callbacks_class", None))
        self.setup(config)
        if self.callbacks is not None:
            self.callbacks.on_algorithm_init(algorithm=self)

    # -- subclass API --------------------------------------------------------

    def setup(self, config: AlgorithmConfig) -> None:
        raise NotImplementedError

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    # -- Trainable-style API -------------------------------------------------

    def train(self) -> Dict[str, Any]:
        self.iteration += 1
        result = self.training_step()
        result.setdefault("training_iteration", self.iteration)
        result.setdefault("num_env_steps_sampled_lifetime",
                          self._num_env_steps_sampled_lifetime)
        if self._episode_returns:
            result.setdefault(
                "episode_return_mean",
                sum(self._episode_returns) / len(self._episode_returns))
        if self.callbacks is not None:
            self.callbacks.on_train_result(algorithm=self, result=result)
        return result

    def _record_episodes(self, episodes) -> None:
        for ep in episodes:
            if self.callbacks is not None and (
                    ep.is_done or (getattr(ep, "is_truncated", False)
                                   and not getattr(ep,
                                                   "is_boundary_fragment",
                                                   False))):
                # boundary fragments are still-running episodes cut at a
                # sample() edge — not ends
                self.callbacks.on_episode_end(episode=ep, algorithm=self)
            self._num_env_steps_sampled_lifetime += len(ep)
            # terminated AND env-truncated (TimeLimit) episodes have a
            # complete return; boundary fragments do not
            if ep.is_done or (ep.is_truncated
                              and not getattr(ep, "is_boundary_fragment",
                                              False)):
                self._episode_returns.append(ep.total_reward)

    def get_state(self) -> Dict[str, Any]:
        return {"iteration": self.iteration,
                "num_env_steps_sampled_lifetime":
                    self._num_env_steps_sampled_lifetime,
                "policy_version": getattr(self, "policy_version", 0)}

    def set_state(self, state: Dict[str, Any]) -> None:
        self.iteration = state.get("iteration", 0)
        self._num_env_steps_sampled_lifetime = state.get(
            "num_env_steps_sampled_lifetime", 0)
        if hasattr(self, "policy_version"):
            # restored learner progress keeps its version monotonic so a
            # checkpoint-restart can't re-accept pre-restart-stale batches
            self.policy_version = state.get("policy_version",
                                            self.policy_version)

    def save(self, checkpoint_dir: str) -> str:
        os.makedirs(checkpoint_dir, exist_ok=True)
        with open(os.path.join(checkpoint_dir, "algo_state.pkl"), "wb") as f:
            pickle.dump(self.get_state(), f)
        if self.callbacks is not None:
            self.callbacks.on_checkpoint_saved(
                algorithm=self, checkpoint_dir=checkpoint_dir)
        return checkpoint_dir

    def restore(self, checkpoint_dir: str) -> None:
        with open(os.path.join(checkpoint_dir, "algo_state.pkl"), "rb") as f:
            self.set_state(pickle.load(f))
        if self.callbacks is not None:
            self.callbacks.on_checkpoint_loaded(
                algorithm=self, checkpoint_dir=checkpoint_dir)

    def stop(self) -> None:
        pass

    @staticmethod
    def _env_spaces(env_id: str, env_config: Optional[dict] = None):
        """(obs, num_actions) for a discrete-action env — obs is a flat dim
        (int) for vector observations or a shape tuple for image (ndim>1)
        observations."""
        from ray_tpu.rllib.env_runner import make_env

        env = make_env(env_id, env_config)
        try:
            shape = env.observation_space.shape
            obs = tuple(int(d) for d in shape) if len(shape) > 1 \
                else int(shape[0])
            num_actions = int(env.action_space.n)
        finally:
            env.close()
        return obs, num_actions

    def _actor_critic_spec(self, config) -> dict:
        """Module spec for actor-critic algorithms, built by the model
        catalog from the env's observation/action spaces (reference:
        rllib core/models/catalog.py — MLP/CNN/flatten/one-hot/dict-concat
        encoder selection)."""
        from ray_tpu.rllib.catalog import Catalog

        return Catalog.from_env(config.env, config.env_config,
                                config.model).actor_critic_spec()

    def _q_module_spec(self, config) -> dict:
        """Module spec for Q-learning algorithms, via the catalog."""
        from ray_tpu.rllib.catalog import Catalog

        return Catalog.from_env(config.env, config.env_config,
                                config.model).q_spec()
