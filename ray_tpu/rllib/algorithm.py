"""Algorithm base (reference: ray rllib/algorithms/algorithm.py:213 —
a Tune Trainable whose step() (:818) runs one training_step and returns a
result dict; save/restore via checkpoint dirs)."""

from __future__ import annotations

import os
import pickle
from collections import deque
from typing import Any, Dict, Optional

from ray_tpu.rllib.algorithm_config import AlgorithmConfig


class Algorithm:
    def __init__(self, config: AlgorithmConfig):
        self.config = config
        self.iteration = 0
        self._num_env_steps_sampled_lifetime = 0
        self._episode_returns = deque(maxlen=100)
        self.setup(config)

    # -- subclass API --------------------------------------------------------

    def setup(self, config: AlgorithmConfig) -> None:
        raise NotImplementedError

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    # -- Trainable-style API -------------------------------------------------

    def train(self) -> Dict[str, Any]:
        self.iteration += 1
        result = self.training_step()
        result.setdefault("training_iteration", self.iteration)
        result.setdefault("num_env_steps_sampled_lifetime",
                          self._num_env_steps_sampled_lifetime)
        if self._episode_returns:
            result.setdefault(
                "episode_return_mean",
                sum(self._episode_returns) / len(self._episode_returns))
        return result

    def _record_episodes(self, episodes) -> None:
        for ep in episodes:
            self._num_env_steps_sampled_lifetime += len(ep)
            if ep.is_done:
                self._episode_returns.append(ep.total_reward)

    def get_state(self) -> Dict[str, Any]:
        return {"iteration": self.iteration}

    def set_state(self, state: Dict[str, Any]) -> None:
        self.iteration = state.get("iteration", 0)

    def save(self, checkpoint_dir: str) -> str:
        os.makedirs(checkpoint_dir, exist_ok=True)
        with open(os.path.join(checkpoint_dir, "algo_state.pkl"), "wb") as f:
            pickle.dump(self.get_state(), f)
        return checkpoint_dir

    def restore(self, checkpoint_dir: str) -> None:
        with open(os.path.join(checkpoint_dir, "algo_state.pkl"), "rb") as f:
            self.set_state(pickle.load(f))

    def stop(self) -> None:
        pass

    @staticmethod
    def _env_spaces(env_id: str, env_config: Optional[dict] = None):
        """(obs_dim, num_actions) for a discrete-action env."""
        import gymnasium as gym

        env = gym.make(env_id, **(env_config or {}))
        try:
            obs_dim = int(env.observation_space.shape[0])
            num_actions = int(env.action_space.n)
        finally:
            env.close()
        return obs_dim, num_actions
