"""Offline RL I/O + estimators.

Reference counterpart: ray rllib/offline/ — JsonWriter (json_writer.py),
JsonReader (json_reader.py:221), InputReader (input_reader.py:18),
off-policy estimators (offline/estimators/).
"""

from ray_tpu.rllib.offline.estimators import (  # noqa: F401
    DirectMethod,
    ImportanceSampling,
    WeightedImportanceSampling,
)
from ray_tpu.rllib.offline.io import (  # noqa: F401
    JsonReader,
    JsonWriter,
    load_episode_batches,
)

__all__ = [
    "DirectMethod",
    "ImportanceSampling",
    "JsonReader",
    "JsonWriter",
    "WeightedImportanceSampling",
    "load_episode_batches",
]
