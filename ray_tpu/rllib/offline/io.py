"""Episode JSON I/O (reference: ray rllib/offline/json_writer.py,
json_reader.py:221 — SampleBatch-rows-as-JSON-lines; here each line is one
episode batch, the natural unit for MC-return computation in MARWIL).

Line schema: {"obs": [[...]], "next_obs": [[...]], "actions": [...],
"rewards": [...], "terminateds": [...], "truncateds": [...],
optional "action_logp": [...]} — arrays as nested lists.
"""

from __future__ import annotations

import glob as _glob
import json
import os
from typing import Dict, Iterator, List, Optional

import numpy as np

_ARRAY_KEYS = ("obs", "next_obs", "actions", "rewards", "terminateds",
               "truncateds", "action_logp")


def _expand(paths) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(_glob.glob(os.path.join(p, "*.json"))))
        elif any(c in p for c in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no offline data matched {paths}")
    return out


class JsonWriter:
    """Append episode batches to a JSON-lines file."""

    def __init__(self, path: str, max_file_size: int = 64 * 1024 * 1024):
        os.makedirs(path, exist_ok=True)
        self._dir = path
        self._max = max_file_size
        self._index = 0
        self._fp = None
        self._open_next()

    def _open_next(self):
        if self._fp:
            self._fp.close()
        name = os.path.join(self._dir, f"episodes-{self._index:05d}.json")
        self._index += 1
        self._fp = open(name, "w")

    def write(self, batch: Dict[str, np.ndarray]) -> None:
        row = {}
        for k, v in batch.items():
            if k in _ARRAY_KEYS or isinstance(v, np.ndarray):
                row[k] = np.asarray(v).tolist()
            else:
                row[k] = v
        self._fp.write(json.dumps(row) + "\n")
        self._fp.flush()
        if self._fp.tell() > self._max:
            self._open_next()

    def write_episode(self, episode) -> None:
        self.write(episode.to_batch())

    def close(self):
        if self._fp:
            self._fp.close()
            self._fp = None

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()


def _decode(row: dict) -> Dict[str, np.ndarray]:
    out = {}
    for k, v in row.items():
        if isinstance(v, list):
            arr = np.asarray(v)
            if arr.dtype == np.float64:
                arr = arr.astype(np.float32)
            out[k] = arr
        else:
            out[k] = v
    return out


class JsonReader:
    """Iterate episode batches from JSON-lines files; next() cycles."""

    def __init__(self, paths):
        self._files = _expand(paths)
        self._iter: Optional[Iterator] = None

    def read_all(self) -> List[Dict[str, np.ndarray]]:
        out = []
        for f in self._files:
            with open(f) as fp:
                for line in fp:
                    line = line.strip()
                    if line:
                        out.append(_decode(json.loads(line)))
        return out

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        for f in self._files:
            with open(f) as fp:
                for line in fp:
                    line = line.strip()
                    if line:
                        yield _decode(json.loads(line))

    def next(self) -> Dict[str, np.ndarray]:
        if self._iter is None:
            self._iter = iter(self)
        try:
            return next(self._iter)
        except StopIteration:
            self._iter = iter(self)
            return next(self._iter)


def load_episode_batches(input_) -> List[Dict[str, np.ndarray]]:
    """config.input_ (paths / dirs / list of either, or a list of
    already-decoded episode batch dicts) → list of episode batches."""
    if isinstance(input_, list) and input_ and isinstance(input_[0], dict):
        return [
            {k: np.asarray(v) for k, v in b.items()} for b in input_]
    return JsonReader(input_).read_all()
