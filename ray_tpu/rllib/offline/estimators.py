"""Off-policy estimators (reference: ray rllib/offline/estimators/ —
importance_sampling.py, weighted_importance_sampling.py, direct_method.py).

Each estimator scores a target policy on behavior-policy episodes. Episode
batches must carry "action_logp" (behavior log-probs); the target policy is
a callable (obs_batch, actions) -> target log-probs.
"""

from __future__ import annotations

from typing import Callable, Dict, List

import numpy as np

TargetLogP = Callable[[np.ndarray, np.ndarray], np.ndarray]


def _episode_ratios(batch: Dict[str, np.ndarray],
                    target_logp: TargetLogP, gamma: float):
    """-> (per-step cumulative IS ratios, discounted rewards)."""
    logp_b = np.asarray(batch["action_logp"], dtype=np.float64)
    logp_t = np.asarray(
        target_logp(batch["obs"], batch["actions"]), dtype=np.float64)
    step_ratio = np.exp(np.clip(logp_t - logp_b, -20, 20))
    cum_ratio = np.cumprod(step_ratio)
    discounts = gamma ** np.arange(len(step_ratio))
    rewards = np.asarray(batch["rewards"], dtype=np.float64)
    return cum_ratio, discounts * rewards


class ImportanceSampling:
    """Per-episode trajectory-IS estimate of the target policy's return."""

    def __init__(self, gamma: float = 1.0):
        self.gamma = gamma

    def estimate(self, batches: List[Dict[str, np.ndarray]],
                 target_logp: TargetLogP) -> Dict[str, float]:
        values = []
        for b in batches:
            cum_ratio, disc_r = _episode_ratios(b, target_logp, self.gamma)
            values.append(float(np.sum(cum_ratio * disc_r)))
        v = np.asarray(values)
        return {"v_target": float(v.mean()),
                "v_target_std": float(v.std()),
                "num_episodes": len(values)}


class WeightedImportanceSampling:
    """Self-normalized (weighted) per-step IS — lower variance than IS."""

    def __init__(self, gamma: float = 1.0):
        self.gamma = gamma

    def estimate(self, batches: List[Dict[str, np.ndarray]],
                 target_logp: TargetLogP) -> Dict[str, float]:
        # per-step normalization across episodes (aligned by timestep)
        max_t = max(len(b["rewards"]) for b in batches)
        ratio_sum = np.zeros(max_t)
        counts = np.zeros(max_t)
        per_ep = []
        for b in batches:
            cum_ratio, disc_r = _episode_ratios(b, target_logp, self.gamma)
            per_ep.append((cum_ratio, disc_r))
            ratio_sum[:len(cum_ratio)] += cum_ratio
            counts[:len(cum_ratio)] += 1
        w_mean = ratio_sum / np.maximum(counts, 1)
        values = [float(np.sum(cum_ratio / np.maximum(
            w_mean[:len(cum_ratio)], 1e-12) * disc_r))
            for cum_ratio, disc_r in per_ep]
        v = np.asarray(values)
        return {"v_target": float(v.mean()),
                "v_target_std": float(v.std()),
                "num_episodes": len(values)}


class DirectMethod:
    """Model-based estimate: a fitted value function evaluated at episode
    starts (the caller supplies v_fn, e.g. a MARWIL critic)."""

    def __init__(self, v_fn: Callable[[np.ndarray], np.ndarray]):
        self.v_fn = v_fn

    def estimate(self, batches: List[Dict[str, np.ndarray]],
                 target_logp: TargetLogP = None) -> Dict[str, float]:
        starts = np.stack([np.asarray(b["obs"][0]) for b in batches])
        v = np.asarray(self.v_fn(starts), dtype=np.float64).ravel()
        return {"v_target": float(v.mean()),
                "v_target_std": float(v.std()),
                "num_episodes": len(batches)}
