"""RLlib sampling/training benchmarks (reference:
rllib/benchmarks/ppo/benchmark_atari_ppo.py — env-steps/sec with the conv
policy in the loop). Run: python -m ray_tpu.rllib.benchmarks [env_id]."""

from __future__ import annotations

import json
import time
from typing import Optional


def benchmark_env_steps(env_id: Optional[str] = None, *, num_envs: int = 8,
                        steps: int = 256, conv_filters=None,
                        hiddens=(256,)) -> dict:
    """env-steps/sec through EnvRunner.sample with a jitted conv policy."""
    import jax

    from ray_tpu.rllib.env_runner import EnvRunner, make_env

    if env_id is None:
        from ray_tpu.rllib.atari import register_synthetic_env

        env_id = register_synthetic_env()
        conv_filters = conv_filters or ((16, 3, 2), (32, 3, 2))
    conv_filters = conv_filters or ((32, 8, 4), (64, 4, 2), (64, 3, 1))
    probe = make_env(env_id)
    obs_shape = tuple(probe.observation_space.shape)
    num_actions = int(probe.action_space.n)
    probe.close()
    spec = {"obs_shape": obs_shape, "num_actions": num_actions,
            "module_class": "ray_tpu.rllib.rl_module:ConvActorCriticModule",
            "conv_filters": conv_filters, "hiddens": tuple(hiddens)}
    runner = EnvRunner({"env": env_id, "num_envs_per_env_runner": num_envs,
                        "rollout_fragment_length": steps, "seed": 0}, spec)
    runner.set_weights(runner.module.init(jax.random.PRNGKey(0)))
    runner.sample(num_steps=8)  # compile
    t0 = time.perf_counter()
    runner.sample(num_steps=steps)
    dt = time.perf_counter() - t0
    runner.stop()
    return {
        "metric": "rllib_env_steps_per_sec",
        "value": round(num_envs * steps / dt, 1),
        "unit": "env-steps/s",
        "detail": {"env": env_id, "num_envs": num_envs,
                   "obs_shape": list(obs_shape)},
    }


if __name__ == "__main__":
    import sys

    env = sys.argv[1] if len(sys.argv) > 1 else None
    print(json.dumps(benchmark_env_steps(env)))
