"""RLlib sampling/training benchmarks (reference:
rllib/benchmarks/ppo/benchmark_atari_ppo.py — env-steps/sec with the conv
policy in the loop). Run: python -m ray_tpu.rllib.benchmarks [env_id]."""

from __future__ import annotations

import json
import os
import time
from typing import Optional


def benchmark_env_steps(env_id: Optional[str] = None, *, num_envs: int = 8,
                        steps: int = 256, conv_filters=None,
                        hiddens=(256,)) -> dict:
    """env-steps/sec through EnvRunner.sample with a jitted conv policy."""
    import jax

    from ray_tpu.rllib.env_runner import EnvRunner, make_env

    if env_id is None:
        from ray_tpu.rllib.atari import register_synthetic_env

        env_id = register_synthetic_env()
        conv_filters = conv_filters or ((16, 3, 2), (32, 3, 2))
    conv_filters = conv_filters or ((32, 8, 4), (64, 4, 2), (64, 3, 1))
    probe = make_env(env_id)
    obs_shape = tuple(probe.observation_space.shape)
    num_actions = int(probe.action_space.n)
    probe.close()
    spec = {"obs_shape": obs_shape, "num_actions": num_actions,
            "module_class": "ray_tpu.rllib.rl_module:ConvActorCriticModule",
            "conv_filters": conv_filters, "hiddens": tuple(hiddens)}
    runner = EnvRunner({"env": env_id, "num_envs_per_env_runner": num_envs,
                        "rollout_fragment_length": steps, "seed": 0}, spec)
    runner.set_weights(runner.module.init(jax.random.PRNGKey(0)))
    runner.sample(num_steps=8)  # compile
    t0 = time.perf_counter()
    runner.sample(num_steps=steps)
    dt = time.perf_counter() - t0
    runner.stop()
    return {
        "metric": "rllib_env_steps_per_sec",
        "value": round(num_envs * steps / dt, 1),
        "unit": "env-steps/s",
        "detail": {"env": env_id, "num_envs": num_envs,
                   "obs_shape": list(obs_shape)},
    }


def benchmark_decoupled(worker_counts=(1, 2), *, env_id: Optional[str] = None,
                        num_envs: int = 4, fragment: int = 64,
                        duration_s: float = 8.0) -> dict:
    """Decoupled-dataflow env-steps/sec vs rollout-worker count: the
    fleet pushes through the bounded sample queue while a learner-side
    consumer drains continuously — the number is CONSUMED steps/sec at
    the learner (what training actually sees), not raw sampling rate.
    Reported at >=2 worker counts so the trajectory carries a measured
    scaling curve instead of a single-number plateau."""
    import jax

    import ray_tpu
    from ray_tpu.rllib.dataflow import DecoupledDataflow
    from ray_tpu.rllib.env_runner import make_env

    if env_id is None:
        from ray_tpu.rllib.atari import register_synthetic_env

        env_id = register_synthetic_env()
    conv_filters = ((16, 3, 2), (32, 3, 2))
    probe = make_env(env_id)
    obs_shape = tuple(probe.observation_space.shape)
    num_actions = int(probe.action_space.n)
    probe.close()
    spec = {"obs_shape": obs_shape, "num_actions": num_actions,
            "module_class": "ray_tpu.rllib.rl_module:ConvActorCriticModule",
            "conv_filters": conv_filters, "hiddens": (256,)}
    from ray_tpu.rllib.rl_module import resolve_module

    weights = resolve_module(spec).init(jax.random.PRNGKey(0))
    per_worker = {}
    for n in worker_counts:
        cfg = {"env": env_id, "num_envs_per_env_runner": num_envs,
               "rollout_fragment_length": fragment, "seed": 0,
               "num_env_runners": n,
               "max_requests_in_flight_per_env_runner": 2,
               "sample_queue_size": 8 * n}
        flow = DecoupledDataflow(cfg, spec, weights, version=0)
        try:
            # warm: first pulls cover actor spawn + jit compile
            deadline = time.perf_counter() + 60.0
            warmed = 0
            while warmed < 2 * n and time.perf_counter() < deadline:
                warmed += len(flow.pull(current_version=0))
                time.sleep(0.02)
            steps = 0
            t0 = time.perf_counter()
            while time.perf_counter() - t0 < duration_s:
                for entry, _eps in flow.pull(current_version=0):
                    steps += int(entry.get("env_steps", 0))
                time.sleep(0.005)
            dt = time.perf_counter() - t0
            per_worker[str(n)] = round(steps / dt, 1)
        finally:
            flow.stop()
    counts = [str(n) for n in worker_counts]
    base = per_worker.get(counts[0]) or 1.0
    top = per_worker.get(counts[-1]) or 0.0
    return {
        "metric": "rllib_decoupled_env_steps_per_sec",
        "value": top,
        "unit": "env-steps/s",
        "detail": {
            "env": env_id,
            "per_worker_counts": per_worker,
            "scaling": round(top / base, 3) if base else None,
            "worker_counts": list(worker_counts),
            "num_envs_per_runner": num_envs,
            # a 1-core CI host time-slices the fleet: the curve is the
            # artifact, flat scaling there is the host, not the dataflow
            "host_cpus": os.cpu_count(),
        },
    }


def main(argv) -> dict:
    if argv and argv[0] == "decoupled":
        import ray_tpu

        ray_tpu.init(num_cpus=4)
        try:
            return benchmark_decoupled()
        finally:
            ray_tpu.shutdown()
    return benchmark_env_steps(argv[0] if argv else None)


if __name__ == "__main__":
    import sys

    print(json.dumps(main(sys.argv[1:])))
