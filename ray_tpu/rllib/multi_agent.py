"""Multi-agent RL: episodes, sampling, and multi-agent PPO.

Reference counterparts: ray rllib/env/multi_agent_episode.py
(MultiAgentEpisode), rllib/core/rl_module/multi_rl_module.py (one RLModule
per policy id), and the multi-agent paths of
rllib/algorithms/ppo/ppo.py — AlgorithmConfig.multi_agent(policies=...,
policy_mapping_fn=...) routes each agent's experience to its module's
learner; shared policies train on all mapped agents' data.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm
from ray_tpu.rllib.algorithms.ppo import PPOConfig, PPOLearner, compute_gae
from ray_tpu.rllib.episode import SingleAgentEpisode


class MultiAgentEpisode:
    """Per-agent SingleAgentEpisodes plus env-level bookkeeping."""

    def __init__(self):
        self.agent_episodes: Dict[Any, SingleAgentEpisode] = {}
        self.is_done = False

    def agent(self, agent_id) -> SingleAgentEpisode:
        ep = self.agent_episodes.get(agent_id)
        if ep is None:
            ep = self.agent_episodes[agent_id] = SingleAgentEpisode()
        return ep

    def __len__(self) -> int:
        return sum(len(ep) for ep in self.agent_episodes.values())

    @property
    def total_reward(self) -> float:
        return float(sum(ep.total_reward
                         for ep in self.agent_episodes.values()))


class MultiAgentEnvRunner:
    """Samples MultiAgentEpisodes from one MultiAgentEnv with one jitted
    forward per module (driver-side; the reference's local-worker mode)."""

    def __init__(self, env, modules: Dict[str, Any],
                 params: Dict[str, Any],
                 policy_mapping_fn: Callable[[Any], str],
                 seed: Optional[int] = None):
        import jax

        self.env = env
        self.modules = modules
        self.params = params
        self.policy_mapping_fn = policy_mapping_fn
        self._fwd = {mid: jax.jit(m.forward) for mid, m in modules.items()}
        self._rng = np.random.default_rng(seed)
        self._obs: Optional[Dict] = None
        self._episode: Optional[MultiAgentEpisode] = None

    def set_params(self, params: Dict[str, Any]) -> None:
        self.params = params

    def _act(self, agent_id, obs):
        """-> (action, logp, value) sampled from the agent's module."""
        mid = self.policy_mapping_fn(agent_id)
        logits, value = self._fwd[mid](
            self.params[mid], np.asarray(obs, np.float32)[None, :])
        logits = np.asarray(logits, np.float64)[0]
        logits = logits - logits.max()
        probs = np.exp(logits)
        probs /= probs.sum()
        action = int(self._rng.choice(len(probs), p=probs))
        return action, float(np.log(probs[action] + 1e-12)), \
            float(np.asarray(value)[0])

    def sample(self, num_steps: int) -> List[MultiAgentEpisode]:
        out: List[MultiAgentEpisode] = []
        steps = 0
        if self._obs is None:
            self._obs, _ = self.env.reset(
                seed=int(self._rng.integers(1 << 31)))
            self._episode = MultiAgentEpisode()
            for aid, ob in self._obs.items():
                self._episode.agent(aid).add_env_reset(ob)
        while steps < num_steps:
            actions, logps, values = {}, {}, {}
            for aid, ob in self._obs.items():
                a, lp, v = self._act(aid, ob)
                actions[aid], logps[aid], values[aid] = a, lp, v
            obs, rewards, terms, truncs, _infos = self.env.step(actions)
            done_all = terms.get("__all__", False) or \
                truncs.get("__all__", False)
            for aid in actions:
                ep = self._episode.agent(aid)
                if not ep.obs:
                    # agent entered mid-episode (dynamic-entry envs):
                    # its first observation plays the reset role
                    ep.add_env_reset(self._obs[aid])
                next_ob = obs.get(aid, self._obs[aid])
                ep.add_env_step(
                    next_ob, actions[aid], rewards.get(aid, 0.0),
                    terminated=bool(terms.get(aid, False)
                                    or terms.get("__all__", False)),
                    truncated=bool(truncs.get(aid, False)
                                   or truncs.get("__all__", False)),
                    logp=logps[aid], vf_preds=values[aid])
                steps += 1
            self._obs = {aid: ob for aid, ob in obs.items()
                         if not (terms.get(aid) or truncs.get(aid))}
            if done_all or not self._obs:
                self._episode.is_done = True
                out.append(self._episode)
                self._obs, _ = self.env.reset(
                    seed=int(self._rng.integers(1 << 31)))
                self._episode = MultiAgentEpisode()
                for aid, ob in self._obs.items():
                    self._episode.agent(aid).add_env_reset(ob)
        if self._episode is not None and len(self._episode):
            # cut the in-progress fragment so its data trains this round
            out.append(self._episode)
            self._episode = MultiAgentEpisode()
            for aid, ob in self._obs.items():
                self._episode.agent(aid).add_env_reset(ob)
        return out


class MultiAgentPPOConfig(PPOConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = MultiAgentPPO
        self.policies: Optional[List[str]] = None
        self.policy_mapping_fn: Callable[[Any], str] = (
            lambda agent_id: "default_policy")

    def multi_agent(self, *, policies: Optional[List[str]] = None,
                    policy_mapping_fn: Optional[Callable] = None,
                    **_kw) -> "MultiAgentPPOConfig":
        if policies is not None:
            self.policies = list(policies)
        if policy_mapping_fn is not None:
            self.policy_mapping_fn = policy_mapping_fn
        return self


class MultiAgentPPO(Algorithm):
    """PPO over a MultiAgentEnv: one PPOLearner per policy id; each
    agent's experience routes to its mapped policy's learner."""

    def setup(self, config) -> None:
        env = config.env
        if isinstance(env, type):
            env = env(**(config.env_config or {}))
        self.env = env
        policies = config.policies or ["default_policy"]
        # fail fast on an inconsistent mapping: every agent must map into
        # the declared policy set (a bad fn would otherwise surface as a
        # KeyError deep inside sampling)
        for aid in env.possible_agents:
            mapped = config.policy_mapping_fn(aid)
            if mapped not in policies:
                raise ValueError(
                    f"policy_mapping_fn({aid!r}) -> {mapped!r}, which is "
                    f"not in policies {policies}")
        self.learners: Dict[str, PPOLearner] = {}
        modules, params = {}, {}
        for pid in policies:
            # spaces from any agent mapped to this policy
            agents = [a for a in env.possible_agents
                      if config.policy_mapping_fn(a) == pid]
            if not agents:
                raise ValueError(f"no agents map to policy {pid!r}")
            obs_space = env.observation_space(agents[0])
            act_space = env.action_space(agents[0])
            spec = {
                "obs_dim": int(obs_space.shape[0]),
                "num_actions": int(act_space.n),
                "hiddens": tuple(
                    config.model.get("fcnet_hiddens", (64, 64))),
            }
            learner = PPOLearner(spec, config.to_dict())
            self.learners[pid] = learner
            modules[pid] = learner.module
            params[pid] = learner.params
        self.runner = MultiAgentEnvRunner(
            env, modules, params, config.policy_mapping_fn,
            seed=config.seed)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        episodes: List[MultiAgentEpisode] = []
        steps = 0
        while steps < cfg.train_batch_size:
            new = self.runner.sample(
                num_steps=cfg.train_batch_size - steps)
            episodes.extend(new)
            steps += sum(len(e) for e in new)
        self._record_episodes(
            [ep for mae in episodes for ep in mae.agent_episodes.values()])

        # group agent fragments by policy, GAE per fragment
        per_policy: Dict[str, List[Dict[str, np.ndarray]]] = {
            pid: [] for pid in self.learners}
        for mae in episodes:
            for aid, ep in mae.agent_episodes.items():
                if not len(ep):
                    continue
                b = ep.to_batch()
                last_value = 0.0 if ep.is_done else float(b["vf_preds"][-1])
                adv, targets = compute_gae(
                    b["rewards"], b["vf_preds"], b["terminateds"],
                    last_value, cfg.gamma, cfg.lambda_)
                b["advantages"] = adv
                b["value_targets"] = targets
                per_policy[cfg.policy_mapping_fn(aid)].append(b)

        keys = ("obs", "actions", "logp", "advantages", "value_targets")
        metrics: Dict[str, Any] = {"num_env_steps_sampled": steps}
        rng = np.random.default_rng(self.iteration)
        for pid, batches in per_policy.items():
            if not batches:
                continue
            train_batch = {
                k: np.concatenate([b[k] for b in batches]).astype(
                    np.float32 if k != "actions" else np.int32)
                for k in keys}
            n = len(train_batch["obs"])
            learner = self.learners[pid]
            mbs = min(cfg.minibatch_size, n)
            for _ in range(cfg.num_epochs):
                perm = rng.permutation(n)
                for s in range(0, n - mbs + 1, mbs):
                    idx = perm[s:s + mbs]
                    out = learner.update_from_batch(
                        {k: v[idx] for k, v in train_batch.items()})
                    metrics[pid] = out
        self.runner.set_params(
            {pid: lr.params for pid, lr in self.learners.items()})
        return metrics

    def get_state(self) -> Dict[str, Any]:
        state = super().get_state()
        state["learners"] = {pid: lr.get_state()
                             for pid, lr in self.learners.items()}
        return state

    def set_state(self, state: Dict[str, Any]) -> None:
        super().set_state(state)
        for pid, s in state.get("learners", {}).items():
            if pid in self.learners:
                self.learners[pid].set_state(s)
        self.runner.set_params(
            {pid: lr.params for pid, lr in self.learners.items()})

    def stop(self) -> None:
        self.env.close()
