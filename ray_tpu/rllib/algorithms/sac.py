"""SAC — continuous control (reference: ray rllib/algorithms/sac/ —
squashed-Gaussian actor, twin Q critics with target networks, entropy
temperature alpha auto-tuned to a target entropy).

The actor/critic/alpha updates run as ONE jitted step per gradient update
(no host roundtrips between the three optimizers); target networks use
polyak averaging inside the same program.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm
from ray_tpu.rllib.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.replay_buffer import ReplayBuffer


class SACConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=SAC)
        self.lr = 3e-4
        self.train_batch_size = 256
        self.num_steps_per_iteration = 1000
        self.tau = 0.005                      # polyak target rate
        self.initial_alpha = 1.0
        self.target_entropy = "auto"          # -act_dim
        self.num_steps_sampled_before_learning_starts = 1500
        self.model = {"fcnet_hiddens": [256, 256]}


class SAC(Algorithm):
    def setup(self, config: AlgorithmConfig) -> None:
        import gymnasium as gym
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.rllib.rl_module import (
            ContinuousQModule,
            GaussianActorModule,
        )

        env = gym.make(config.env, **(config.env_config or {}))
        from ray_tpu.rllib.catalog import Catalog

        spec = Catalog(env.observation_space, env.action_space,
                       config.model).sac_specs()
        obs_dim, act_dim = spec["obs_dim"], spec["act_dim"]
        self._act_low = np.asarray(env.action_space.low, np.float32)
        self._act_high = np.asarray(env.action_space.high, np.float32)
        self.env = env
        hid = spec["hiddens"]
        self.actor = GaussianActorModule(obs_dim, act_dim, hid)
        self.q1 = ContinuousQModule(obs_dim, act_dim, hid)
        self.q2 = ContinuousQModule(obs_dim, act_dim, hid)

        key = jax.random.PRNGKey(config.seed or 0)
        ka, k1, k2 = jax.random.split(key, 3)
        self.params = {
            "actor": self.actor.init(ka),
            "q1": self.q1.init(k1),
            "q2": self.q2.init(k2),
            "log_alpha": jnp.asarray(np.log(config.initial_alpha),
                                     jnp.float32),
        }
        self.target = {"q1": self.params["q1"], "q2": self.params["q2"]}
        self.opt = optax.adam(config.lr)
        self.opt_state = self.opt.init(self.params)
        target_entropy = (-float(act_dim)
                          if config.target_entropy == "auto"
                          else float(config.target_entropy))
        gamma, tau = config.gamma, config.tau
        actor, q1m, q2m = self.actor, self.q1, self.q2

        def losses(params, target, batch, key):
            obs, act = batch["obs"], batch["actions"]
            next_obs = batch["next_obs"]
            alpha = jnp.exp(params["log_alpha"])

            # critic targets from the CURRENT policy at next_obs
            next_act, next_logp = actor.sample(params["actor"], next_obs, key)
            tq = jnp.minimum(
                q1m.forward(target["q1"], next_obs, next_act),
                q2m.forward(target["q2"], next_obs, next_act))
            backup = batch["rewards"] + gamma * (1 - batch["terminateds"]) * (
                tq - jax.lax.stop_gradient(alpha) * next_logp)
            backup = jax.lax.stop_gradient(backup)
            q1_pred = q1m.forward(params["q1"], obs, act)
            q2_pred = q2m.forward(params["q2"], obs, act)
            critic_loss = jnp.mean((q1_pred - backup) ** 2) + jnp.mean(
                (q2_pred - backup) ** 2)

            # actor: maximize Q - alpha * logp (fresh sample, reparam'd)
            new_act, logp = actor.sample(params["actor"], obs,
                                         jax.random.fold_in(key, 1))
            q_new = jnp.minimum(
                q1m.forward(jax.lax.stop_gradient(params["q1"]), obs, new_act),
                q2m.forward(jax.lax.stop_gradient(params["q2"]), obs, new_act))
            actor_loss = jnp.mean(
                jax.lax.stop_gradient(alpha) * logp - q_new)

            # alpha: drive entropy toward the target
            alpha_loss = -jnp.mean(
                params["log_alpha"]
                * jax.lax.stop_gradient(logp + target_entropy))
            total = critic_loss + actor_loss + alpha_loss
            return total, {
                "critic_loss": critic_loss, "actor_loss": actor_loss,
                "alpha": alpha, "entropy": -jnp.mean(logp),
                "qf_mean": jnp.mean(q1_pred),
            }

        def update(params, opt_state, target, batch, key):
            (_, aux), grads = jax.value_and_grad(
                losses, has_aux=True)(params, target, batch, key)
            updates, opt_state = self.opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            target = jax.tree.map(
                lambda t, p: (1 - tau) * t + tau * p,
                target, {"q1": params["q1"], "q2": params["q2"]})
            return params, opt_state, target, aux

        self._update = jax.jit(update, donate_argnums=(1,))
        self._sample_act = jax.jit(actor.sample)
        self._greedy = jax.jit(
            lambda p, o: actor.forward_inference(p, {"obs": o})["actions"])
        self._key = jax.random.PRNGKey((config.seed or 0) + 1)
        self.buffer = ReplayBuffer(
            capacity=config.replay_buffer_config.get("capacity", 100_000))
        self._obs, _ = env.reset(seed=config.seed)
        self._ep_return = 0.0
        self._rng = np.random.default_rng(config.seed)

    def compute_single_action(self, obs, explore: bool = False):
        """Deterministic (tanh-mean) or sampled action in ENV units
        (reference API: Algorithm.compute_single_action)."""
        import jax

        obs = np.asarray(obs, np.float32)[None, :]
        if explore:
            self._key, sub = jax.random.split(self._key)
            act, _ = self._sample_act(self.params["actor"], obs, sub)
        else:
            act = self._greedy(self.params["actor"], obs)
        return self._env_action(np.asarray(act)[0])

    def _env_action(self, act):
        return (act * (self._act_high - self._act_low) / 2.0
                + (self._act_high + self._act_low) / 2.0)

    def training_step(self) -> Dict[str, Any]:
        import jax

        cfg = self.config
        metrics: Dict[str, Any] = {}
        warmup = cfg.num_steps_sampled_before_learning_starts
        for _ in range(cfg.num_steps_per_iteration):
            if self._num_env_steps_sampled_lifetime < warmup:
                act = self._rng.uniform(-1, 1,
                                        size=self._act_low.shape).astype(
                                            np.float32)
            else:
                self._key, sub = jax.random.split(self._key)
                a, _ = self._sample_act(
                    self.params["actor"],
                    self._obs.astype(np.float32)[None, :], sub)
                act = np.asarray(a)[0]
            next_obs, reward, term, trunc, _ = self.env.step(
                self._env_action(act))
            self.buffer.add({
                "obs": self._obs.astype(np.float32),
                "next_obs": np.asarray(next_obs, np.float32),
                "actions": act.astype(np.float32),
                "rewards": np.float32(reward),
                "terminateds": np.float32(term),
            })
            self._num_env_steps_sampled_lifetime += 1
            self._ep_return += float(reward)
            if term or trunc:
                self._episode_returns.append(self._ep_return)
                self._ep_return = 0.0
                self._obs, _ = self.env.reset()
            else:
                self._obs = next_obs

            if (self._num_env_steps_sampled_lifetime >= warmup
                    and len(self.buffer) >= cfg.train_batch_size):
                batch = self.buffer.sample(cfg.train_batch_size)
                self._key, sub = jax.random.split(self._key)
                (self.params, self.opt_state, self.target,
                 aux) = self._update(self.params, self.opt_state,
                                     self.target, batch, sub)
                metrics = {k: float(v) for k, v in aux.items()}
        metrics["buffer_size"] = len(self.buffer)
        return metrics

    def stop(self) -> None:
        self.env.close()
