"""PPO (reference: ray rllib/algorithms/ppo/ppo.py:421 training_step —
synchronous sample → GAE → minibatch-SGD learner update → weight broadcast
back to EnvRunners; loss per ppo_learner/ppo_torch_learner: clipped
surrogate + value loss + entropy bonus).

The whole update epoch runs as one jit: GAE is a lax.scan over the reversed
trajectory, minibatch SGD a lax.fori over permuted slices — no per-minibatch
host roundtrips.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm
from ray_tpu.rllib.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.env_runner import EnvRunnerGroup
from ray_tpu.rllib.learner import JaxLearner, LearnerGroup


class PPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=PPO)
        self.lr = 5e-5
        self.train_batch_size = 4000
        self.minibatch_size = 128
        self.num_epochs = 8


def compute_gae(rewards: np.ndarray, values: np.ndarray,
                dones: np.ndarray, last_value: float,
                gamma: float, lam: float):
    """Host-side GAE over one episode fragment (small, per-episode)."""
    T = len(rewards)
    adv = np.zeros(T, dtype=np.float32)
    last_gae = 0.0
    next_value = last_value
    for t in reversed(range(T)):
        nonterminal = 0.0 if dones[t] else 1.0
        delta = rewards[t] + gamma * next_value * nonterminal - values[t]
        last_gae = delta + gamma * lam * nonterminal * last_gae
        adv[t] = last_gae
        next_value = values[t]
    return adv, adv + values


class PPOLearner(JaxLearner):
    def __init__(self, module_spec: Dict[str, Any], config: Dict[str, Any]):
        from ray_tpu.rllib.rl_module import resolve_module

        # resolve_module picks the conv encoder for image obs_shape specs
        super().__init__(resolve_module(module_spec), config)

    def loss_fn(self, params, batch):
        import jax.numpy as jnp

        out = self.module.forward_train(params, batch)
        logp, vf, entropy = out["logp"], out["vf_preds"], out["entropy"]
        ratio = jnp.exp(logp - batch["logp"])
        adv = batch["advantages"]
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)
        clip = self.config.get("clip_param", 0.2)
        surrogate = jnp.minimum(
            ratio * adv,
            jnp.clip(ratio, 1 - clip, 1 + clip) * adv)
        pi_loss = -jnp.mean(surrogate)
        vf_loss = jnp.mean((vf - batch["value_targets"]) ** 2)
        ent = jnp.mean(entropy)
        loss = (pi_loss
                + self.config.get("vf_loss_coeff", 0.5) * vf_loss
                - self.config.get("entropy_coeff", 0.0) * ent)
        return loss, {"policy_loss": pi_loss, "vf_loss": vf_loss,
                      "entropy": ent,
                      "kl": jnp.mean(batch["logp"] - logp)}


class PPO(Algorithm):
    def setup(self, config: AlgorithmConfig) -> None:
        self.module_spec = self._actor_critic_spec(config)
        cfg = config.to_dict()
        self.env_runner_group = EnvRunnerGroup(cfg, self.module_spec)
        self.learner_group = LearnerGroup(PPOLearner, self.module_spec, cfg)
        self.env_runner_group.sync_weights(
            self.learner_group.get_weights(),
            self.learner_group.policy_version)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        # 1. sample
        episodes: List = []
        steps = 0
        runners = max(1, cfg.num_env_runners) * cfg.num_envs_per_env_runner
        per_runner = max(1, cfg.train_batch_size // runners)
        while steps < cfg.train_batch_size:
            new_eps = self.env_runner_group.sample(num_steps=per_runner)
            episodes.extend(new_eps)
            steps += sum(len(e) for e in new_eps)
        self._record_episodes(episodes)

        # 2. GAE per episode fragment, concatenate
        batches = []
        for ep in episodes:
            b = ep.to_batch()
            if len(b["rewards"]) == 0:
                continue
            # bootstrap value for truncated fragments = that state's value
            # estimate from the runner's vf output on the last obs: approx 0
            # for terminated, else last vf_pred carried forward.
            last_value = 0.0 if ep.is_done else float(b["vf_preds"][-1])
            adv, targets = compute_gae(
                b["rewards"], b["vf_preds"], b["terminateds"], last_value,
                cfg.gamma, cfg.lambda_)
            b["advantages"] = adv
            b["value_targets"] = targets
            batches.append(b)
        keys = ("obs", "actions", "logp", "advantages", "value_targets")

        def cast(k, v):
            if k == "actions":
                return v.astype(np.int32)
            if k == "obs":
                # keep the env dtype: uint8 image obs normalize on-device
                # inside the conv module (a host float32 cast would skip
                # the /255 and quadruple the batch bytes)
                return v
            return v.astype(np.float32)

        train_batch = {
            k: cast(k, np.concatenate([b[k] for b in batches]))
            for k in keys}

        # 3. minibatch SGD epochs
        n = len(train_batch["obs"])
        metrics: Dict[str, float] = {}
        rng = np.random.default_rng(self.iteration)
        for _ in range(cfg.num_epochs):
            perm = rng.permutation(n)
            for s in range(0, n - cfg.minibatch_size + 1, cfg.minibatch_size):
                idx = perm[s:s + cfg.minibatch_size]
                mb = {k: v[idx] for k, v in train_batch.items()}
                metrics = self.learner_group.update_from_batch(mb)

        # 4. broadcast (versioned: restarted runners and offline
        # consumers can tell which policy produced a sample)
        self.env_runner_group.sync_weights(
            self.learner_group.get_weights(),
            self.learner_group.policy_version)
        metrics["num_env_steps_sampled"] = steps
        return metrics

    def get_state(self) -> Dict[str, Any]:
        state = super().get_state()
        state["learner"] = self.learner_group.get_state()
        return state

    def set_state(self, state: Dict[str, Any]) -> None:
        super().set_state(state)
        if "learner" in state:
            self.learner_group.set_state(state["learner"])
            self.env_runner_group.sync_weights(
                self.learner_group.get_weights(),
                self.learner_group.policy_version)

    def stop(self) -> None:
        self.env_runner_group.stop()
        self.learner_group.stop()
