"""MARWIL + BC (reference: ray rllib/algorithms/marwil/marwil.py —
Monotonic Advantage Re-Weighted Imitation Learning; BC (algorithms/bc/bc.py)
is MARWIL with beta=0, exactly as in the reference).

Offline episode batches (rllib/offline/io.py) are loaded once at setup;
Monte-Carlo returns are computed per episode; the jitted update trains the
value head to regress returns and re-weights the imitation cross-entropy by
exp(beta * normalized advantage).
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm
from ray_tpu.rllib.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.learner import JaxLearner


class MARWILConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or MARWIL)
        self.beta = 1.0
        self.vf_coeff = 1.0
        self.train_batch_size = 2000
        self.minibatch_size = 256
        self.num_updates_per_iteration = 20
        self.lr = 1e-3


class BCConfig(MARWILConfig):
    def __init__(self):
        super().__init__(algo_class=BC)
        self.beta = 0.0  # pure imitation: no advantage weighting


class MARWILLearner(JaxLearner):
    def __init__(self, module_spec: Dict[str, Any], config: Dict[str, Any]):
        from ray_tpu.rllib.rl_module import DiscreteActorCriticModule

        module = DiscreteActorCriticModule(
            module_spec["obs_dim"], module_spec["num_actions"],
            module_spec.get("hiddens", (64, 64)))
        super().__init__(module, config)

    def loss_fn(self, params, batch):
        import jax
        import jax.numpy as jnp

        beta = self.config.get("beta", 1.0)
        vf_coeff = self.config.get("vf_coeff", 1.0)
        logits, values = self.module.forward(params, batch["obs"])
        logp_all = jax.nn.log_softmax(logits)
        logp = logp_all[jnp.arange(logits.shape[0]), batch["actions"]]
        returns = batch["returns"]
        vf_loss = jnp.mean((values - returns) ** 2)
        if beta > 0:
            adv = returns - jax.lax.stop_gradient(values)
            # normalize by RMS like the reference's moving ma_adv_norm
            adv = adv / jnp.sqrt(jnp.mean(adv ** 2) + 1e-8)
            weight = jnp.exp(jnp.clip(beta * adv, -10.0, 10.0))
        else:
            weight = jnp.ones_like(logp)
        policy_loss = -jnp.mean(weight * logp)
        total = policy_loss + vf_coeff * vf_loss
        return total, {"policy_loss": policy_loss, "vf_loss": vf_loss,
                       "mean_weight": jnp.mean(weight)}


def compute_mc_returns(batch: Dict[str, np.ndarray],
                       gamma: float) -> np.ndarray:
    r = np.asarray(batch["rewards"], dtype=np.float32)
    out = np.zeros_like(r)
    acc = 0.0
    for t in range(len(r) - 1, -1, -1):
        acc = r[t] + gamma * acc
        out[t] = acc
    return out


class MARWIL(Algorithm):
    def setup(self, config: AlgorithmConfig) -> None:
        from ray_tpu.rllib.offline import load_episode_batches

        obs_dim, num_actions = self._env_spaces(config.env, config.env_config)
        self.module_spec = {
            "obs_dim": obs_dim, "num_actions": num_actions,
            "hiddens": tuple(config.model.get("fcnet_hiddens", (64, 64))),
        }
        self.learner = MARWILLearner(self.module_spec, config.to_dict())
        episodes = load_episode_batches(config.input_)
        obs, actions, returns = [], [], []
        for ep in episodes:
            obs.append(np.asarray(ep["obs"], dtype=np.float32))
            actions.append(np.asarray(ep["actions"], dtype=np.int32))
            returns.append(compute_mc_returns(ep, config.gamma))
        self._obs = np.concatenate(obs)
        self._actions = np.concatenate(actions)
        self._returns = np.concatenate(returns)
        self._rng = np.random.default_rng(config.seed)
        self._eval_env = None

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        n = len(self._obs)
        metrics: Dict[str, Any] = {}
        for _ in range(cfg.num_updates_per_iteration):
            idx = self._rng.integers(0, n, size=min(cfg.minibatch_size, n))
            metrics = self.learner.update_from_batch({
                "obs": self._obs[idx],
                "actions": self._actions[idx],
                "returns": self._returns[idx],
            })
        metrics["num_offline_transitions"] = n
        if (cfg.evaluation_interval
                and self.iteration % cfg.evaluation_interval == 0):
            metrics["evaluation"] = self.evaluate()
        return metrics

    def evaluate(self) -> Dict[str, Any]:
        """Greedy rollouts in the real env (reference:
        Algorithm.evaluate)."""
        import gymnasium as gym
        import jax

        cfg = self.config
        if self._eval_env is None:
            self._eval_env = gym.make(cfg.env, **(cfg.env_config or {}))
            self._fwd = jax.jit(self.learner.module.forward)
        returns = []
        for _ in range(cfg.evaluation_duration):
            obs, _ = self._eval_env.reset(seed=None)
            done = trunc = False
            total = 0.0
            while not (done or trunc):
                logits, _v = self._fwd(
                    self.learner.params,
                    np.asarray(obs, dtype=np.float32)[None, :])
                action = int(np.argmax(np.asarray(logits)[0]))
                obs, r, done, trunc, _ = self._eval_env.step(action)
                total += float(r)
            returns.append(total)
        return {"episode_return_mean": float(np.mean(returns)),
                "num_episodes": len(returns)}

    def get_state(self) -> Dict[str, Any]:
        state = super().get_state()
        state["learner"] = self.learner.get_state()
        return state

    def set_state(self, state: Dict[str, Any]) -> None:
        super().set_state(state)
        if "learner" in state:
            self.learner.set_state(state["learner"])

    def stop(self) -> None:
        if self._eval_env is not None:
            self._eval_env.close()


class BC(MARWIL):
    """Behavior cloning — MARWIL with beta=0 (reference:
    rllib/algorithms/bc/bc.py)."""
