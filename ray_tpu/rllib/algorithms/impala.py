"""IMPALA — asynchronous actor-learner with V-trace off-policy correction
(reference: ray rllib/algorithms/impala/impala.py:679 — EnvRunner actors
sample continuously; the learner consumes whatever batches are ready and
broadcasts weights periodically, so sampling never blocks on learning).

V-trace (Espeholt et al. 2018) runs as a lax.scan over the reversed
trajectory inside the jitted update — the whole correction + policy-gradient
+ value + entropy update is one XLA program.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

import ray_tpu
from ray_tpu import exceptions as exc
from ray_tpu.rllib.algorithm import Algorithm
from ray_tpu.rllib.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.env_runner import EnvRunner, EnvRunnerGroup


class IMPALAConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=IMPALA)
        self.lr = 5e-4
        self.rollout_fragment_length = 50
        self.num_env_runners = 2
        self.vtrace_clip_rho_threshold = 1.0
        self.vtrace_clip_c_threshold = 1.0
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.broadcast_interval = 1   # learner steps between weight pushes
        self.max_requests_in_flight_per_env_runner = 2
        self.normalize_advantages = True


def make_vtrace_update(module, optimizer, config: Dict[str, Any]):
    """-> jitted update(params, opt_state, batch) for [B, T] trajectories."""
    import jax
    import jax.numpy as jnp
    import optax

    gamma = config.get("gamma", 0.99)
    rho_bar = config.get("vtrace_clip_rho_threshold", 1.0)
    c_bar = config.get("vtrace_clip_c_threshold", 1.0)
    vf_coeff = config.get("vf_loss_coeff", 0.5)
    ent_coeff = config.get("entropy_coeff", 0.01)
    normalize_adv = config.get("normalize_advantages", True)
    # APPO: PPO clipped surrogate on the v-trace advantages instead of the
    # plain policy gradient (reference: appo.py / appo_learner).
    appo_clip = config.get("appo_clip", False)
    clip_param = config.get("clip_param", 0.2)

    def loss_fn(params, batch):
        # batch arrays are [B, T] (+ trailing dims); flatten for the module.
        b, t = batch["actions"].shape
        # flatten [B, T] rows only — image obs keep their [H, W, C] tail
        obs = batch["obs"].reshape((b * t,) + batch["obs"].shape[2:])
        out = module.forward_train(
            params, {"obs": obs, "actions": batch["actions"].reshape(-1)})
        logp = out["logp"].reshape(b, t)
        values = out["vf_preds"].reshape(b, t)
        entropy = out["entropy"].reshape(b, t)
        mask = batch["mask"]  # 1 = real transition, 0 = shape padding
        denom = jnp.maximum(jnp.sum(mask), 1.0)
        behaviour_logp = batch["logp"]
        rhos = jnp.exp(logp - behaviour_logp)
        clipped_rho = jnp.minimum(rho_bar, rhos)
        clipped_c = jnp.minimum(c_bar, rhos)
        discounts = gamma * (1.0 - batch["terminateds"])
        bootstrap = batch["bootstrap_value"]  # [B]

        values_t_plus_1 = jnp.concatenate(
            [values[:, 1:], bootstrap[:, None]], axis=1)
        deltas = clipped_rho * (
            batch["rewards"] + discounts * values_t_plus_1 - values)

        # vs_t = V(x_t) + sum_{k>=t} gamma^{k-t} (prod c) delta_k — reverse scan.
        def backward(acc, xs):
            delta_t, disc_t, c_t = xs
            acc = delta_t + disc_t * c_t * acc
            return acc, acc

        _, vs_minus_v = jax.lax.scan(
            backward, jnp.zeros_like(bootstrap),
            (deltas.T[::-1], discounts.T[::-1], clipped_c.T[::-1]))
        vs = values + vs_minus_v[::-1].T

        vs_t_plus_1 = jnp.concatenate([vs[:, 1:], bootstrap[:, None]], axis=1)
        pg_adv = jax.lax.stop_gradient(
            clipped_rho * (batch["rewards"] + discounts * vs_t_plus_1
                           - values))
        if normalize_adv:
            adv_mean = jnp.sum(pg_adv * mask) / denom
            adv_var = jnp.sum(mask * (pg_adv - adv_mean) ** 2) / denom
            pg_adv = (pg_adv - adv_mean) * jax.lax.rsqrt(adv_var + 1e-8)
        if appo_clip:
            ratio = jnp.exp(logp - behaviour_logp)
            surr = jnp.minimum(
                ratio * pg_adv,
                jnp.clip(ratio, 1 - clip_param, 1 + clip_param) * pg_adv)
            pg_loss = -jnp.sum(surr * mask) / denom
        else:
            pg_loss = -jnp.sum(logp * pg_adv * mask) / denom
        vf_loss = 0.5 * jnp.sum(
            mask * (values - jax.lax.stop_gradient(vs)) ** 2) / denom
        ent = jnp.sum(entropy * mask) / denom
        total = pg_loss + vf_coeff * vf_loss - ent_coeff * ent
        return total, {"pg_loss": pg_loss, "vf_loss": vf_loss,
                       "entropy": ent,
                       "mean_rho": jnp.sum(rhos * mask) / denom}

    def update(params, opt_state, batch):
        (loss, aux), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        aux["total_loss"] = loss
        return params, opt_state, aux

    return jax.jit(update, donate_argnums=(1,))


class IMPALA(Algorithm):
    def setup(self, config: AlgorithmConfig) -> None:
        import jax
        import optax

        from ray_tpu.rllib.rl_module import resolve_module

        self.module_spec = self._actor_critic_spec(config)
        self.module = resolve_module(self.module_spec)
        self.params = self.module.init(jax.random.PRNGKey(config.seed or 0))
        self.optimizer = optax.adam(config.lr)
        self.opt_state = self.optimizer.init(self.params)
        self._update = make_vtrace_update(
            self.module, self.optimizer, config.to_dict())
        self._value_fn = jax.jit(
            lambda p, o: self.module.forward(p, o)[1])

        cfg = config.to_dict()
        self.policy_version = 0
        self._updates = 0
        self._stale_seen = 0  # stale-drop watermark for livelock escape
        self.dataflow = None
        self.runner_group = None
        self._inflight: Dict[Any, Any] = {}  # ref -> runner handle
        if getattr(config, "decoupled", False) and config.num_env_runners:
            # Decoupled fault-tolerant dataflow (ISSUE 14): the rollout
            # fleet pushes into a bounded object-store sample queue; this
            # learner pulls asynchronously under the staleness bound and
            # never waits on (or even knows about) any single runner.
            from ray_tpu.rllib.dataflow import DecoupledDataflow

            self.dataflow = DecoupledDataflow(
                cfg, self.module_spec, self.params,
                version=self.policy_version)
        else:
            self.runner_group = EnvRunnerGroup(cfg, self.module_spec)
            self.runner_group.sync_weights(self.params,
                                           self.policy_version)
            # Async pipeline: keep N sample requests in flight per runner.
            if self.runner_group.remotes:
                per = config.max_requests_in_flight_per_env_runner
                for w in self.runner_group.remotes:
                    for _ in range(per):
                        self._inflight[w.sample.remote(
                            num_steps=config.rollout_fragment_length)] = w
        self._steps_since_broadcast = 0

    def _episodes_to_batch(self, episodes) -> Dict[str, np.ndarray]:
        """Pack fragments densely: concatenate every fragment into one
        stream, then chop into rows of exactly T=rollout_fragment_length
        ([B_bucket, T], B padded to a bucket of 4 with masked dead rows).

        Every fragment ends with terminateds=1: terminated episodes as-is,
        truncated ones with the bootstrap folded into the last reward
        (r += gamma*V(boundary_obs)). The discount therefore cuts at every
        fragment boundary, so v-trace targets never cross rows and rows may
        split the stream anywhere — no per-episode padding (the old
        per-episode layout was ~75% padding on short-episode envs)."""
        t_len = self.config.rollout_fragment_length
        stream = {k: [] for k in
                  ("obs", "actions", "rewards", "logp", "terminateds")}
        for ep in episodes:
            rews = np.asarray(ep.rewards, np.float32).copy()
            terms = np.zeros(len(ep), np.float32)
            terms[-1] = 1.0
            if not ep.is_done:
                # keep the env dtype: uint8 image obs normalize on-device
                last_obs = np.asarray(ep.obs[-1])
                rews[-1] += self.config.gamma * float(self._value_fn(
                    self.params, last_obs[None])[0])
            stream["obs"].append(np.asarray(ep.obs[:-1]))
            stream["actions"].append(np.asarray(ep.actions, np.int64))
            stream["rewards"].append(rews)
            stream["logp"].append(
                np.asarray(ep.extra.get("logp"), np.float32))
            stream["terminateds"].append(terms)
        flat = {k: np.concatenate(v) for k, v in stream.items()}
        n = len(flat["actions"])
        mask = np.ones(n, np.float32)
        pad = (-n) % t_len
        if pad:
            flat = {k: np.concatenate(
                [v, np.repeat(v[-1:], pad, axis=0)]) for k, v in flat.items()}
            flat["rewards"][n:] = 0
            flat["terminateds"][n:] = 1
            mask = np.concatenate([mask, np.zeros(pad, np.float32)])
        b = (n + pad) // t_len
        b_bucket = ((b + 3) // 4) * 4
        batch = {}
        for k, v in flat.items():
            v = v.reshape((b, t_len) + v.shape[1:])
            dead = np.zeros(((b_bucket - b), t_len) + v.shape[2:], v.dtype)
            if k == "terminateds":
                dead = dead + 1
            batch[k] = np.concatenate([v, dead])
        m = mask.reshape(b, t_len)
        batch["mask"] = np.concatenate(
            [m, np.zeros((b_bucket - b, t_len), np.float32)])
        # Row boundaries may split a fragment mid-stream; such rows need a
        # bootstrap value V(first obs of the NEXT row) or their tail targets
        # would assume zero future return. Rows ending at a fragment end
        # (terminateds=1) ignore the bootstrap (discount is 0 there).
        boots = np.zeros(b_bucket, np.float32)
        flat_terms = batch["terminateds"].reshape(-1)
        need = [i for i in range(b - 1)
                if flat_terms[(i + 1) * t_len - 1] == 0]
        if need:
            next_obs = batch["obs"].reshape((-1,) + batch["obs"].shape[2:])[
                [(i + 1) * t_len for i in need]]
            vals = np.asarray(self._value_fn(self.params, next_obs))
            for i, v in zip(need, vals):
                boots[i] = v
        batch["bootstrap_value"] = boots
        return batch

    def _replenish_pipeline(self) -> None:
        """Keep max_requests_in_flight sample calls armed per CURRENT
        fleet member. Deficit-based rather than re-arm-what-returned:
        a dead runner's handle may already have been replaced in place
        by another path (sync_weights' broadcast repair), which would
        strand the replacement with zero armed calls — counting
        in-flight per live handle and topping up can never silently
        lose a pipeline slot, whoever did the replacing."""
        per = max(1, self.config.max_requests_in_flight_per_env_runner)
        n = self.config.rollout_fragment_length
        counts: Dict[int, int] = {}
        for h in self._inflight.values():
            counts[id(h)] = counts.get(id(h), 0) + 1
        for slot in range(len(self.runner_group.remotes)):
            h = self.runner_group.remotes[slot]
            deficit = per - counts.get(id(h), 0)
            while deficit > 0:
                try:
                    self._inflight[h.sample.remote(num_steps=n)] = h
                    deficit -= 1
                except exc.RayActorError:
                    # dead at submit: replace in place and keep arming
                    # the replacement (restart budget permitting)
                    h = self.runner_group.replace_runner(h)
                    if h is None:
                        break

    def _pull_decoupled(self) -> Dict[str, Any]:
        """One decoupled pull: whatever version-safe batches are queued.
        Returns {"episodes": [...], "meta": {...}}; empty episodes means
        the fleet is (re)filling the queue — the learner returns to its
        caller instead of blocking on any runner."""
        pulled = self.dataflow.pull(self.policy_version)
        episodes: List = []
        versions = []
        for entry, eps in pulled:
            episodes.extend(eps)
            versions.append(int(entry.get("policy_version", 0)))
        return {"episodes": episodes,
                "min_batch_version": min(versions) if versions else None}

    def training_step(self) -> Dict[str, Any]:
        from ray_tpu._private import event_log

        cfg = self.config
        min_batch_version = None
        if self.dataflow is not None:
            pulled = self._pull_decoupled()
            episodes = pulled["episodes"]
            min_batch_version = pulled["min_batch_version"]
        elif not self.runner_group.remotes:
            # Synchronous fallback (num_env_runners=0): sample inline.
            episodes = self.runner_group.sample(
                num_steps=cfg.rollout_fragment_length)
        else:
            ready, _ = ray_tpu.wait(
                list(self._inflight), num_returns=1, timeout=60)
            episodes = []
            for ref in ready:
                runner = self._inflight.pop(ref)
                try:
                    episodes.extend(ray_tpu.get(ref))
                except exc.RayActorError:
                    # crashable fleet, pipelined path: drop the dead
                    # runner's fragment and replace the slot in place
                    # (no-op if sync_weights already did)
                    self.runner_group.replace_runner(runner)
            # deficit-based re-arm: every CURRENT fleet member keeps its
            # full in-flight pipeline, replacements included
            self._replenish_pipeline()
        if not episodes:
            if self.dataflow is not None \
                    and self.dataflow.stale_dropped > self._stale_seen:
                # an empty pull where batches were dropped as STALE means
                # the fleet is stamping versions the learner no longer
                # accepts (restored checkpoint, broadcast_interval wider
                # than the staleness window): re-broadcast NOW or the
                # loop livelocks — no update, so the interval-gated
                # broadcast below would never fire again
                self._stale_seen = self.dataflow.stale_dropped
                self.dataflow.broadcast(self.params, self.policy_version)
                self._steps_since_broadcast = 0
            # Queue refilling / runners stalled (respawn, first-compile):
            # the learner's cadence is preserved by returning, not waiting.
            return {"num_episodes": 0}
        if self.dataflow is not None:
            self._stale_seen = self.dataflow.stale_dropped
        self._record_episodes(episodes)
        env_steps = sum(len(e) for e in episodes)
        batch = self._episodes_to_batch(episodes)
        self.params, self.opt_state, aux = self._update(
            self.params, self.opt_state, batch)
        self.policy_version += 1
        self._updates += 1
        self._steps_since_broadcast += 1
        if self._steps_since_broadcast >= cfg.broadcast_interval:
            if self.dataflow is not None:
                self.dataflow.broadcast(self.params, self.policy_version)
            else:
                self.runner_group.sync_weights(self.params,
                                               self.policy_version)
            self._steps_since_broadcast = 0
        out = {k: float(v) for k, v in aux.items()}
        out["num_episodes"] = len(episodes)
        out["policy_version"] = self.policy_version
        if self.dataflow is not None:
            # one rl.learner_step per ACTUAL update: step cadence, the
            # staleness proof (version vs min batch version vs bound) and
            # monotonic progress all derive from these events
            # (drills/slo.rl_slo)
            df = self.dataflow.stats()
            event_log.emit(
                "rl.learner_step", step=self._updates,
                version=self.policy_version, env_steps=env_steps,
                min_batch_version=min_batch_version,
                staleness_bound=self.dataflow.max_staleness,
                stale_dropped=df["stale_dropped"],
                discarded_dead=df["discarded_dead"],
                runners=df["fleet_runners"])
            out["dataflow"] = df
        return out

    def stop(self) -> None:
        if self.dataflow is not None:
            self.dataflow.stop()
        if self.runner_group is not None:
            self.runner_group.stop()


class APPOConfig(IMPALAConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = APPO
        self.appo_clip = True
        self.clip_param = 0.2


class APPO(IMPALA):
    """Asynchronous PPO (reference: ray rllib/algorithms/appo/appo.py —
    IMPALA's async actor-learner architecture with the PPO clipped
    surrogate applied to v-trace-corrected advantages)."""
