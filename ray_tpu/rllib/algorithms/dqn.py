"""DQN (reference: ray rllib/algorithms/dqn/ — epsilon-greedy sampling into
a (prioritized) replay buffer, double-Q target update, periodic target-net
sync)."""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm
from ray_tpu.rllib.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.learner import JaxLearner
from ray_tpu.rllib.replay_buffer import PrioritizedReplayBuffer, ReplayBuffer


class DQNConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=DQN)
        self.lr = 5e-4
        self.train_batch_size = 32
        self.num_steps_per_iteration = 1000


class DQNLearner(JaxLearner):
    def __init__(self, module_spec: Dict[str, Any], config: Dict[str, Any]):
        from ray_tpu.rllib.rl_module import resolve_module

        # Q-learners default to QModule — resolve_module's global default
        # is the actor-critic module, wrong for bare specs (CQL builds one)
        module_spec = dict(module_spec)
        module_spec.setdefault("module_class",
                               "ray_tpu.rllib.rl_module:QModule")
        module = resolve_module(module_spec)
        super().__init__(module, config)
        self.target_params = self.params

    def _build_update(self):
        import jax
        import jax.numpy as jnp
        import optax

        gamma = self.config.get("gamma", 0.99)

        def loss_fn(params, target_params, batch):
            q = self.module.forward(params, batch["obs"])
            q_sel = q[jnp.arange(q.shape[0]), batch["actions"]]
            q_next_online = self.module.forward(params, batch["next_obs"])
            best = jnp.argmax(q_next_online, axis=-1)
            q_next = self.module.forward(target_params, batch["next_obs"])
            q_best = q_next[jnp.arange(q_next.shape[0]), best]
            target = batch["rewards"] + gamma * q_best * (
                1.0 - batch["terminateds"])
            td = q_sel - jax.lax.stop_gradient(target)
            weights = batch.get("weights", jnp.ones_like(td))
            loss = jnp.mean(weights * td ** 2)
            return loss, {"td_error": jnp.abs(td), "qf_mean": jnp.mean(q_sel)}

        def update(params, opt_state, target_params, batch):
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, target_params, batch)
            updates, opt_state = self.optimizer.update(
                grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            aux["total_loss"] = loss
            return params, opt_state, aux

        # Donate opt_state only: params may alias target_params right after
        # a target sync (donating both args of `f(donate(a), a)` is invalid).
        return jax.jit(update, donate_argnums=(1,))

    def update_from_batch(self, batch: Dict[str, np.ndarray]
                          ) -> Dict[str, Any]:
        self.params, self.opt_state, aux = self._update(
            self.params, self.opt_state, self.target_params, batch)
        td = np.asarray(aux.pop("td_error"))
        out = {k: float(v) for k, v in aux.items()}
        out["td_error"] = td
        return out

    def sync_target(self) -> None:
        self.target_params = self.params


class DQN(Algorithm):
    def setup(self, config: AlgorithmConfig) -> None:
        from ray_tpu.rllib.exploration import EpsilonGreedy, make_exploration

        self.module_spec = self._q_module_spec(config)
        enc = (self.module_spec.get("module_kwargs") or {}).get(
            "encoder_spec") or {}
        if enc.get("kind") == "concat":
            raise NotImplementedError(
                "DQN's sampling loop supports Box/Discrete observations; "
                "Dict/Tuple observation spaces are not wired here yet "
                "(PPO's connector path handles them)")
        num_actions = self.module_spec["num_actions"]
        cfg = config.to_dict()
        # exploration_config (reference: utils/exploration/) takes priority;
        # the legacy `epsilon` piecewise schedule maps onto EpsilonGreedy
        expl_cfg = cfg.get("exploration_config")
        if expl_cfg:
            self.exploration = make_exploration(expl_cfg,
                                                default="EpsilonGreedy")
        else:
            self.exploration = EpsilonGreedy(schedule=config.epsilon)
        self.learner = DQNLearner(self.module_spec, cfg)
        buf_cfg = config.replay_buffer_config
        buf_cls = PrioritizedReplayBuffer \
            if buf_cfg.get("type") == "PrioritizedReplayBuffer" \
            else ReplayBuffer
        self.buffer = buf_cls(capacity=buf_cfg.get("capacity", 50_000))
        self._rng = np.random.default_rng(config.seed)
        import gymnasium as gym

        self.env = gym.make(config.env, **(config.env_config or {}))
        self._obs, _ = self.env.reset(seed=config.seed)
        self._ep_return = 0.0
        self._num_actions = num_actions
        import jax

        self._q_fwd = jax.jit(self.learner.module.forward)
        self._steps_since_target_sync = 0

    def _epsilon(self) -> float:
        if hasattr(self.exploration, "epsilon"):
            return self.exploration.epsilon(
                self._num_env_steps_sampled_lifetime)
        return 0.0

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        metrics: Dict[str, Any] = {}
        for _ in range(cfg.num_steps_per_iteration):
            def _greedy():
                q = self._q_fwd(
                    self.learner.params,
                    np.asarray(self._obs, np.float32)[None, ...])
                return int(np.argmax(np.asarray(q)[0]))

            action = self.exploration.select_discrete(
                self._num_env_steps_sampled_lifetime, _greedy,
                self._num_actions, self._rng)
            next_obs, reward, term, trunc, _ = self.env.step(action)
            self.buffer.add({
                # asarray: Discrete envs emit plain ints (the catalog
                # encoder one-hots them on device)
                "obs": np.asarray(self._obs, np.float32),
                "next_obs": np.asarray(next_obs, dtype=np.float32),
                "actions": np.int32(action),
                "rewards": np.float32(reward),
                "terminateds": np.float32(term),
            })
            self._num_env_steps_sampled_lifetime += 1
            self._ep_return += float(reward)
            if term or trunc:
                self._episode_returns.append(self._ep_return)
                self._ep_return = 0.0
                self._obs, _ = self.env.reset()
            else:
                self._obs = next_obs

            if (self._num_env_steps_sampled_lifetime
                    >= cfg.num_steps_sampled_before_learning_starts
                    and len(self.buffer) >= cfg.train_batch_size):
                batch = self.buffer.sample(cfg.train_batch_size)
                out = self.learner.update_from_batch(batch)
                td = out.pop("td_error")
                if isinstance(self.buffer, PrioritizedReplayBuffer):
                    self.buffer.update_priorities(
                        batch["batch_indexes"], td)
                metrics = out
                self._steps_since_target_sync += 1
                if (self._steps_since_target_sync
                        >= cfg.target_network_update_freq):
                    self.learner.sync_target()
                    self._steps_since_target_sync = 0
        metrics["buffer_size"] = len(self.buffer)
        metrics["epsilon"] = self._epsilon()
        return metrics

    def stop(self) -> None:
        self.env.close()
