from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig  # noqa: F401
from ray_tpu.rllib.algorithms.ppo import PPO, PPOConfig  # noqa: F401

__all__ = ["DQN", "DQNConfig", "PPO", "PPOConfig"]
