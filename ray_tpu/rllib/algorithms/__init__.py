from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig  # noqa: F401
from ray_tpu.rllib.algorithms.impala import IMPALA, IMPALAConfig  # noqa: F401
from ray_tpu.rllib.algorithms.ppo import PPO, PPOConfig  # noqa: F401
from ray_tpu.rllib.algorithms.sac import SAC, SACConfig  # noqa: F401

__all__ = ["DQN", "DQNConfig", "IMPALA", "IMPALAConfig", "PPO", "PPOConfig",
           "SAC", "SACConfig"]
