from ray_tpu.rllib.algorithms.cql import CQL, CQLConfig  # noqa: F401
from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig  # noqa: F401
from ray_tpu.rllib.algorithms.dreamerv3 import (  # noqa: F401
    DreamerV3,
    DreamerV3Config,
)
from ray_tpu.rllib.algorithms.impala import (  # noqa: F401
    APPO,
    APPOConfig,
    IMPALA,
    IMPALAConfig,
)
from ray_tpu.rllib.algorithms.marwil import (  # noqa: F401
    BC,
    BCConfig,
    MARWIL,
    MARWILConfig,
)
from ray_tpu.rllib.algorithms.ppo import PPO, PPOConfig  # noqa: F401
from ray_tpu.rllib.algorithms.sac import SAC, SACConfig  # noqa: F401

__all__ = ["APPO", "APPOConfig", "BC", "BCConfig", "CQL", "CQLConfig",
           "DQN", "DQNConfig", "DreamerV3", "DreamerV3Config", "IMPALA",
           "IMPALAConfig", "MARWIL", "MARWILConfig", "PPO", "PPOConfig",
           "SAC", "SACConfig"]
