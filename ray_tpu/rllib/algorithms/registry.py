"""Algorithm registry: resolve algorithms by name.

Reference: ray rllib/algorithms/registry.py (get_algorithm_class) — used
by Tune string trainables ("PPO") and the CLI.
"""

from __future__ import annotations

__all__ = ["get_algorithm_class", "ALGORITHMS"]


def _table():
    from ray_tpu.rllib import algorithms as a

    return {
        "PPO": a.PPO, "APPO": a.APPO, "IMPALA": a.IMPALA, "DQN": a.DQN,
        "SAC": a.SAC, "BC": a.BC, "MARWIL": a.MARWIL, "CQL": a.CQL,
        "DreamerV3": a.DreamerV3,
    }


ALGORITHMS = tuple(("PPO", "APPO", "IMPALA", "DQN", "SAC", "BC", "MARWIL",
                    "CQL", "DreamerV3"))


def get_algorithm_class(name: str):
    table = _table()
    if name not in table:
        raise ValueError(
            f"unknown algorithm {name!r}; available: {sorted(table)}")
    return table[name]
