"""CQL — Conservative Q-Learning on offline data (reference: ray
rllib/algorithms/cql/cql.py; Kumar et al. 2020).

Discrete-action CQL(H): the double-Q TD loss of DQN plus
alpha * E[logsumexp_a Q(s,a) - Q(s, a_data)], which pushes down
out-of-distribution action values so the greedy policy stays inside the
dataset's support. (The reference builds CQL on SAC for continuous control;
on a discrete action space the same penalty applies exactly, without the
sampling approximation the continuous version needs.)
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm
from ray_tpu.rllib.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.algorithms.dqn import DQNLearner


class CQLConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=CQL)
        self.cql_alpha = 1.0
        self.lr = 5e-4
        self.train_batch_size = 256
        self.num_updates_per_iteration = 200
        self.target_network_update_freq = 100


class CQLLearner(DQNLearner):
    def _build_update(self):
        import jax
        import jax.numpy as jnp
        import optax

        gamma = self.config.get("gamma", 0.99)
        alpha = self.config.get("cql_alpha", 1.0)

        def loss_fn(params, target_params, batch):
            q = self.module.forward(params, batch["obs"])
            idx = jnp.arange(q.shape[0])
            q_data = q[idx, batch["actions"]]
            # double-Q TD target
            q_next_online = self.module.forward(params, batch["next_obs"])
            best = jnp.argmax(q_next_online, axis=-1)
            q_next = self.module.forward(target_params, batch["next_obs"])
            target = batch["rewards"] + gamma * q_next[idx, best] * (
                1.0 - batch["terminateds"])
            td_loss = jnp.mean(
                (q_data - jax.lax.stop_gradient(target)) ** 2)
            # CQL(H) conservative penalty
            cql_penalty = jnp.mean(
                jax.scipy.special.logsumexp(q, axis=-1) - q_data)
            loss = td_loss + alpha * cql_penalty
            return loss, {"td_loss": td_loss, "cql_penalty": cql_penalty,
                          "qf_mean": jnp.mean(q_data)}

        def update(params, opt_state, target_params, batch):
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, target_params, batch)
            updates, opt_state = self.optimizer.update(
                grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            aux["total_loss"] = loss
            return params, opt_state, aux

        return jax.jit(update, donate_argnums=(1,))

    def update_from_batch(self, batch: Dict[str, np.ndarray]
                          ) -> Dict[str, Any]:
        self.params, self.opt_state, aux = self._update(
            self.params, self.opt_state, self.target_params, batch)
        return {k: float(v) for k, v in aux.items()}


class CQL(Algorithm):
    def setup(self, config: AlgorithmConfig) -> None:
        from ray_tpu.rllib.offline import load_episode_batches

        obs_dim, num_actions = self._env_spaces(config.env, config.env_config)
        self.module_spec = {
            "obs_dim": obs_dim, "num_actions": num_actions,
            "hiddens": tuple(config.model.get("fcnet_hiddens", (64, 64))),
        }
        self.learner = CQLLearner(self.module_spec, config.to_dict())
        episodes = load_episode_batches(config.input_)
        cols = {"obs": [], "next_obs": [], "actions": [], "rewards": [],
                "terminateds": []}
        for ep in episodes:
            cols["obs"].append(np.asarray(ep["obs"], dtype=np.float32))
            cols["next_obs"].append(
                np.asarray(ep["next_obs"], dtype=np.float32))
            cols["actions"].append(np.asarray(ep["actions"], dtype=np.int32))
            cols["rewards"].append(
                np.asarray(ep["rewards"], dtype=np.float32))
            cols["terminateds"].append(
                np.asarray(ep["terminateds"], dtype=np.float32))
        self._data = {k: np.concatenate(v) for k, v in cols.items()}
        self._rng = np.random.default_rng(config.seed)
        self._steps_since_sync = 0
        self._eval_env = None

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        n = len(self._data["obs"])
        metrics: Dict[str, Any] = {}
        for _ in range(cfg.num_updates_per_iteration):
            idx = self._rng.integers(0, n,
                                     size=min(cfg.train_batch_size, n))
            metrics = self.learner.update_from_batch(
                {k: v[idx] for k, v in self._data.items()})
            self._steps_since_sync += 1
            if self._steps_since_sync >= cfg.target_network_update_freq:
                self.learner.sync_target()
                self._steps_since_sync = 0
        metrics["num_offline_transitions"] = n
        if (cfg.evaluation_interval
                and self.iteration % cfg.evaluation_interval == 0):
            metrics["evaluation"] = self.evaluate()
        return metrics

    def evaluate(self) -> Dict[str, Any]:
        import gymnasium as gym
        import jax

        cfg = self.config
        if self._eval_env is None:
            self._eval_env = gym.make(cfg.env, **(cfg.env_config or {}))
            self._fwd = jax.jit(self.learner.module.forward)
        returns = []
        for _ in range(cfg.evaluation_duration):
            obs, _ = self._eval_env.reset()
            done = trunc = False
            total = 0.0
            while not (done or trunc):
                q = self._fwd(self.learner.params,
                              np.asarray(obs, dtype=np.float32)[None, :])
                action = int(np.argmax(np.asarray(q)[0]))
                obs, r, done, trunc, _ = self._eval_env.step(action)
                total += float(r)
            returns.append(total)
        return {"episode_return_mean": float(np.mean(returns)),
                "num_episodes": len(returns)}

    def get_state(self) -> Dict[str, Any]:
        state = super().get_state()
        state["learner"] = self.learner.get_state()
        return state

    def set_state(self, state: Dict[str, Any]) -> None:
        super().set_state(state)
        if "learner" in state:
            self.learner.set_state(state["learner"])

    def stop(self) -> None:
        if self._eval_env is not None:
            self._eval_env.close()
