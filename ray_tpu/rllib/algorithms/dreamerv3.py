"""DreamerV3: model-based RL — learn a world model, act in imagination.

Reference: ray rllib/algorithms/dreamerv3/ (dreamerv3.py, the tf2 RSSM in
utils/model_sizes + the world-model/actor/critic triple). This is a
defensibly-scoped JAX reimplementation of the core method:

  * RSSM world model: obs encoder -> GRU deterministic state h; posterior
    z ~ Cat(groups x classes) from [h, embed]; prior from h alone; decoder,
    reward head (symlog), continue head. KL-balanced dyn/rep losses with
    free bits (the V3 trick that makes one hyperparameter set work).
  * Straight-through categorical latents (V3's discrete codes).
  * Actor-critic trained purely in IMAGINATION: roll the prior forward
    H steps with the actor, lambda-returns on predicted rewards/continues,
    REINFORCE policy gradient (V3's discrete-action estimator) with
    return normalization and entropy regularization.

Scoped down vs the reference: vector observations only (the catalog's MLP
encoder — no image CNN decoder), fixed model dims instead of the XS..XL
size table, no replay-ratio scheduling.

Whole-sequence training runs as one jit (lax.scan over time), so the hot
loop is a single XLA program per batch — TPU-friendly by construction.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm
from ray_tpu.rllib.algorithm_config import AlgorithmConfig


def _symlog(x):
    import jax.numpy as jnp

    return jnp.sign(x) * jnp.log1p(jnp.abs(x))


def _symexp(x):
    import jax.numpy as jnp

    return jnp.sign(x) * (jnp.exp(jnp.abs(x)) - 1.0)


class DreamerV3Config(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=DreamerV3)
        self.lr = 3e-4
        self.actor_lr = 1e-4
        self.critic_lr = 1e-4
        # model dims (a "nano" row of the reference's size table)
        self.deter_dim = 128          # GRU/deterministic state
        self.stoch_groups = 8
        self.stoch_classes = 8
        self.embed_dim = 64
        self.hidden_dim = 128
        self.batch_size_B = 8         # sequences per world-model batch
        self.batch_length_T = 16
        self.horizon_H = 10           # imagination rollout length
        self.gamma = 0.99
        self.gae_lambda = 0.95
        self.entropy_coeff = 3e-3
        self.free_bits = 1.0
        self.kl_dyn_scale = 0.5
        self.kl_rep_scale = 0.1
        self.train_ratio = 32         # model updates per iteration
        self.num_steps_per_iteration = 400
        self.buffer_capacity = 100_000
        self.num_steps_sampled_before_learning_starts = 400

    def training(self, **kwargs) -> "DreamerV3Config":
        for k, v in kwargs.items():
            setattr(self, k, v)
        return self


class _SeqBuffer:
    """Episode-segment replay: stores transitions contiguously per episode
    and samples [B, T] windows (reference: dreamerv3's EpisodeReplayBuffer).

    Dreamer ARRIVAL convention: entry t is (obs_t, a_t, r_t, c_t) where
    a_t is the action chosen AT obs_t, while r_t / c_t describe ARRIVING
    at obs_t (reward emitted by the previous transition; c_t == 0 iff
    obs_t is terminal). The terminal observation IS stored (with a dummy
    action) — without it the continue head never sees a zero label and
    imagination can never predict episode end."""

    def __init__(self, capacity: int):
        self.capacity = capacity
        self.episodes: List[Dict[str, list]] = []
        self._cur: Optional[Dict[str, list]] = None
        self.size = 0

    def start_episode(self):
        self._cur = {"obs": [], "actions": [], "rewards": [], "cont": []}

    def add(self, obs, action, reward, cont):
        self._cur["obs"].append(np.asarray(obs, np.float32))
        self._cur["actions"].append(int(action))
        self._cur["rewards"].append(float(reward))
        self._cur["cont"].append(float(cont))
        self.size += 1

    def end_episode(self):
        if self._cur and len(self._cur["obs"]) >= 2:
            self.episodes.append({
                k: np.asarray(v) for k, v in self._cur.items()})
        self._cur = None
        while self.size > self.capacity and self.episodes:
            self.size -= len(self.episodes.pop(0)["obs"])

    def sample(self, rng, B: int, T: int) -> Optional[Dict[str, np.ndarray]]:
        pool = [ep for ep in self.episodes if len(ep["obs"]) >= T]
        if not pool:
            return None
        out = {k: [] for k in ("obs", "actions", "rewards", "cont")}
        for _ in range(B):
            ep = pool[int(rng.integers(len(pool)))]
            start = int(rng.integers(len(ep["obs"]) - T + 1))
            for k in out:
                out[k].append(ep[k][start:start + T])
        return {k: np.stack(v) for k, v in out.items()}


class DreamerV3(Algorithm):
    def setup(self, config: DreamerV3Config) -> None:
        import gymnasium as gym
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.rllib.rl_module import _dense, _dense_init

        cfg = config
        self.env = gym.make(cfg.env, **(cfg.env_config or {}))
        obs_dim = int(self.env.observation_space.shape[0])
        num_actions = int(self.env.action_space.n)
        self.obs_dim, self.num_actions = obs_dim, num_actions
        G, C = cfg.stoch_groups, cfg.stoch_classes
        Z = G * C
        D, E, H = cfg.deter_dim, cfg.embed_dim, cfg.hidden_dim

        key = jax.random.PRNGKey(cfg.seed or 0)
        ks = iter(jax.random.split(key, 24))

        def mlp_init(sizes):
            return [_dense_init(next(ks), a, b)
                    for a, b in zip(sizes, sizes[1:])]

        wm = {
            "enc": mlp_init([obs_dim, E, E]),
            # GRU over [z, a] with state h: fused gate weights
            "gru_x": _dense_init(next(ks), Z + num_actions, 3 * D),
            "gru_h": _dense_init(next(ks), D, 3 * D),
            "post": mlp_init([D + E, H, Z]),
            "prior": mlp_init([D, H, Z]),
            "dec": mlp_init([D + Z, H, obs_dim]),
            "rew": mlp_init([D + Z, H, 1]),
            "cont": mlp_init([D + Z, H, 1]),
        }
        actor = mlp_init([D + Z, H, num_actions])
        critic = mlp_init([D + Z, H, 1])
        self.params = {"wm": wm, "actor": actor, "critic": critic}

        self.wm_opt = optax.adam(cfg.lr)
        self.actor_opt = optax.adam(cfg.actor_lr)
        self.critic_opt = optax.adam(cfg.critic_lr)
        self.opt_state = {
            "wm": self.wm_opt.init(wm),
            "actor": self.actor_opt.init(actor),
            "critic": self.critic_opt.init(critic),
        }

        def mlp(layers, x, act=jax.nn.silu):
            for p in layers[:-1]:
                x = act(_dense(p, x))
            return _dense(layers[-1], x)

        def gru(p, h, x):
            gates = _dense(p["gru_x"], x) + _dense(p["gru_h"], h)
            r, u, c = jnp.split(gates, 3, axis=-1)
            r, u = jax.nn.sigmoid(r), jax.nn.sigmoid(u)
            cand = jnp.tanh(r * c)
            return u * h + (1 - u) * cand

        def _unimix(lg):
            """V3's 1% uniform mixture over latent classes: keeps the
            categorical from saturating (stabilizes the KL terms)."""
            probs = 0.99 * jax.nn.softmax(lg) + 0.01 / C
            return jnp.log(probs)

        def sample_latent(logits, k):
            """Straight-through one-hot sample per group -> flat [.., Z]."""
            lg = _unimix(logits.reshape(*logits.shape[:-1], G, C))
            idx = jax.random.categorical(k, lg)
            one = jax.nn.one_hot(idx, C)
            prob = jnp.exp(lg)
            st = one + prob - jax.lax.stop_gradient(prob)
            return st.reshape(*logits.shape[:-1], Z)

        def kl_cat(lhs_logits, rhs_logits):
            """KL( Cat(lhs) || Cat(rhs) ) summed over groups."""
            lhs = lhs_logits.reshape(*lhs_logits.shape[:-1], G, C)
            rhs = rhs_logits.reshape(*rhs_logits.shape[:-1], G, C)
            lp, lq = _unimix(lhs), _unimix(rhs)
            return jnp.sum(jnp.exp(lp) * (lp - lq), axis=(-2, -1))

        def observe_seq(wm_p, obs_seq, act_seq, k):
            """Filter a [B, T, ...] batch through the RSSM.
            -> (h_seq, z_seq, post_logits, prior_logits)."""
            B = obs_seq.shape[0]
            embed = mlp(wm_p["enc"], _symlog(obs_seq))

            def step(carry, xs):
                h, z, kk = carry
                emb_t, act_t = xs
                kk, k1 = jax.random.split(kk)
                x = jnp.concatenate([z, act_t], -1)
                h = gru(wm_p, h, x)
                prior_lg = mlp(wm_p["prior"], h)
                post_lg = mlp(wm_p["post"],
                              jnp.concatenate([h, emb_t], -1))
                z = sample_latent(post_lg, k1)
                return (h, z, kk), (h, z, post_lg, prior_lg)

            h0 = jnp.zeros((B, D))
            z0 = jnp.zeros((B, Z))
            xs = (jnp.swapaxes(embed, 0, 1), jnp.swapaxes(act_seq, 0, 1))
            (_, _, _), (hs, zs, post_lg, prior_lg) = jax.lax.scan(
                step, (h0, z0, k), xs)
            sw = lambda a: jnp.swapaxes(a, 0, 1)  # noqa: E731
            return sw(hs), sw(zs), sw(post_lg), sw(prior_lg)

        def wm_loss(wm_p, batch, k):
            obs = batch["obs"]                       # [B, T, obs]
            acts = jax.nn.one_hot(batch["actions"], num_actions)
            # h_t must condition on the PREVIOUS step's action (the one
            # whose transition ARRIVED at obs_t) — exactly how the acting
            # path rolls h forward (policy_step). Conditioning on a_t
            # would train the model on future information and make
            # imagination diverge from reality.
            acts_prev = jnp.concatenate(
                [jnp.zeros_like(acts[:, :1]), acts[:, :-1]], axis=1)
            hs, zs, post_lg, prior_lg = observe_seq(
                wm_p, obs, acts_prev, k)
            feat = jnp.concatenate([hs, zs], -1)
            recon = mlp(wm_p["dec"], feat)
            rew = mlp(wm_p["rew"], feat)[..., 0]
            cont_logit = mlp(wm_p["cont"], feat)[..., 0]
            l_rec = jnp.mean(jnp.sum(
                (recon - _symlog(obs)) ** 2, -1))
            l_rew = jnp.mean((rew - _symlog(batch["rewards"])) ** 2)
            cont = batch["cont"]
            l_cont = jnp.mean(
                jnp.maximum(cont_logit, 0) - cont_logit * cont
                + jnp.log1p(jnp.exp(-jnp.abs(cont_logit))))
            # KL balance with free bits (V3): dyn pulls prior to posterior,
            # rep (small) pulls posterior toward prior
            sg = jax.lax.stop_gradient
            kl_dyn = jnp.maximum(
                cfg.free_bits, jnp.mean(kl_cat(sg(post_lg), prior_lg)))
            kl_rep = jnp.maximum(
                cfg.free_bits, jnp.mean(kl_cat(post_lg, sg(prior_lg))))
            loss = (l_rec + l_rew + l_cont
                    + cfg.kl_dyn_scale * kl_dyn
                    + cfg.kl_rep_scale * kl_rep)
            return loss, (hs, zs, l_rec, l_rew, kl_dyn)

        def imagine(wm_p, actor_p, h0, z0, k):
            """Roll the PRIOR forward H steps with the actor.
            -> feats [H+1, N, D+Z], actions [H, N], logps, entropy."""

            def step(carry, _):
                h, z, kk = carry
                kk, k1, k2 = jax.random.split(kk, 3)
                feat = jnp.concatenate([h, z], -1)
                logits = mlp(actor_p, feat)
                a = jax.random.categorical(k1, logits)
                lp_all = jax.nn.log_softmax(logits)
                lp = jnp.take_along_axis(lp_all, a[:, None], 1)[:, 0]
                ent = -jnp.sum(jnp.exp(lp_all) * lp_all, -1)
                a1 = jax.nn.one_hot(a, num_actions)
                h = gru(wm_p, h, jnp.concatenate([z, a1], -1))
                z = sample_latent(mlp(wm_p["prior"], h), k2)
                return (h, z, kk), (feat, a, lp, ent)

            (h, z, _), (feats, acts, lps, ents) = jax.lax.scan(
                step, (h0, z0, k), None, length=cfg.horizon_H)
            last = jnp.concatenate([h, z], -1)[None]
            return jnp.concatenate([feats, last], 0), acts, lps, ents

        def lambda_returns(rew, cont, values):
            """V3's bootstrapped lambda-return over imagined steps."""
            lam, gamma = cfg.gae_lambda, cfg.gamma

            def step(nxt, xs):
                r_t, c_t, v_next = xs
                ret = r_t + gamma * c_t * (
                    (1 - lam) * v_next + lam * nxt)
                return ret, ret

            _, rets = jax.lax.scan(
                step, values[-1],
                (rew, cont, values[1:]), reverse=True)
            return rets

        def ac_losses(actor_p, critic_p, wm_p, h0, z0, k):
            feats, acts, lps, ents = imagine(wm_p, actor_p, h0, z0, k)
            sg = jax.lax.stop_gradient
            feats = sg(feats)  # REINFORCE: no grad through the dynamics
            rew = _symexp(mlp(wm_p["rew"], feats)[1:, :, 0])
            cont = jax.nn.sigmoid(mlp(wm_p["cont"], feats)[1:, :, 0])
            values = mlp(critic_p, feats)[..., 0]
            rets = lambda_returns(rew, cont, sg(values))
            # return normalization (V3: scale by range percentiles)
            scale = jnp.maximum(
                1.0, jnp.percentile(rets, 95) - jnp.percentile(rets, 5))
            adv = sg((rets - values[:-1]) / scale)
            actor_loss = -jnp.mean(lps * adv) - cfg.entropy_coeff * \
                jnp.mean(ents)
            critic_loss = jnp.mean((values[:-1] - sg(rets)) ** 2)
            return actor_loss, critic_loss, jnp.mean(rets)

        def train_step(params, opt_state, batch, k):
            k1, k2 = jax.random.split(k)
            (wml, (hs, zs, l_rec, l_rew, kld)), wm_grad = \
                jax.value_and_grad(wm_loss, has_aux=True)(
                    params["wm"], batch, k1)
            upd, wm_os = self.wm_opt.update(
                wm_grad, opt_state["wm"], params["wm"])
            wm_p = optax.apply_updates(params["wm"], upd)

            # imagination starts from every posterior state in the batch
            h0 = hs.reshape(-1, D)
            z0 = zs.reshape(-1, Z)

            def a_loss(ap):
                al, _cl, ret = ac_losses(ap, params["critic"], wm_p,
                                         h0, z0, k2)
                return al, ret

            (al, ret), a_grad = jax.value_and_grad(
                a_loss, has_aux=True)(params["actor"])
            upd, a_os = self.actor_opt.update(
                a_grad, opt_state["actor"], params["actor"])
            actor_p = optax.apply_updates(params["actor"], upd)

            def c_loss(cp):
                _al, cl, _ = ac_losses(actor_p, cp, wm_p, h0, z0, k2)
                return cl

            cl, c_grad = jax.value_and_grad(c_loss)(params["critic"])
            upd, c_os = self.critic_opt.update(
                c_grad, opt_state["critic"], params["critic"])
            critic_p = optax.apply_updates(params["critic"], upd)
            new_params = {"wm": wm_p, "actor": actor_p, "critic": critic_p}
            new_os = {"wm": wm_os, "actor": a_os, "critic": c_os}
            metrics = {"wm_loss": wml, "recon_loss": l_rec,
                       "reward_loss": l_rew, "kl_dyn": kld,
                       "actor_loss": al, "critic_loss": cl,
                       "imagined_return": ret}
            return new_params, new_os, metrics

        self._train_step = jax.jit(train_step)

        def policy_step(params, h, z, obs, k):
            """Filtered acting in the real env (posterior latents)."""
            k1, k2 = jax.random.split(k)
            emb = mlp(params["wm"]["enc"], _symlog(obs))
            post_lg = mlp(params["wm"]["post"],
                          jnp.concatenate([h, emb], -1))
            z = sample_latent(post_lg, k1)
            feat = jnp.concatenate([h, z], -1)
            a = jax.random.categorical(k2, mlp(params["actor"], feat))
            a1 = jax.nn.one_hot(a, num_actions)
            h = gru(params["wm"], h, jnp.concatenate([z, a1], -1))
            return h, z, a

        self._policy_step = jax.jit(policy_step)
        self._h = np.zeros((1, D), np.float32)
        self._z = np.zeros((1, Z), np.float32)
        self._jkey = jax.random.PRNGKey((cfg.seed or 0) + 1)
        self.buffer = _SeqBuffer(cfg.buffer_capacity)
        self.buffer.start_episode()
        self._rng = np.random.default_rng(cfg.seed)
        self._obs, _ = self.env.reset(seed=cfg.seed)
        self._ep_return = 0.0
        # arrival labels for the NEXT buffer entry (see _SeqBuffer)
        self._arrival_reward = 0.0
        self._arrival_cont = 1.0
        self._D, self._Z = D, Z

    def training_step(self) -> Dict[str, Any]:
        import jax
        import numpy as np

        cfg = self.config
        metrics: Dict[str, Any] = {}
        for _ in range(cfg.num_steps_per_iteration):
            self._jkey, sub = jax.random.split(self._jkey)
            h, z, a = self._policy_step(
                self.params, self._h, self._z,
                np.asarray(self._obs, np.float32)[None], sub)
            self._h, self._z = np.asarray(h), np.asarray(z)
            action = int(np.asarray(a)[0])
            # entry for the CURRENT obs: its chosen action + the arrival
            # labels stashed when we got here
            self.buffer.add(self._obs, action, self._arrival_reward,
                            self._arrival_cont)
            next_obs, reward, term, trunc, _ = self.env.step(action)
            self._arrival_reward = float(reward)
            self._arrival_cont = 0.0 if term else 1.0
            self._num_env_steps_sampled_lifetime += 1
            self._ep_return += float(reward)
            if term or trunc:
                # terminal/truncation ARRIVAL state (dummy action)
                self.buffer.add(next_obs, 0, self._arrival_reward,
                                self._arrival_cont)
                self._episode_returns.append(self._ep_return)
                self._ep_return = 0.0
                self.buffer.end_episode()
                self.buffer.start_episode()
                self._obs, _ = self.env.reset()
                self._arrival_reward = 0.0
                self._arrival_cont = 1.0
                self._h = np.zeros((1, self._D), np.float32)
                self._z = np.zeros((1, self._Z), np.float32)
            else:
                self._obs = next_obs

        if (self._num_env_steps_sampled_lifetime
                >= cfg.num_steps_sampled_before_learning_starts):
            for _ in range(cfg.train_ratio):
                batch = self.buffer.sample(
                    self._rng, cfg.batch_size_B, cfg.batch_length_T)
                if batch is None:
                    break
                self._jkey, sub = jax.random.split(self._jkey)
                self.params, self.opt_state, m = self._train_step(
                    self.params, self.opt_state, batch, sub)
                metrics = {k: float(v) for k, v in m.items()}
        metrics["buffer_size"] = self.buffer.size
        return metrics

    def get_state(self):
        return {"params": self.params,
                "counters": {
                    "env_steps": self._num_env_steps_sampled_lifetime}}

    def set_state(self, state):
        self.params = state["params"]

    def stop(self) -> None:
        self.env.close()
