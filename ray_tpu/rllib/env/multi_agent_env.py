"""MultiAgentEnv API (reference: ray rllib/env/multi_agent_env.py —
dict-keyed reset/step with the "__all__" terminated/truncated convention).

Subclasses define `possible_agents` and per-agent spaces
(`observation_spaces` / `action_spaces` dicts), then:

    obs, infos = env.reset(seed=...)
    obs, rewards, terminateds, truncateds, infos = env.step(action_dict)

Each returned dict is keyed by agent id and includes only agents alive that
step; `terminateds["__all__"]` / `truncateds["__all__"]` end the episode.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple


class MultiAgentEnv:
    possible_agents: List[Any] = []
    observation_spaces: Dict[Any, Any] = {}
    action_spaces: Dict[Any, Any] = {}

    def reset(self, *, seed: Optional[int] = None,
              options: Optional[dict] = None
              ) -> Tuple[Dict[Any, Any], Dict[Any, dict]]:
        raise NotImplementedError

    def step(self, action_dict: Dict[Any, Any]):
        raise NotImplementedError

    def observation_space(self, agent_id) -> Any:
        return self.observation_spaces[agent_id]

    def action_space(self, agent_id) -> Any:
        return self.action_spaces[agent_id]

    def close(self) -> None:
        pass
