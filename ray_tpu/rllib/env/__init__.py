"""Env APIs (reference: ray rllib/env/ — MultiAgentEnv multi_agent_env.py;
single-agent runners live in ray_tpu.rllib.env_runner)."""

from ray_tpu.rllib.env.multi_agent_env import MultiAgentEnv  # noqa: F401

__all__ = ["MultiAgentEnv"]
