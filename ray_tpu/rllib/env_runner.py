"""EnvRunner: samples episodes from gymnasium vector envs.

Reference: ray rllib/env/single_agent_env_runner.py:124 (sample loop over
gymnasium vector envs with RLModule.forward_exploration) and
env/env_runner_group.py (the actor group with weight sync). The action
step is one jit (module forward + categorical sample) so the hot loop is
env.step + a single device call.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu.rllib.episode import SingleAgentEpisode


def make_env(env_id, env_config: Optional[dict] = None):
    """env_id: a gym id, an env-creator callable, or "ALE/..." (routed
    through the Atari preprocessing pipeline). ray_tpu/-prefixed built-in
    envs self-register on first use."""
    import gymnasium as gym

    if callable(env_id):
        return env_id(**(env_config or {}))
    if isinstance(env_id, str) and env_id.startswith("ALE/"):
        from ray_tpu.rllib.atari import make_atari_env

        # pipeline knobs route to the wrapper; everything else is a plain
        # gym.make kwarg (full_action_space, mode, ...)
        cfg = dict(env_config or {})
        pipeline = {k: cfg.pop(k)
                    for k in ("frame_stack", "screen_size", "frameskip")
                    if k in cfg}
        return make_atari_env(env_id, **pipeline, env_config=cfg)
    if isinstance(env_id, str) and env_id.startswith("ray_tpu/"):
        from ray_tpu.rllib.atari import register_synthetic_env

        register_synthetic_env()
    return gym.make(env_id, **(env_config or {}))


class EnvRunner:
    """One sampling worker (used inline with num_env_runners=0, or as an
    actor in an EnvRunnerGroup)."""

    def __init__(self, config: Dict[str, Any], module_spec: Dict[str, Any],
                 worker_index: int = 0):
        import gymnasium as gym
        import jax

        self.config = config
        self.worker_index = worker_index
        n_envs = config.get("num_envs_per_env_runner", 1)
        self.envs = gym.vector.SyncVectorEnv(
            [partial(make_env, config["env"], config.get("env_config"))
             for _ in range(n_envs)])
        self.n_envs = n_envs
        from ray_tpu.rllib.rl_module import resolve_module

        self.module = resolve_module(module_spec)
        # Continuous (Box) action spaces: module outputs live in [-1,1];
        # rescale into the env bounds at the boundary.
        space = self.envs.single_action_space
        self._act_scale = None
        if hasattr(space, "low") and hasattr(space, "high"):
            low = np.asarray(space.low, np.float32)
            high = np.asarray(space.high, np.float32)
            self._act_scale = ((high - low) / 2.0, (high + low) / 2.0)
        seed = (config.get("seed") or 0) * 1000 + worker_index
        self._key = jax.random.PRNGKey(seed)
        self.params = None

        @jax.jit
        def _act(params, obs, key):
            return self.module.forward_exploration(
                params, {"obs": obs}, key)

        self._act = _act

        @jax.jit
        def _act_greedy(params, obs):
            return self.module.forward_inference(params, {"obs": obs})

        self._act_greedy = _act_greedy
        self._obs, _ = self.envs.reset(seed=seed)
        self._episodes = [SingleAgentEpisode() for _ in range(n_envs)]
        for i, ep in enumerate(self._episodes):
            ep.add_env_reset(self._obs[i])

    def set_weights(self, params) -> None:
        self.params = params

    def get_weights(self):
        return self.params

    def sample(self, *, num_steps: Optional[int] = None,
               explore: bool = True,
               random_actions: bool = False
               ) -> List[SingleAgentEpisode]:
        """Collect num_steps env steps (per vector env slot), returning
        completed + truncated-in-progress episodes."""
        import jax

        assert self.params is not None or random_actions, \
            "set_weights first"
        num_steps = num_steps or self.config.get(
            "rollout_fragment_length", 200)
        done_episodes: List[SingleAgentEpisode] = []
        for _ in range(num_steps):
            env_actions = None
            if random_actions:
                sampled = np.stack([
                    self.envs.single_action_space.sample()
                    for _ in range(self.n_envs)])
                if self._act_scale is not None:
                    # Store module-space [-1,1] actions; send env units.
                    scale, offset = self._act_scale
                    actions = (sampled - offset) / np.where(scale == 0, 1, scale)
                    env_actions = sampled
                else:
                    actions = sampled
                extra: Dict[str, np.ndarray] = {}
            else:
                self._key, sub = jax.random.split(self._key)
                # uint8 image obs ship raw (1 byte/pixel) and normalize
                # on-device inside the module; everything else goes float32
                obs_in = (self._obs if self._obs.dtype == np.uint8
                          else self._obs.astype(np.float32))
                if explore:
                    out = self._act(self.params, obs_in, sub)
                    extra = {"logp": np.asarray(out["logp"]),
                             "vf_preds": np.asarray(out["vf_preds"])}
                else:
                    out = self._act_greedy(self.params, obs_in)
                    extra = {}
                actions = np.asarray(out["actions"])
            if env_actions is None:
                env_actions = actions
                if self._act_scale is not None:
                    scale, offset = self._act_scale
                    env_actions = actions * scale + offset
            next_obs, rewards, terms, truncs, infos = self.envs.step(env_actions)
            for i in range(self.n_envs):
                per_step_extra = {k: v[i] for k, v in extra.items()}
                self._episodes[i].add_env_step(
                    next_obs[i], actions[i], rewards[i],
                    terminated=bool(terms[i]), truncated=bool(truncs[i]),
                    **per_step_extra)
                if terms[i] or truncs[i]:
                    done_episodes.append(self._episodes[i])
                    self._episodes[i] = SingleAgentEpisode()
                    self._episodes[i].add_env_reset(next_obs[i])
            self._obs = next_obs
        # Hand out in-progress fragments too (truncated at the boundary),
        # so the learner sees exactly n_envs*num_steps transitions.
        for i in range(self.n_envs):
            if len(self._episodes[i]) > 0:
                frag = self._episodes[i]
                frag.is_truncated = True
                frag.is_boundary_fragment = True
                done_episodes.append(frag)
                self._episodes[i] = SingleAgentEpisode()
                self._episodes[i].add_env_reset(self._obs[i])
        return done_episodes

    def stop(self) -> None:
        self.envs.close()


class EnvRunnerGroup:
    """Driver-side handle to N EnvRunner actors (or one inline runner)."""

    def __init__(self, config: Dict[str, Any], module_spec: Dict[str, Any]):
        self.num_remote = config.get("num_env_runners", 0)
        if self.num_remote == 0:
            self.local = EnvRunner(config, module_spec, worker_index=0)
            self.remotes = []
        else:
            self.local = None
            cls = ray_tpu.remote(EnvRunner)
            self.remotes = [
                cls.options(num_cpus=1).remote(config, module_spec, i + 1)
                for i in range(self.num_remote)]

    def sync_weights(self, params) -> None:
        if self.local is not None:
            self.local.set_weights(params)
        else:
            ref = ray_tpu.put(params)
            ray_tpu.get([w.set_weights.remote(ref) for w in self.remotes])

    def sample(self, **kw) -> List[SingleAgentEpisode]:
        if self.local is not None:
            return self.local.sample(**kw)
        out = ray_tpu.get([w.sample.remote(**kw) for w in self.remotes])
        return [ep for eps in out for ep in eps]

    def stop(self) -> None:
        if self.local is not None:
            self.local.stop()
        for w in self.remotes:
            try:
                ray_tpu.kill(w)
            except Exception:  # noqa: BLE001
                pass
