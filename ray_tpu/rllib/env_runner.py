"""EnvRunner: samples episodes from gymnasium vector envs.

Reference: ray rllib/env/single_agent_env_runner.py:124 (sample loop over
gymnasium vector envs with RLModule.forward_exploration) and
env/env_runner_group.py (the actor group with weight sync). The action
step is one jit (module forward + categorical sample) so the hot loop is
env.step + a single device call.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu import exceptions as exc
from ray_tpu.rllib.episode import SingleAgentEpisode


def make_env(env_id, env_config: Optional[dict] = None):
    """env_id: a gym id, an env-creator callable, or "ALE/..." (routed
    through the Atari preprocessing pipeline). ray_tpu/-prefixed built-in
    envs self-register on first use."""
    import gymnasium as gym

    if callable(env_id):
        return env_id(**(env_config or {}))
    if isinstance(env_id, str) and env_id.startswith("ALE/"):
        from ray_tpu.rllib.atari import make_atari_env

        # pipeline knobs route to the wrapper; everything else is a plain
        # gym.make kwarg (full_action_space, mode, ...)
        cfg = dict(env_config or {})
        pipeline = {k: cfg.pop(k)
                    for k in ("frame_stack", "screen_size", "frameskip")
                    if k in cfg}
        return make_atari_env(env_id, **pipeline, env_config=cfg)
    if isinstance(env_id, str) and env_id.startswith("ray_tpu/"):
        from ray_tpu.rllib.atari import register_synthetic_env

        register_synthetic_env()
    return gym.make(env_id, **(env_config or {}))


class EnvRunner:
    """One sampling worker (used inline with num_env_runners=0, or as an
    actor in an EnvRunnerGroup)."""

    def __init__(self, config: Dict[str, Any], module_spec: Dict[str, Any],
                 worker_index: int = 0):
        import gymnasium as gym
        import jax

        self.config = config
        self.worker_index = worker_index
        n_envs = config.get("num_envs_per_env_runner", 1)
        self.envs = gym.vector.SyncVectorEnv(
            [partial(make_env, config["env"], config.get("env_config"))
             for _ in range(n_envs)])
        self.n_envs = n_envs
        from ray_tpu.rllib.rl_module import resolve_module

        self.module = resolve_module(module_spec)
        # Continuous (Box) action spaces: module outputs live in [-1,1];
        # rescale into the env bounds at the boundary.
        space = self.envs.single_action_space
        self._act_scale = None
        if hasattr(space, "low") and hasattr(space, "high"):
            low = np.asarray(space.low, np.float32)
            high = np.asarray(space.high, np.float32)
            self._act_scale = ((high - low) / 2.0, (high + low) / 2.0)
        seed = (config.get("seed") or 0) * 1000 + worker_index
        self._key = jax.random.PRNGKey(seed)
        self.params = None

        @jax.jit
        def _act(params, obs, key):
            return self.module.forward_exploration(
                params, {"obs": obs}, key)

        self._act = _act

        @jax.jit
        def _act_greedy(params, obs):
            return self.module.forward_inference(params, {"obs": obs})

        self._act_greedy = _act_greedy
        self._obs, _ = self.envs.reset(seed=seed)
        self._episodes = [SingleAgentEpisode() for _ in range(n_envs)]
        for i, ep in enumerate(self._episodes):
            ep.add_env_reset(self._obs[i])
        # policy version of the last set_weights: every pushed sample
        # batch is stamped with it so the learner can enforce the
        # off-policy staleness bound (dataflow.DecoupledDataflow)
        self._weights_version = 0

    def set_weights(self, params, version=None) -> None:
        self.params = params
        if version is not None:
            self._weights_version = int(version)

    def get_weights(self):
        return self.params

    def get_weights_version(self) -> int:
        return self._weights_version

    def get_node_id(self) -> str:
        """Node attribution for the fleet's preempt-notice sweep."""
        from ray_tpu.runtime_context import get_runtime_context

        try:
            return get_runtime_context().get_node_id()
        except Exception:  # noqa: BLE001 — inline (non-actor) runner
            return ""

    def sample_and_push(self, queue, *, num_steps: Optional[int] = None,
                        runner_index: int = 0, incarnation: int = 0,
                        explore: bool = True) -> Dict[str, Any]:
        """One decoupled rollout turn: sample a fragment, put it in the
        object store (this runner owns the payload — if this actor dies
        the learner sees typed OwnerDiedError and discards), push the
        stamped entry to the bounded sample queue, and return a SMALL
        ack to the fleet pump. A shed push drops the batch (the ref dies
        with this frame) and paces the next arm from the queue's
        retry-after hint — pushback is honored runner-side so the fleet
        pump stays non-blocking."""
        import time as _time

        episodes = self.sample(num_steps=num_steps, explore=explore)
        steps = sum(len(e) for e in episodes)
        version = self._weights_version
        ref = ray_tpu.put(episodes)
        entry = {"ref": ref, "env_steps": steps, "policy_version": version,
                 "runner": runner_index, "incarnation": incarnation}
        ack = ray_tpu.get(queue.push.remote(entry), timeout=60)
        if ack.get("retry_later"):
            _time.sleep(min(float(ack.get("retry_after_s", 0.05)), 0.5))
            return {"pushed": False, "shed": True, "env_steps": steps,
                    "version": version}
        if ack.get("rejected"):
            return {"pushed": False, "rejected": ack["rejected"],
                    "env_steps": steps, "version": version}
        return {"pushed": True, "env_steps": steps, "version": version,
                "depth": ack.get("depth")}

    def sample(self, *, num_steps: Optional[int] = None,
               explore: bool = True,
               random_actions: bool = False
               ) -> List[SingleAgentEpisode]:
        """Collect num_steps env steps (per vector env slot), returning
        completed + truncated-in-progress episodes."""
        import jax

        assert self.params is not None or random_actions, \
            "set_weights first"
        num_steps = num_steps or self.config.get(
            "rollout_fragment_length", 200)
        done_episodes: List[SingleAgentEpisode] = []
        for _ in range(num_steps):
            env_actions = None
            if random_actions:
                sampled = np.stack([
                    self.envs.single_action_space.sample()
                    for _ in range(self.n_envs)])
                if self._act_scale is not None:
                    # Store module-space [-1,1] actions; send env units.
                    scale, offset = self._act_scale
                    actions = (sampled - offset) / np.where(scale == 0, 1, scale)
                    env_actions = sampled
                else:
                    actions = sampled
                extra: Dict[str, np.ndarray] = {}
            else:
                self._key, sub = jax.random.split(self._key)
                # uint8 image obs ship raw (1 byte/pixel) and normalize
                # on-device inside the module; everything else goes float32
                obs_in = (self._obs if self._obs.dtype == np.uint8
                          else self._obs.astype(np.float32))
                if explore:
                    out = self._act(self.params, obs_in, sub)
                    extra = {"logp": np.asarray(out["logp"]),
                             "vf_preds": np.asarray(out["vf_preds"])}
                else:
                    out = self._act_greedy(self.params, obs_in)
                    extra = {}
                actions = np.asarray(out["actions"])
            if env_actions is None:
                env_actions = actions
                if self._act_scale is not None:
                    scale, offset = self._act_scale
                    env_actions = actions * scale + offset
            next_obs, rewards, terms, truncs, infos = self.envs.step(env_actions)
            for i in range(self.n_envs):
                per_step_extra = {k: v[i] for k, v in extra.items()}
                self._episodes[i].add_env_step(
                    next_obs[i], actions[i], rewards[i],
                    terminated=bool(terms[i]), truncated=bool(truncs[i]),
                    **per_step_extra)
                if terms[i] or truncs[i]:
                    done_episodes.append(self._episodes[i])
                    self._episodes[i] = SingleAgentEpisode()
                    self._episodes[i].add_env_reset(next_obs[i])
            self._obs = next_obs
        # Hand out in-progress fragments too (truncated at the boundary),
        # so the learner sees exactly n_envs*num_steps transitions.
        for i in range(self.n_envs):
            if len(self._episodes[i]) > 0:
                frag = self._episodes[i]
                frag.is_truncated = True
                frag.is_boundary_fragment = True
                done_episodes.append(frag)
                self._episodes[i] = SingleAgentEpisode()
                self._episodes[i].add_env_reset(self._obs[i])
        return done_episodes

    def stop(self) -> None:
        self.envs.close()


class EnvRunnerGroup:
    """Driver-side handle to N EnvRunner actors (or one inline runner).

    Fault-tolerant on the synchronous path too: a runner that dies
    mid-`sample()` is detected per-ref (`ActorDiedError`), replaced with
    a fresh actor carrying the LAST synced weights and its fragment
    re-collected from the survivors' results — one lost env runner no
    longer stalls or kills training (fleet-membership events
    `rl.runner_dead` / `rl.runner_respawn` emitted, CONTRIBUTING rule).
    `restart_failed_env_runners=False` restores fail-fast."""

    def __init__(self, config: Dict[str, Any], module_spec: Dict[str, Any]):
        self.num_remote = config.get("num_env_runners", 0)
        self._config = config
        self._module_spec = module_spec
        self._restart = config.get("restart_failed_env_runners", True)
        self._restart_budget = int(
            config.get("max_env_runner_restarts", 20))
        self.restarts = 0
        self._last_weights_ref = None
        if self.num_remote == 0:
            self.local = EnvRunner(config, module_spec, worker_index=0)
            self.remotes = []
        else:
            self.local = None
            cls = ray_tpu.remote(EnvRunner)
            self._cls = cls
            self.remotes = [
                self._spawn(i + 1) for i in range(self.num_remote)]

    def _spawn(self, worker_index: int):
        opts: Dict[str, Any] = {
            "num_cpus": self._config.get("num_cpus_per_env_runner", 1)}
        custom = self._config.get("custom_resources_per_env_runner")
        if custom:
            opts["resources"] = dict(custom)
        return self._cls.options(**opts).remote(
            self._config, self._module_spec, worker_index)

    def sync_weights(self, params, version: Optional[int] = None) -> None:
        if self.local is not None:
            self.local.set_weights(params, version)
            return
        ref = ray_tpu.put(params)
        self._last_weights_ref = (ref, version)
        pushes: List[tuple] = []
        dead: List[int] = []
        for i, w in enumerate(self.remotes):
            try:
                pushes.append((i, w.set_weights.remote(ref, version)))
            except exc.RayActorError:
                dead.append(i)
        for i, push in pushes:
            try:
                ray_tpu.get(push)
            except exc.RayActorError:
                dead.append(i)
        for i in dead:
            if not self._restart or self.restarts >= self._restart_budget:
                raise exc.ActorDiedError(
                    self.remotes[i]._actor_id,
                    error_message=f"env runner {i} died during weight "
                                  "sync and restarts are exhausted/off")
            # _replace_runner pushes _last_weights_ref (set above), so
            # the replacement comes up on THIS broadcast's weights
            self._replace_runner(i, "actor_died")

    def replace_runner(self, handle, reason: str = "actor_died"):
        """Replace a dead remote runner HANDLE in place and return the
        replacement (carrying the last synced weights). None when the
        handle is no longer in the fleet (another path already replaced
        it). When restarts are off or the budget is exhausted this emits
        the membership event and RAISES — fail-fast parity with the
        sync sample() path; a silently shrinking fleet is worse than a
        loud stop. For callers that drive runners by handle (IMPALA's
        pipelined in-flight map) rather than through sample()."""
        try:
            index = self.remotes.index(handle)
        except ValueError:
            return None
        if not self._restart or self.restarts >= self._restart_budget:
            from ray_tpu._private import event_log

            event_log.emit("rl.runner_dead",
                           actor_id=handle._actor_id.hex(),
                           runner=index, reason=reason)
            raise exc.ActorDiedError(
                handle._actor_id,
                error_message=f"env runner {index} died ({reason}) and "
                              "restarts are exhausted/off")
        self._replace_runner(index, reason)
        return self.remotes[index]

    def _replace_runner(self, index: int, reason: str) -> None:
        from ray_tpu._private import event_log

        old = self.remotes[index]
        event_log.emit("rl.runner_dead", actor_id=old._actor_id.hex(),
                       runner=index, reason=reason)
        replacement = self._spawn(index + 1)
        if self._last_weights_ref is not None:
            ref, version = self._last_weights_ref
            replacement.set_weights.remote(ref, version)
        self.remotes[index] = replacement
        self.restarts += 1
        event_log.emit("rl.runner_respawn",
                       actor_id=replacement._actor_id.hex(),
                       runner=index, incarnation=self.restarts,
                       reason=reason)

    def sample(self, **kw) -> List[SingleAgentEpisode]:
        if self.local is not None:
            return self.local.sample(**kw)
        refs: List[tuple] = []
        out: List[SingleAgentEpisode] = []
        dead: List[int] = []

        def _mark_dead(i):
            if not self._restart or self.restarts >= self._restart_budget:
                raise  # noqa: PLE0704 — re-raise the active RayActorError
            dead.append(i)

        for i, w in enumerate(self.remotes):
            try:
                # a known-dead handle raises synchronously at submit
                refs.append((i, w.sample.remote(**kw)))
            except exc.RayActorError:
                _mark_dead(i)
        for i, ref in refs:
            try:
                out.extend(ray_tpu.get(ref))
            except exc.RayActorError:
                _mark_dead(i)
        for i in dead:
            self._replace_runner(i, "actor_died")
        # the caller's batch-size loop tops up whatever the dead
        # runner(s) failed to deliver; nothing re-blocks here
        return out

    def stop(self) -> None:
        if self.local is not None:
            self.local.stop()
        for w in self.remotes:
            try:
                ray_tpu.kill(w)
            except Exception:  # noqa: BLE001
                pass
