"""Learner / LearnerGroup (reference: ray rllib/core/learner/learner_group.py:69
and core/learner/torch/torch_learner.py:52 — compute_gradients :135,
apply_gradients :147, DDP wrap :387-390).

JAX version: a Learner owns params + optax state and a single donated-buffer
jit update; data-parallel multi-learner = the update jit over a mesh with
batch sharding (XLA inserts the gradient psum that DDP does by hand).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

import ray_tpu


class JaxLearner:
    """Owns params + optimizer; subclasses define loss_fn."""

    def __init__(self, module, config: Dict[str, Any]):
        import jax
        import optax

        self.module = module
        self.config = config
        self._key = jax.random.PRNGKey(config.get("seed") or 0)
        self.params = module.init(self._key)
        clip = config.get("grad_clip")
        tx = [optax.clip_by_global_norm(clip)] if clip else []
        tx.append(optax.adam(config.get("lr", 3e-4)))
        self.optimizer = optax.chain(*tx)
        self.opt_state = self.optimizer.init(self.params)
        self._update = self._build_update()
        # monotonic policy version: bumped per update, stamped onto every
        # weight broadcast so rollout batches carry the version that
        # produced them (the decoupled dataflow's staleness bound)
        self.policy_version = 0

    # -- to be overridden ----------------------------------------------------

    def loss_fn(self, params, batch) -> Any:
        raise NotImplementedError

    def _build_update(self) -> Callable:
        import jax
        import optax

        def update(params, opt_state, batch):
            (loss, metrics), grads = jax.value_and_grad(
                self.loss_fn, has_aux=True)(params, batch)
            updates, opt_state = self.optimizer.update(
                grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            metrics["total_loss"] = loss
            return params, opt_state, metrics

        return jax.jit(update, donate_argnums=(0, 1))

    # -- API -----------------------------------------------------------------

    def update_from_batch(self, batch: Dict[str, np.ndarray]
                          ) -> Dict[str, float]:
        self.params, self.opt_state, metrics = self._update(
            self.params, self.opt_state, batch)
        self.policy_version += 1
        return {k: float(v) for k, v in metrics.items()}

    def get_weights(self):
        return self.params

    def set_weights(self, params) -> None:
        self.params = params

    def get_state(self) -> Dict[str, Any]:
        import jax

        return {"params": jax.device_get(self.params),
                "opt_state": jax.device_get(self.opt_state),
                "policy_version": self.policy_version}

    def set_state(self, state: Dict[str, Any]) -> None:
        self.params = state["params"]
        self.opt_state = state["opt_state"]
        self.policy_version = int(state.get("policy_version", 0))


class LearnerGroup:
    """One local learner or N learner actors with gradient-averaged updates
    (num_learners>0: each actor updates on its batch shard and the driver
    averages weights — parameter-mean data parallelism over DCN; on a TPU
    slice the single-learner path with a sharded batch is preferred since
    XLA's psum over ICI replaces the parameter exchange)."""

    def __init__(self, learner_cls, module_spec: Dict[str, Any],
                 config: Dict[str, Any]):
        self.num_remote = config.get("num_learners", 0)
        # driver-side mirror of the policy version for the remote-learner
        # case (the local case reads the learner's own counter)
        self._version = 0
        if self.num_remote == 0:
            self.local = learner_cls(module_spec, config)
            self.remotes = []
        else:
            self.local = None
            cls = ray_tpu.remote(learner_cls)
            self.remotes = [cls.options(num_cpus=1).remote(module_spec, config)
                            for _ in range(self.num_remote)]

    def update_from_batch(self, batch: Dict[str, np.ndarray]
                          ) -> Dict[str, float]:
        if self.local is not None:
            return self.local.update_from_batch(batch)
        # shard the batch across learners
        n = len(self.remotes)
        size = len(next(iter(batch.values())))
        shards = [
            {k: v[i * size // n:(i + 1) * size // n] for k, v in batch.items()}
            for i in range(n)]
        metrics = ray_tpu.get([
            w.update_from_batch.remote(s)
            for w, s in zip(self.remotes, shards)])
        self._version += 1
        # average weights (parameter-mean DP)
        import jax

        weights = ray_tpu.get([w.get_weights.remote() for w in self.remotes])
        mean_w = jax.tree_util.tree_map(
            lambda *xs: sum(xs) / len(xs), *weights)
        ray_tpu.get([w.set_weights.remote(mean_w) for w in self.remotes])
        out: Dict[str, float] = {}
        for m in metrics:
            for k, v in m.items():
                out[k] = out.get(k, 0.0) + v / len(metrics)
        return out

    def get_weights(self):
        if self.local is not None:
            return self.local.get_weights()
        return ray_tpu.get(self.remotes[0].get_weights.remote())

    @property
    def policy_version(self) -> int:
        if self.local is not None:
            return self.local.policy_version
        return self._version

    def get_state(self):
        if self.local is not None:
            return self.local.get_state()
        return ray_tpu.get(self.remotes[0].get_state.remote())

    def set_state(self, state) -> None:
        if self.local is not None:
            self.local.set_state(state)
        else:
            ray_tpu.get([w.set_state.remote(state) for w in self.remotes])

    def stop(self) -> None:
        for w in self.remotes:
            try:
                ray_tpu.kill(w)
            except Exception:  # noqa: BLE001
                pass
