"""Decoupled, fault-tolerant RL dataflow (ISSUE 14).

The Podracer/MindSpeed-RL shape: a fleet of rollout `EnvRunner` actors
pushes sample batches into a bounded sample queue riding the object
store, while the learner pulls asynchronously — sampling never blocks on
learning and the learner never waits on any single runner. The fleet is
explicitly CRASHABLE and PREEMPTIBLE; the learner makes monotonic
progress through runner deaths, node preemption and elastic resizing.

Three pieces:

* `SampleQueueActor` — the bounded queue (bound named from
  `CONFIG.rl_sample_queue_max` / `AlgorithmConfig.sample_queue_size`;
  CONTRIBUTING "every queue names its bound"). Entries are small
  (ObjectRef + policy-version + runner-incarnation stamps); the sample
  payloads live in the object store, owned by the runner that produced
  them — a dead runner's in-flight batches surface as typed
  OwnerDiedError at the learner and are discarded, never trained on.
  Overflow is typed shed back to the runner ({"retry_later": ...} with a
  retry-after hint, the PR 9 pushback convention) plus an
  `rl.sample_shed` event. Pushes from a superseded runner incarnation
  (a zombie on a partitioned/preempted node the fleet already replaced)
  are rejected, mirroring serve's controller-incarnation guard
  (`rl.zombie_push`).

* `RolloutFleet` — the driver-side fleet manager: keeps
  `max_requests_in_flight_per_env_runner` sample-and-push calls armed
  per runner, detects `ActorDiedError` on ack refs and
  `node.preempt_notice` via `EventCursor` (the serve-controller
  pattern), discards the dead runner's queued batches (incarnation bump
  at the queue), respawns a replacement with the CURRENT weights without
  any blocking call on the learner's step path, and resizes elastically
  on queue starvation/backlog signals. Every membership change emits
  (`rl.runner_dead` / `rl.runner_respawn` / `rl.fleet_scale` —
  CONTRIBUTING rule).

* `DecoupledDataflow` — the learner-side façade: pop a batch of entries,
  enforce the off-policy staleness bound (learner_version −
  batch_version > max_sample_staleness ⇒ dropped + counted +
  `rl.stale_drop`, NEVER trained on), resolve refs (dead-runner refs
  counted discarded), and expose versioned weight broadcast.

Metrics ride the existing autoscaler/dashboard path
(`ray_tpu_rl_queue_depth`, `ray_tpu_rl_rollout_runners`,
`ray_tpu_rl_samples_shed_total`, `ray_tpu_rl_stale_dropped_total`,
`ray_tpu_rl_runner_restarts_total`).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import ray_tpu
from ray_tpu import exceptions as exc
from ray_tpu._private import event_log
from ray_tpu._private.config import CONFIG

logger = logging.getLogger(__name__)

# exceptions that mean "this runner / its objects are gone", not a bug
# (ObjectLostError covers OwnerDied/ObjectFreed/reconstruction-failed)
_RUNNER_GONE = (exc.RayActorError, exc.ObjectLostError,
                exc.WorkerCrashedError)


class SampleQueueActor:
    """Bounded sample queue between the rollout fleet and the learner.

    Bound: `maxsize` entries, named from CONFIG.rl_sample_queue_max at
    the creation site (DecoupledDataflow). Entries carry refs, not
    payloads — the queue actor never materializes a sample batch.
    """

    def __init__(self, maxsize: int):
        self._maxsize = int(maxsize)
        self._items: List[dict] = []  # bounded by _maxsize in push()
        # runner slot -> current incarnation; pushes below it are zombies
        self._incarnations: Dict[int, int] = {}
        self._stats = {"pushed": 0, "popped": 0, "shed": 0,
                       "zombie_rejected": 0, "discarded_dead": 0}

    def set_incarnation(self, runner: int, incarnation: int) -> int:
        """Install a runner slot's current incarnation (spawn/respawn)
        and DISCARD queued entries from older incarnations of that slot
        — the dead/preempted runner's in-flight batches. Returns the
        discard count."""
        runner = int(runner)
        cur = self._incarnations.get(runner, -1)
        if incarnation < cur:
            return 0  # stale installer (out-of-order fleet message)
        self._incarnations[runner] = int(incarnation)
        keep = []
        dropped = 0
        for e in self._items:
            if e.get("runner") == runner \
                    and e.get("incarnation", 0) < incarnation:
                dropped += 1
            else:
                keep.append(e)
        if dropped:
            self._items = keep
            self._stats["discarded_dead"] += dropped
        return dropped

    def push(self, entry: dict) -> dict:
        runner = int(entry.get("runner", 0))
        incarnation = int(entry.get("incarnation", 0))
        current = self._incarnations.get(runner, -1)
        if incarnation < current:
            # zombie: a superseded incarnation still pushing (preempted
            # node not yet torn down) — its weights/version stamps are
            # untrusted, reject outright (never queued, never trained)
            self._stats["zombie_rejected"] += 1
            event_log.emit("rl.zombie_push", runner=runner,
                           incarnation=incarnation, current=current)
            return {"rejected": "zombie", "current": current}
        if incarnation > current:
            # a replacement's first push can beat the fleet's
            # set_incarnation message; newer always supersedes
            self._incarnations[runner] = incarnation
        if len(self._items) >= self._maxsize:
            from ray_tpu._private.backoff import retry_after_hint

            self._stats["shed"] += 1
            event_log.emit("rl.sample_shed", runner=runner,
                           depth=len(self._items))
            # typed pushback, PR 9 convention: refused (not queued, not
            # lost), retry after THE shared hint formula (one fragment's
            # learner-side train time per queued entry, floored so a
            # just-full queue isn't instantly re-hammered)
            return {"retry_later": True,
                    "retry_after_s": retry_after_hint(
                        len(self._items), per_item_s=0.01, floor_s=0.05,
                        cap_s=1.0)}
        self._items.append(entry)
        self._stats["pushed"] += 1
        return {"ok": True, "depth": len(self._items)}

    def pop_batch(self, max_items: int) -> dict:
        """Pop up to `max_items` entries, returning them WITH a stats
        snapshot in one reply — the learner's pull must never need a
        second round trip whose failure would strand already-popped
        (hence unrecoverable) entries."""
        out, self._items = (self._items[:max_items],
                            self._items[max_items:])
        self._stats["popped"] += len(out)
        return {"entries": out, **self.stats()}

    def depth(self) -> int:
        return len(self._items)

    def stats(self) -> dict:
        return {"depth": len(self._items), "maxsize": self._maxsize,
                "incarnations": dict(self._incarnations), **self._stats}


class _Slot:
    """One rollout-fleet slot: a runner actor + its incarnation."""

    def __init__(self, index: int, incarnation: int, handle):
        self.index = index
        self.incarnation = incarnation
        self.handle = handle
        self.node_ref = None        # in-flight get_node_id
        self.node_id: Optional[str] = None
        self.inflight: set = set()  # ack refs of armed sample_and_push
        self.actor_id = handle._actor_id.hex()


class RolloutFleet:
    """Driver-side manager of a crashable, elastic rollout fleet."""

    def __init__(self, config: Dict[str, Any], module_spec: Dict[str, Any],
                 queue_handle):
        from ray_tpu._private.event_watch import EventCursor
        from ray_tpu.rllib.env_runner import EnvRunner

        self._config = config
        self._module_spec = module_spec
        self._queue = queue_handle
        self._cls = ray_tpu.remote(EnvRunner)
        self._num_steps = config.get("rollout_fragment_length", 200)
        self._per_runner_inflight = max(
            1, int(config.get("max_requests_in_flight_per_env_runner", 2)))
        self._restart = config.get("restart_failed_env_runners", True)
        self._restart_budget = int(
            config.get("max_env_runner_restarts", 20))
        self._elastic_min = config.get("elastic_min_env_runners")
        self._elastic_max = config.get("elastic_max_env_runners")
        self._lock = threading.Lock()   # snapshot() is read cross-thread
        self.slots: Dict[int, _Slot] = {}
        self._next_index = 0
        self._weights_ref = None
        self._version = 0
        self.restarts = 0
        self.deaths = 0
        self._acks = {"pushed": 0, "shed": 0, "env_steps": 0}
        # starvation/backlog windows for the elastic policy
        self._starved_pumps = 0
        self._backlogged_pumps = 0
        # preempt notices, consumed once each (serve-controller pattern)
        self._preempt_cursor = EventCursor("node.preempt_notice")

    # -- lifecycle -----------------------------------------------------------

    def start(self, weights, version: int = 0) -> None:
        self._weights_ref = ray_tpu.put(weights)
        self._version = int(version)
        n = int(self._config.get("num_env_runners", 0))
        for _ in range(n):
            self._spawn_slot()

    def stop(self) -> None:
        with self._lock:
            slots = list(self.slots.values())
            self.slots = {}
        for s in slots:
            try:
                ray_tpu.kill(s.handle)
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass

    # -- spawning ------------------------------------------------------------

    def _actor_options(self) -> dict:
        opts: Dict[str, Any] = {
            "num_cpus": self._config.get("num_cpus_per_env_runner", 1)}
        custom = self._config.get("custom_resources_per_env_runner")
        if custom:
            opts["resources"] = dict(custom)
        return opts

    def _spawn_slot(self, index: Optional[int] = None,
                    incarnation: int = 0) -> _Slot:
        """Create a runner for `index` (fresh slot when None) and arm it.
        Everything here is non-blocking submission — a respawn must not
        consume the learner's step cadence."""
        if index is None:
            index = self._next_index
            self._next_index += 1
        handle = self._cls.options(**self._actor_options()).remote(
            self._config, self._module_spec, index + 1)
        slot = _Slot(index, incarnation, handle)
        # versioned weights BEFORE the first sample; the queue learns the
        # incarnation so older queued pushes from this slot are discarded
        handle.set_weights.remote(self._weights_ref, self._version)
        self._queue.set_incarnation.remote(index, incarnation)
        slot.node_ref = handle.get_node_id.remote()
        for _ in range(self._per_runner_inflight):
            self._arm(slot)
        with self._lock:
            self.slots[index] = slot
        return slot

    def _arm(self, slot: _Slot) -> None:
        ref = slot.handle.sample_and_push.remote(
            self._queue, num_steps=self._num_steps,
            runner_index=slot.index, incarnation=slot.incarnation)
        slot.inflight.add(ref)

    # -- death / preemption / respawn ----------------------------------------

    def _on_runner_gone(self, slot: _Slot, reason: str) -> None:
        """A runner died (ActorDiedError on an ack) or its node got a
        preempt notice: discard its queued batches via the incarnation
        bump and respawn a replacement with the current weights."""
        with self._lock:
            live = self.slots.get(slot.index)
            if live is None or live.incarnation != slot.incarnation:
                return  # already replaced (death raced the preempt path)
            del self.slots[slot.index]
        self.deaths += 1
        event_log.emit("rl.runner_dead", actor_id=slot.actor_id,
                       runner=slot.index, reason=reason,
                       incarnation=slot.incarnation)
        if reason == "preempt_notice":
            # the old actor may still run for the notice window; kill it
            # so it stops burning the node's last CPU-seconds (its pushes
            # would be zombie-rejected regardless)
            try:
                ray_tpu.kill(slot.handle)
            except Exception:  # noqa: BLE001 — node may already be gone
                pass
        if not self._restart:
            return
        if self.restarts >= self._restart_budget:
            logger.warning(
                "rollout runner %d died (%s) but the respawn budget "
                "(max_env_runner_restarts=%d) is spent; fleet degrades "
                "to %d runner(s)", slot.index, reason,
                self._restart_budget, len(self.slots))
            return
        self.restarts += 1
        new = self._spawn_slot(slot.index, slot.incarnation + 1)
        event_log.emit("rl.runner_respawn", actor_id=new.actor_id,
                       runner=new.index, incarnation=new.incarnation,
                       reason=reason)

    def _check_preempt_notices(self) -> None:
        for ev in self._preempt_cursor.poll(limit=100):
            node = ev.get("node_id")
            if not node:
                continue
            with self._lock:
                victims = [s for s in self.slots.values()
                           if s.node_id == node]
            for slot in victims:
                self._on_runner_gone(slot, "preempt_notice")

    # -- the pump ------------------------------------------------------------

    def pump(self, timeout: float = 0.0) -> Dict[str, int]:
        """Collect ready acks, re-arm runners, resolve node attribution,
        react to deaths and preempt notices. Non-blocking by default —
        this runs on the learner's step path."""
        self._check_preempt_notices()
        with self._lock:
            slots = list(self.slots.values())
        # node attribution resolves lazily (one outstanding ref per slot)
        for slot in slots:
            if slot.node_id is None and slot.node_ref is not None:
                ready, _ = ray_tpu.wait([slot.node_ref], num_returns=1,
                                        timeout=0)
                if ready:
                    try:
                        slot.node_id = ray_tpu.get(ready[0])
                    except _RUNNER_GONE:
                        self._on_runner_gone(slot, "actor_died")
                    slot.node_ref = None
        by_ref: Dict[Any, _Slot] = {
            ref: slot for slot in slots for ref in slot.inflight}
        if not by_ref:
            return dict(self._acks)
        ready, _ = ray_tpu.wait(list(by_ref), num_returns=len(by_ref),
                                timeout=timeout)
        dead: Dict[int, Tuple[_Slot, str]] = {}
        for ref in ready:
            slot = by_ref[ref]
            slot.inflight.discard(ref)
            try:
                ack = ray_tpu.get(ref)
            except _RUNNER_GONE as e:
                dead.setdefault(slot.index, (slot, type(e).__name__))
                continue
            except Exception as e:  # noqa: BLE001 — sampling bug: surface
                raise e
            self._acks["env_steps"] += int(ack.get("env_steps", 0))
            if ack.get("pushed"):
                self._acks["pushed"] += 1
            elif ack.get("shed"):
                self._acks["shed"] += 1
            # re-arm (the runner already paced itself on shed); a kill
            # between ack and re-arm surfaces HERE as a synchronous
            # ActorDiedError from submit
            if slot.index in self.slots \
                    and self.slots[slot.index] is slot:
                try:
                    self._arm(slot)
                except _RUNNER_GONE as e:
                    dead.setdefault(slot.index, (slot, type(e).__name__))
        for slot, reason in dead.values():
            self._on_runner_gone(slot, reason)
        return dict(self._acks)

    # -- weights -------------------------------------------------------------

    def broadcast(self, weights, version: int) -> None:
        """Versioned weight push to every live runner (one put, N refs).
        Fire-and-forget: a broadcast never blocks the learner; a runner
        that dies mid-push is caught by the next pump."""
        self._weights_ref = ray_tpu.put(weights)
        self._version = int(version)
        with self._lock:
            slots = list(self.slots.values())
        for slot in slots:
            try:
                slot.handle.set_weights.remote(self._weights_ref, version)
            except _RUNNER_GONE:
                self._on_runner_gone(slot, "actor_died")
        event_log.emit("rl.weights_broadcast", version=version,
                       runners=len(slots))

    # -- elastic scaling -----------------------------------------------------

    def maybe_autoscale(self, queue_depth: int, shed_delta: int) -> None:
        """Elastic fleet sizing off the same signals the metrics path
        exports: a persistently EMPTY queue means the learner is starved
        (scale up, bounded by elastic_max_env_runners); persistent shed
        means rollouts outpace the learner (scale down to the min —
        shed work is wasted env steps)."""
        if self._elastic_max is None:
            return
        lo = int(self._elastic_min if self._elastic_min is not None
                 else self._config.get("num_env_runners", 1))
        hi = int(self._elastic_max)
        n = len(self.slots)
        self._starved_pumps = self._starved_pumps + 1 \
            if queue_depth == 0 and shed_delta == 0 else 0
        self._backlogged_pumps = self._backlogged_pumps + 1 \
            if shed_delta > 0 else 0
        if self._starved_pumps >= 5 and n < hi:
            self._starved_pumps = 0
            self._spawn_slot()
            event_log.emit("rl.fleet_scale", from_runners=n,
                           to_runners=n + 1, reason="learner_starved")
        elif self._backlogged_pumps >= 5 and n > lo:
            self._backlogged_pumps = 0
            with self._lock:
                idx = max(self.slots)
                slot = self.slots.pop(idx)
            # retire the slot: discard its queued entries (the queue
            # treats a bumped incarnation's predecessors as dead) and
            # kill the actor
            self._queue.set_incarnation.remote(idx, slot.incarnation + 1)
            try:
                ray_tpu.kill(slot.handle)
            except Exception:  # noqa: BLE001
                pass
            event_log.emit("rl.fleet_scale", from_runners=n,
                           to_runners=n - 1, reason="queue_backlogged")

    # -- introspection -------------------------------------------------------

    def snapshot(self) -> Dict[int, Dict[str, Any]]:
        """Thread-safe fleet view (the rl_rollout_storm drill picks its
        victims from this on another thread)."""
        with self._lock:
            return {
                i: {"actor_id": s.actor_id, "incarnation": s.incarnation,
                    "node_id": s.node_id, "handle": s.handle}
                for i, s in self.slots.items()
            }

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            n = len(self.slots)
        return {"runners": n, "deaths": self.deaths,
                "restarts": self.restarts, "version": self._version,
                **self._acks}


class DecoupledDataflow:
    """Learner-side façade over the queue + fleet."""

    def __init__(self, config: Dict[str, Any], module_spec: Dict[str, Any],
                 weights, version: int = 0):
        bound = int(config.get("sample_queue_size")
                    or CONFIG.rl_sample_queue_max)
        qopts: Dict[str, Any] = {"num_cpus": 0.05}
        if config.get("sample_queue_resources"):
            # e.g. pin to the head node while the fleet rides
            # preemptible nodes — the queue is learner-side state
            qopts["resources"] = dict(config["sample_queue_resources"])
        self.queue = ray_tpu.remote(SampleQueueActor).options(
            **qopts).remote(bound)
        self.fleet = RolloutFleet(config, module_spec, self.queue)
        self.fleet.start(weights, version)
        self.max_staleness = int(config.get("max_sample_staleness", 2))
        self.stale_dropped = 0
        self.discarded_dead = 0
        self.env_steps_trained = 0
        self._last_shed = 0
        self._metrics_ready = False

    def pull(self, current_version: int,
             max_batches: Optional[int] = None,
             ) -> List[Tuple[dict, list]]:
        """Pump the fleet, pop ready entries, enforce the staleness
        bound, resolve refs. Returns [(entry, episodes), ...] of batches
        SAFE to train on. Never blocks on any individual runner."""
        self.fleet.pump()
        if max_batches is None:
            max_batches = max(2, 2 * len(self.fleet.slots))
        try:
            # ONE round trip: entries + stats snapshot together — a
            # failure here loses nothing (the entries stay queued)
            qstats = ray_tpu.get(
                self.queue.pop_batch.remote(max_batches), timeout=30)
            entries = qstats["entries"]
        except Exception:  # noqa: BLE001 — queue actor mid-restart blip
            logger.warning("sample queue unreachable this pull; retrying "
                           "next step", exc_info=True)
            return []
        out: List[Tuple[dict, list]] = []
        for e in entries:
            version = int(e.get("policy_version", 0))
            if current_version - version > self.max_staleness:
                # off-policy staleness bound: dropped and counted, NEVER
                # trained on
                self.stale_dropped += 1
                event_log.emit("rl.stale_drop", version=current_version,
                               batch_version=version,
                               bound=self.max_staleness,
                               runner=e.get("runner"))
                continue
            try:
                episodes = ray_tpu.get(e["ref"], timeout=30)
            except _RUNNER_GONE:
                # the producing runner died with this batch in flight
                self.discarded_dead += 1
                continue
            self.env_steps_trained += int(e.get("env_steps", 0))
            out.append((e, episodes))
        shed_delta = qstats["shed"] - self._last_shed
        self._last_shed = qstats["shed"]
        self.fleet.maybe_autoscale(qstats["depth"], shed_delta)
        self._export_metrics(qstats)
        return out

    def broadcast(self, weights, version: int) -> None:
        self.fleet.broadcast(weights, version)

    def stats(self) -> Dict[str, Any]:
        return {"stale_dropped": self.stale_dropped,
                "discarded_dead": self.discarded_dead,
                "env_steps_trained": self.env_steps_trained,
                **{f"fleet_{k}": v for k, v in self.fleet.stats().items()}}

    def stop(self) -> None:
        self.fleet.stop()
        try:
            ray_tpu.kill(self.queue)
        except Exception:  # noqa: BLE001 — teardown best-effort
            pass

    # -- metrics (the autoscaler/dashboard path) -----------------------------

    def _export_metrics(self, qstats: dict) -> None:
        try:
            from ray_tpu.util.metrics import Counter, Gauge, get_metric

            def gauge(name, desc):
                m = get_metric(name)
                return m if m is not None else Gauge(name, desc)

            def counter(name, desc):
                m = get_metric(name)
                return m if m is not None else Counter(name, desc)

            gauge("ray_tpu_rl_queue_depth",
                  "Sample-queue depth (entries)").set(qstats["depth"])
            gauge("ray_tpu_rl_rollout_runners",
                  "Live rollout runners").set(len(self.fleet.slots))
            if not self._metrics_ready:
                # counters exist from the first export so dashboards see
                # zeros rather than gaps
                counter("ray_tpu_rl_samples_shed_total",
                        "Sample batches shed by the bounded queue")
                counter("ray_tpu_rl_stale_dropped_total",
                        "Batches dropped by the staleness bound")
                counter("ray_tpu_rl_runner_restarts_total",
                        "Rollout runners respawned after death")
                self._metrics_ready = True
                self._exported = {"shed": 0, "stale": 0, "restarts": 0}
            deltas = (("ray_tpu_rl_samples_shed_total", "shed",
                       qstats["shed"]),
                      ("ray_tpu_rl_stale_dropped_total", "stale",
                       self.stale_dropped),
                      ("ray_tpu_rl_runner_restarts_total", "restarts",
                       self.fleet.restarts))
            for name, key, total in deltas:
                d = total - self._exported[key]
                if d > 0:
                    counter(name, "").inc(d)
                    self._exported[key] = total
        except Exception:  # noqa: BLE001 — metrics never fail training
            logger.debug("rl metric export failed", exc_info=True)
