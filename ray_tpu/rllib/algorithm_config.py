"""Config-as-object builder (reference: ray
rllib/algorithms/algorithm_config.py — AlgorithmConfig with .environment()/
.env_runners()/.training()/.evaluation() builder methods and .build())."""

from __future__ import annotations

import copy
from typing import Any, Dict, Optional, Type


class AlgorithmConfig:
    def __init__(self, algo_class: Optional[Type] = None):
        self.algo_class = algo_class
        # environment
        self.env: Optional[str] = None
        self.env_config: Dict[str, Any] = {}
        # env runners
        self.num_env_runners: int = 0  # 0 = sample in the driver
        self.num_envs_per_env_runner: int = 1
        self.rollout_fragment_length: int = 200
        self.num_cpus_per_env_runner: float = 1.0
        self.custom_resources_per_env_runner: Dict[str, float] = {}
        # decoupled fault-tolerant dataflow (rllib/dataflow.py):
        # rollout fleet -> bounded sample queue -> async learner pulls
        self.decoupled: bool = False
        # sample-queue bound (entries); None -> CONFIG.rl_sample_queue_max
        self.sample_queue_size: Optional[int] = None
        # custom-resource pin for the queue actor (e.g. keep it on the
        # head node while the rollout fleet rides preemptible nodes)
        self.sample_queue_resources: Optional[Dict[str, float]] = None
        # off-policy staleness bound: batches whose stamped policy
        # version trails the learner by more than this are dropped
        # (counted, evented), never trained on
        self.max_sample_staleness: int = 2
        # crashable-fleet knobs: dead/preempted runners are respawned
        # with the current weights, bounded by the restart budget
        self.restart_failed_env_runners: bool = True
        self.max_env_runner_restarts: int = 20
        # elastic fleet sizing (decoupled mode): None = fixed fleet
        self.elastic_min_env_runners: Optional[int] = None
        self.elastic_max_env_runners: Optional[int] = None
        # training
        self.lr: float = 3e-4
        self.gamma: float = 0.99
        self.train_batch_size: int = 4000
        self.minibatch_size: int = 128
        self.num_epochs: int = 8
        self.grad_clip: Optional[float] = None
        self.model: Dict[str, Any] = {"fcnet_hiddens": [64, 64]}
        # PPO
        self.lambda_: float = 0.95
        self.clip_param: float = 0.2
        self.vf_loss_coeff: float = 0.5
        self.entropy_coeff: float = 0.0
        # DQN
        self.epsilon: list = [(0, 1.0), (10_000, 0.05)]
        self.target_network_update_freq: int = 500
        self.replay_buffer_config: Dict[str, Any] = {
            "type": "ReplayBuffer", "capacity": 50_000}
        self.num_steps_sampled_before_learning_starts: int = 1000
        # learners
        self.num_learners: int = 0
        # offline (BC/MARWIL/CQL: input_ = episode-JSON paths/dirs)
        self.input_: Optional[Any] = None
        self.beta: float = 1.0  # MARWIL advantage coefficient (0 == BC)
        self.cql_alpha: float = 1.0  # CQL conservative penalty weight
        # evaluation
        self.evaluation_interval: Optional[int] = None
        self.evaluation_duration: int = 5
        # misc
        self.seed: Optional[int] = None
        self.explore: bool = True
        self.callbacks_class = None  # RLlibCallback subclass/instance

    # -- builder methods -----------------------------------------------------

    def environment(self, env: Optional[str] = None, *,
                    env_config: Optional[dict] = None) -> "AlgorithmConfig":
        if env is not None:
            self.env = env
        if env_config is not None:
            self.env_config = env_config
        return self

    def env_runners(self, *, num_env_runners: Optional[int] = None,
                    num_envs_per_env_runner: Optional[int] = None,
                    rollout_fragment_length: Optional[int] = None,
                    num_cpus_per_env_runner: Optional[float] = None,
                    custom_resources_per_env_runner: Optional[dict] = None,
                    **_kw) -> "AlgorithmConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_env_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        if num_cpus_per_env_runner is not None:
            self.num_cpus_per_env_runner = num_cpus_per_env_runner
        if custom_resources_per_env_runner is not None:
            self.custom_resources_per_env_runner = dict(
                custom_resources_per_env_runner)
        return self

    def fault_tolerance(
            self, *, restart_failed_env_runners: Optional[bool] = None,
            max_env_runner_restarts: Optional[int] = None,
            **_kw) -> "AlgorithmConfig":
        """Crashable-fleet policy (reference: algorithm_config.py
        fault_tolerance() — restart_failed_env_runners)."""
        if restart_failed_env_runners is not None:
            self.restart_failed_env_runners = restart_failed_env_runners
        if max_env_runner_restarts is not None:
            self.max_env_runner_restarts = max_env_runner_restarts
        return self

    def dataflow(self, *, decoupled: Optional[bool] = None,
                 sample_queue_size: Optional[int] = None,
                 sample_queue_resources: Optional[dict] = None,
                 max_sample_staleness: Optional[int] = None,
                 elastic_min_env_runners: Optional[int] = None,
                 elastic_max_env_runners: Optional[int] = None,
                 **_kw) -> "AlgorithmConfig":
        """Decoupled rollout/learner dataflow (rllib/dataflow.py): the
        fleet pushes into a bounded object-store sample queue; the
        learner pulls asynchronously under `max_sample_staleness`."""
        if decoupled is not None:
            self.decoupled = decoupled
        if sample_queue_size is not None:
            self.sample_queue_size = sample_queue_size
        if sample_queue_resources is not None:
            self.sample_queue_resources = dict(sample_queue_resources)
        if max_sample_staleness is not None:
            self.max_sample_staleness = max_sample_staleness
        if elastic_min_env_runners is not None:
            self.elastic_min_env_runners = elastic_min_env_runners
        if elastic_max_env_runners is not None:
            self.elastic_max_env_runners = elastic_max_env_runners
        return self

    def training(self, **kwargs) -> "AlgorithmConfig":
        for k, v in kwargs.items():
            key = "lambda_" if k == "lambda" else k
            if not hasattr(self, key):
                raise ValueError(f"unknown training option {k!r}")
            setattr(self, key, v)
        return self

    def offline_data(self, *, input_: Optional[Any] = None,
                     **_kw) -> "AlgorithmConfig":
        if input_ is not None:
            self.input_ = input_
        return self

    def evaluation(self, *, evaluation_interval: Optional[int] = None,
                   evaluation_duration: Optional[int] = None,
                   **_kw) -> "AlgorithmConfig":
        if evaluation_interval is not None:
            self.evaluation_interval = evaluation_interval
        if evaluation_duration is not None:
            self.evaluation_duration = evaluation_duration
        return self

    def learners(self, *, num_learners: Optional[int] = None,
                 **_kw) -> "AlgorithmConfig":
        if num_learners is not None:
            self.num_learners = num_learners
        return self

    def callbacks(self, callbacks_class) -> "AlgorithmConfig":
        """Install an RLlibCallback (reference:
        algorithm_config.py callbacks())."""
        self.callbacks_class = callbacks_class
        return self

    def debugging(self, *, seed: Optional[int] = None,
                  **_kw) -> "AlgorithmConfig":
        if seed is not None:
            self.seed = seed
        return self

    def copy(self) -> "AlgorithmConfig":
        return copy.deepcopy(self)

    def to_dict(self) -> Dict[str, Any]:
        return {k: v for k, v in self.__dict__.items()
                if k != "algo_class"}

    def build(self):
        if self.algo_class is None:
            raise ValueError(
                "use PPOConfig()/DQNConfig() or pass algo_class")
        return self.algo_class(config=self.copy())
