"""Model catalog: observation/action-space-driven module construction.

Reference: ray rllib/core/models/catalog.py (Catalog —
``_get_encoder_config`` picks CNN/MLP/flatten by space shape; heads are
built to match the action distribution). Here the catalog emits a
SERIALIZABLE module_spec (a dict) because env runners and learners are
separate actors: each side rebuilds the module from the spec via
``resolve_module``.

Encoder selection by observation space (gym duck-typing):
  Discrete(n)            -> one-hot(n) -> MLP
  Box shape (d,)         -> MLP
  Box shape (H, W, C)    -> Nature-CNN conv stack
  Box other ndim         -> flatten -> MLP
  Dict/Tuple             -> per-leaf flatten/one-hot -> concat -> MLP
                            (leaves must be Box/Discrete; nested composites
                            flatten recursively)

Action-space handling:
  Discrete(n)            -> categorical logits head (actor-critic / Q)
  Box shape (d,)         -> tanh-squashed diagonal Gaussian head
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

DEFAULT_CONV_FILTERS = ((32, 8, 4), (64, 4, 2), (64, 3, 1))


def _is_discrete(space) -> bool:
    return hasattr(space, "n") and not hasattr(space, "spaces")


def _is_composite(space) -> bool:
    return hasattr(space, "spaces")


def _leaf_encoding(space) -> Tuple[str, Any]:
    """-> ("onehot", n) | ("flatten", flat_dim) for a composite leaf."""
    if _is_discrete(space):
        return ("onehot", int(space.n))
    if hasattr(space, "shape"):
        size = 1
        for d in space.shape:
            size *= int(d)
        return ("flatten", size)
    raise ValueError(f"unsupported leaf space: {space!r}")


class Catalog:
    """Builds module specs from spaces + model_config (fcnet_hiddens,
    post_fcnet_hiddens, conv_filters — the reference's model-config keys).
    """

    def __init__(self, observation_space, action_space,
                 model_config: Optional[Dict[str, Any]] = None):
        self.observation_space = observation_space
        self.action_space = action_space
        self.model_config = dict(model_config or {})

    # -- encoder -------------------------------------------------------------

    def encoder_spec(self) -> Dict[str, Any]:
        space = self.observation_space
        if _is_discrete(space):
            return {"kind": "onehot", "n": int(space.n)}
        if _is_composite(space):
            spaces = space.spaces
            if isinstance(spaces, dict):
                leaves = [(k, self._leaf_spec(s))
                          for k, s in sorted(spaces.items())]
                return {"kind": "concat", "container": "dict",
                        "leaves": leaves}
            leaves = [(i, self._leaf_spec(s)) for i, s in enumerate(spaces)]
            return {"kind": "concat", "container": "tuple", "leaves": leaves}
        shape = tuple(int(d) for d in space.shape)
        if len(shape) == 3:
            return {"kind": "cnn", "obs_shape": shape,
                    "conv_filters": tuple(tuple(f) for f in
                                          self.model_config.get(
                                              "conv_filters",
                                              DEFAULT_CONV_FILTERS))}
        if len(shape) == 1:
            return {"kind": "mlp", "obs_dim": shape[0]}
        size = 1
        for d in shape:
            size *= d
        return {"kind": "flatten", "obs_dim": size, "obs_shape": shape}

    def _leaf_spec(self, space):
        if _is_composite(space):
            # nested composite: flatten recursively leaf by leaf
            sub = Catalog(space, self.action_space,
                          self.model_config).encoder_spec()
            return sub
        kind, arg = _leaf_encoding(space)
        return ({"kind": "onehot", "n": arg} if kind == "onehot"
                else {"kind": "flatten", "obs_dim": arg})

    @staticmethod
    def encoded_dim(enc: Dict[str, Any]) -> int:
        """Flat feature width an encoder feeds into the dense stack (CNN
        excluded — its width is computed by the conv module itself)."""
        kind = enc["kind"]
        if kind == "onehot":
            return enc["n"]
        if kind in ("mlp", "flatten"):
            return enc["obs_dim"]
        if kind == "concat":
            return sum(Catalog.encoded_dim(leaf)
                       for _key, leaf in enc["leaves"])
        raise ValueError(f"no flat width for encoder {kind!r}")

    # -- module specs --------------------------------------------------------

    def _hiddens(self, default=(64, 64)) -> tuple:
        return tuple(self.model_config.get("fcnet_hiddens", default))

    def actor_critic_spec(self) -> Dict[str, Any]:
        """Spec for PPO/IMPALA/APPO-family modules."""
        enc = self.encoder_spec()
        if not _is_discrete(self.action_space):
            raise ValueError(
                "actor-critic catalog currently supports Discrete action "
                "spaces (continuous control goes through SAC's Gaussian "
                "actor — sac_specs())")
        num_actions = int(self.action_space.n)
        if enc["kind"] == "cnn":
            return {
                "module_class":
                    "ray_tpu.rllib.rl_module:ConvActorCriticModule",
                "obs_shape": enc["obs_shape"], "num_actions": num_actions,
                "conv_filters": enc["conv_filters"],
                "hiddens": tuple(self.model_config.get(
                    "post_fcnet_hiddens", (512,))),
            }
        if enc["kind"] == "mlp":
            return {"obs_dim": enc["obs_dim"], "num_actions": num_actions,
                    "hiddens": self._hiddens()}
        return {
            "module_class":
                "ray_tpu.rllib.rl_module:EncodedActorCriticModule",
            "module_kwargs": {"encoder_spec": enc,
                              "num_actions": num_actions,
                              "hiddens": self._hiddens()},
        }

    def q_spec(self) -> Dict[str, Any]:
        """Spec for DQN-family Q-modules."""
        enc = self.encoder_spec()
        if not _is_discrete(self.action_space):
            raise ValueError("Q catalog requires a Discrete action space")
        num_actions = int(self.action_space.n)
        if enc["kind"] == "cnn":
            raise ValueError(
                "image-observation DQN is not wired yet; use PPO/IMPALA's "
                "conv path or flatten the observation")
        obs_dim = self.encoded_dim(enc)
        spec = {"obs_dim": obs_dim, "num_actions": num_actions,
                "hiddens": self._hiddens(),
                "module_class": "ray_tpu.rllib.rl_module:QModule"}
        if enc["kind"] != "mlp":
            spec["module_class"] = (
                "ray_tpu.rllib.rl_module:EncodedQModule")
            spec["module_kwargs"] = {"encoder_spec": enc,
                                     "num_actions": num_actions,
                                     "hiddens": self._hiddens()}
        return spec

    def sac_specs(self) -> Dict[str, Any]:
        """(actor, critic) dims for SAC's Gaussian actor + Q critics."""
        enc = self.encoder_spec()
        if _is_discrete(self.action_space):
            raise ValueError("SAC catalog requires a Box action space")
        act_dim = int(self.action_space.shape[0])
        return {"obs_dim": self.encoded_dim(enc), "act_dim": act_dim,
                "hiddens": tuple(self.model_config.get(
                    "fcnet_hiddens", (256, 256)))}

    @classmethod
    def from_env(cls, env_id: str, env_config: Optional[dict] = None,
                 model_config: Optional[dict] = None) -> "Catalog":
        from ray_tpu.rllib.env_runner import make_env

        env = make_env(env_id, env_config)
        try:
            return cls(env.observation_space, env.action_space, model_config)
        finally:
            env.close()
