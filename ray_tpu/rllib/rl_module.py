"""RLModule: the policy/value network as a pure JAX params pytree + apply
functions (reference: ray rllib/core/rl_module/rl_module.py — the
forward_exploration / forward_inference / forward_train triple; torch
nn.Module there, functional JAX here so the same apply runs inside the
EnvRunner's jit action step and the Learner's jit update).
"""

from __future__ import annotations

from typing import Any, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _dense_init(key, in_dim: int, out_dim: int, scale: float = None):
    kw, _ = jax.random.split(key)
    scale = scale if scale is not None else float(np.sqrt(2.0 / in_dim))
    return {
        "w": jax.random.normal(kw, (in_dim, out_dim)) * scale,
        "b": jnp.zeros((out_dim,)),
    }


def _dense(p, x):
    return x @ p["w"] + p["b"]


class DiscreteActorCriticModule:
    """MLP torso + policy logits head + value head (discrete actions)."""

    def __init__(self, obs_dim: int, num_actions: int,
                 hiddens: Sequence[int] = (64, 64)):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.hiddens = tuple(hiddens)

    def init(self, key) -> Dict[str, Any]:
        params: Dict[str, Any] = {"torso": []}
        dims = [self.obs_dim] + list(self.hiddens)
        keys = jax.random.split(key, len(dims) + 1)
        for i in range(len(dims) - 1):
            params["torso"].append(_dense_init(keys[i], dims[i], dims[i + 1]))
        params["pi"] = _dense_init(keys[-2], dims[-1], self.num_actions,
                                   scale=0.01)
        params["vf"] = _dense_init(keys[-1], dims[-1], 1, scale=1.0)
        return params

    def _torso(self, params, obs):
        x = obs
        for layer in params["torso"]:
            x = jnp.tanh(_dense(layer, x))
        return x

    def forward(self, params, obs) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """-> (logits [B, A], value [B])"""
        x = self._torso(params, obs)
        return _dense(params["pi"], x), _dense(params["vf"], x)[..., 0]

    # -- RLModule API --------------------------------------------------------

    def forward_inference(self, params, batch: Dict[str, jnp.ndarray]):
        logits, _ = self.forward(params, batch["obs"])
        return {"actions": jnp.argmax(logits, axis=-1)}

    def forward_exploration(self, params, batch, key):
        logits, value = self.forward(params, batch["obs"])
        actions = jax.random.categorical(key, logits)
        logp = jax.nn.log_softmax(logits)[
            jnp.arange(logits.shape[0]), actions]
        return {"actions": actions, "logp": logp, "vf_preds": value}

    def forward_train(self, params, batch):
        logits, value = self.forward(params, batch["obs"])
        logp_all = jax.nn.log_softmax(logits)
        logp = logp_all[jnp.arange(logits.shape[0]), batch["actions"]]
        entropy = -jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1)
        return {"logp": logp, "vf_preds": value, "entropy": entropy,
                "logits": logits}


class ConvActorCriticModule:
    """Conv torso (Nature-CNN shape family) + policy/value heads for
    image observations (reference: rllib core/models/configs.py:637
    CNNEncoderConfig + the torch CNN encoder; here NHWC lax convs so XLA
    tiles them onto the MXU, bf16-friendly, uint8 obs normalized on-device
    to keep sample transport at 1 byte/pixel).

    obs: [B, H, W, C] uint8 (or float); conv_filters: (out_ch, kernel,
    stride) triples, VALID padding.
    """

    def __init__(self, obs_shape: Sequence[int], num_actions: int,
                 conv_filters: Sequence[Tuple[int, int, int]] = (
                     (32, 8, 4), (64, 4, 2), (64, 3, 1)),
                 hiddens: Sequence[int] = (512,)):
        self.obs_shape = tuple(obs_shape)
        self.num_actions = num_actions
        self.conv_filters = tuple(tuple(f) for f in conv_filters)
        self.hiddens = tuple(hiddens)
        # VALID-padding output spatial dims -> flatten width for the dense
        # stack (shape math here so init needs no tracing).
        h, w, c = self.obs_shape
        for _out, k, s in self.conv_filters:
            h = (h - k) // s + 1
            w = (w - k) // s + 1
            if h <= 0 or w <= 0:
                raise ValueError(
                    f"conv_filters {conv_filters} reduce a {self.obs_shape}"
                    " observation below 1x1; use smaller kernels/strides")
        self._flat_dim = h * w * self.conv_filters[-1][0]

    def init(self, key) -> Dict[str, Any]:
        params: Dict[str, Any] = {"convs": [], "torso": []}
        n_conv = len(self.conv_filters)
        keys = jax.random.split(key, n_conv + len(self.hiddens) + 2)
        in_ch = self.obs_shape[-1]
        for i, (out_ch, k, _s) in enumerate(self.conv_filters):
            fan_in = k * k * in_ch
            params["convs"].append({
                "w": jax.random.normal(keys[i], (k, k, in_ch, out_ch))
                * np.sqrt(2.0 / fan_in),
                "b": jnp.zeros((out_ch,)),
            })
            in_ch = out_ch
        dims = [self._flat_dim] + list(self.hiddens)
        for i in range(len(dims) - 1):
            params["torso"].append(
                _dense_init(keys[n_conv + i], dims[i], dims[i + 1]))
        params["pi"] = _dense_init(keys[-2], dims[-1], self.num_actions,
                                   scale=0.01)
        params["vf"] = _dense_init(keys[-1], dims[-1], 1, scale=1.0)
        return params

    def _torso(self, params, obs):
        x = obs.astype(jnp.float32)
        if obs.dtype == jnp.uint8:
            x = x / 255.0
        for layer, (_out, _k, s) in zip(params["convs"], self.conv_filters):
            x = jax.lax.conv_general_dilated(
                x, layer["w"], window_strides=(s, s), padding="VALID",
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            x = jax.nn.relu(x + layer["b"])
        x = x.reshape(x.shape[0], -1)
        for layer in params["torso"]:
            x = jax.nn.relu(_dense(layer, x))
        return x

    def forward(self, params, obs) -> Tuple[jnp.ndarray, jnp.ndarray]:
        x = self._torso(params, obs)
        return _dense(params["pi"], x), _dense(params["vf"], x)[..., 0]

    # same RLModule API as DiscreteActorCriticModule
    forward_inference = DiscreteActorCriticModule.forward_inference
    forward_exploration = DiscreteActorCriticModule.forward_exploration
    forward_train = DiscreteActorCriticModule.forward_train


class QModule:
    """MLP Q-network for DQN (discrete actions)."""

    def __init__(self, obs_dim: int, num_actions: int,
                 hiddens: Sequence[int] = (64, 64)):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.hiddens = tuple(hiddens)

    def init(self, key) -> Dict[str, Any]:
        params: Dict[str, Any] = {"layers": []}
        dims = [self.obs_dim] + list(self.hiddens) + [self.num_actions]
        keys = jax.random.split(key, len(dims))
        for i in range(len(dims) - 1):
            params["layers"].append(_dense_init(keys[i], dims[i], dims[i + 1]))
        return params

    def forward(self, params, obs) -> jnp.ndarray:
        x = obs
        layers = params["layers"]
        for layer in layers[:-1]:
            x = jnp.tanh(_dense(layer, x))
        return _dense(layers[-1], x)


class GaussianActorModule:
    """Squashed-Gaussian policy for continuous actions (SAC actor;
    reference: rllib sac policy's tanh-squashed DiagGaussian)."""

    def __init__(self, obs_dim: int, act_dim: int,
                 hiddens: Sequence[int] = (256, 256),
                 log_std_bounds=(-10.0, 2.0)):
        self.obs_dim = obs_dim
        self.act_dim = act_dim
        self.hiddens = tuple(hiddens)
        self.log_std_bounds = log_std_bounds

    def init(self, key) -> Dict[str, Any]:
        params: Dict[str, Any] = {"torso": []}
        dims = [self.obs_dim] + list(self.hiddens)
        keys = jax.random.split(key, len(dims) + 1)
        for i in range(len(dims) - 1):
            params["torso"].append(_dense_init(keys[i], dims[i], dims[i + 1]))
        params["mu"] = _dense_init(keys[-2], dims[-1], self.act_dim, scale=0.01)
        params["log_std"] = _dense_init(keys[-1], dims[-1], self.act_dim,
                                        scale=0.01)
        return params

    def _dist(self, params, obs):
        x = obs
        for layer in params["torso"]:
            x = jax.nn.relu(_dense(layer, x))
        mu = _dense(params["mu"], x)
        lo, hi = self.log_std_bounds
        log_std = jnp.clip(_dense(params["log_std"], x), lo, hi)
        return mu, log_std

    def sample(self, params, obs, key):
        """-> (action in [-1,1], log_prob) with tanh squash correction."""
        mu, log_std = self._dist(params, obs)
        std = jnp.exp(log_std)
        eps = jax.random.normal(key, mu.shape)
        pre = mu + std * eps
        act = jnp.tanh(pre)
        logp = jnp.sum(
            -0.5 * (eps ** 2 + 2 * log_std + jnp.log(2 * jnp.pi))
            - jnp.log(jnp.clip(1 - act ** 2, 1e-6)), axis=-1)
        return act, logp

    # RLModule-style API (used by EnvRunner)
    def forward_exploration(self, params, batch, key):
        act, logp = self.sample(params, batch["obs"], key)
        return {"actions": act, "logp": logp,
                "vf_preds": jnp.zeros(act.shape[0])}

    def forward_inference(self, params, batch):
        mu, _ = self._dist(params, batch["obs"])
        return {"actions": jnp.tanh(mu)}


class ContinuousQModule:
    """Q(s, a) head for continuous control (SAC critic)."""

    def __init__(self, obs_dim: int, act_dim: int,
                 hiddens: Sequence[int] = (256, 256)):
        self.obs_dim = obs_dim
        self.act_dim = act_dim
        self.hiddens = tuple(hiddens)

    def init(self, key) -> Dict[str, Any]:
        params: Dict[str, Any] = {"layers": []}
        dims = [self.obs_dim + self.act_dim] + list(self.hiddens) + [1]
        keys = jax.random.split(key, len(dims))
        for i in range(len(dims) - 1):
            params["layers"].append(_dense_init(keys[i], dims[i], dims[i + 1]))
        return params

    def forward(self, params, obs, act) -> jnp.ndarray:
        x = jnp.concatenate([obs, act], axis=-1)
        layers = params["layers"]
        for layer in layers[:-1]:
            x = jax.nn.relu(_dense(layer, x))
        return _dense(layers[-1], x)[..., 0]


def apply_encoder(enc: Dict[str, Any], obs):
    """Pure-JAX obs encoding for catalog-built composite/odd-shaped
    observation spaces (reference: the catalog's flatten/one-hot encoder
    configs, rllib core/models/configs.py). Returns a [B, D] float array.
    """
    kind = enc["kind"]
    if kind in ("mlp",):
        return obs
    if kind == "flatten":
        return obs.reshape(obs.shape[0], -1).astype(jnp.float32)
    if kind == "onehot":
        return jax.nn.one_hot(obs.astype(jnp.int32), enc["n"])
    if kind == "concat":
        parts = []
        for key, leaf in enc["leaves"]:
            sub = obs[key] if enc["container"] == "dict" else obs[int(key)]
            parts.append(apply_encoder(leaf, sub))
        return jnp.concatenate(parts, axis=-1)
    raise ValueError(f"unknown encoder kind {kind!r}")


class EncodedActorCriticModule(DiscreteActorCriticModule):
    """Actor-critic over a catalog encoder (one-hot / flatten /
    dict-concat observations)."""

    def __init__(self, encoder_spec: Dict[str, Any], num_actions: int,
                 hiddens: Sequence[int] = (64, 64)):
        from ray_tpu.rllib.catalog import Catalog

        super().__init__(Catalog.encoded_dim(encoder_spec), num_actions,
                         hiddens)
        self.encoder_spec = encoder_spec

    def _torso(self, params, obs):
        return super()._torso(params, apply_encoder(self.encoder_spec, obs))


class EncodedQModule(QModule):
    """Q-network over a catalog encoder."""

    def __init__(self, encoder_spec: Dict[str, Any], num_actions: int,
                 hiddens: Sequence[int] = (64, 64)):
        from ray_tpu.rllib.catalog import Catalog

        super().__init__(Catalog.encoded_dim(encoder_spec), num_actions,
                         hiddens)
        self.encoder_spec = encoder_spec

    def forward(self, params, obs) -> jnp.ndarray:
        return super().forward(params,
                               apply_encoder(self.encoder_spec, obs))


def resolve_module(module_spec: Dict[str, Any]):
    """Build the RLModule named by module_spec['module_class'] (defaults to
    DiscreteActorCriticModule). Accepts a class or "module:ClassName"."""
    cls = module_spec.get("module_class", DiscreteActorCriticModule)
    if isinstance(cls, str):
        import importlib

        mod, _, name = cls.rpartition(":")
        cls = getattr(importlib.import_module(mod or __name__), name)
    kwargs = dict(module_spec.get("module_kwargs") or {})
    if cls is ConvActorCriticModule:
        return cls(module_spec["obs_shape"], module_spec["num_actions"],
                   module_spec.get("conv_filters",
                                   ((32, 8, 4), (64, 4, 2), (64, 3, 1))),
                   module_spec.get("hiddens", (512,)))
    if cls is DiscreteActorCriticModule:
        return cls(module_spec["obs_dim"], module_spec["num_actions"],
                   module_spec.get("hiddens", (64, 64)))
    if kwargs:
        return cls(**kwargs)
    args = [module_spec["obs_dim"],
            module_spec.get("num_actions") or module_spec["act_dim"]]
    if module_spec.get("hiddens"):
        args.append(tuple(module_spec["hiddens"]))
    return cls(*args)
