"""RLModule: the policy/value network as a pure JAX params pytree + apply
functions (reference: ray rllib/core/rl_module/rl_module.py — the
forward_exploration / forward_inference / forward_train triple; torch
nn.Module there, functional JAX here so the same apply runs inside the
EnvRunner's jit action step and the Learner's jit update).
"""

from __future__ import annotations

from typing import Any, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _dense_init(key, in_dim: int, out_dim: int, scale: float = None):
    kw, _ = jax.random.split(key)
    scale = scale if scale is not None else float(np.sqrt(2.0 / in_dim))
    return {
        "w": jax.random.normal(kw, (in_dim, out_dim)) * scale,
        "b": jnp.zeros((out_dim,)),
    }


def _dense(p, x):
    return x @ p["w"] + p["b"]


class DiscreteActorCriticModule:
    """MLP torso + policy logits head + value head (discrete actions)."""

    def __init__(self, obs_dim: int, num_actions: int,
                 hiddens: Sequence[int] = (64, 64)):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.hiddens = tuple(hiddens)

    def init(self, key) -> Dict[str, Any]:
        params: Dict[str, Any] = {"torso": []}
        dims = [self.obs_dim] + list(self.hiddens)
        keys = jax.random.split(key, len(dims) + 1)
        for i in range(len(dims) - 1):
            params["torso"].append(_dense_init(keys[i], dims[i], dims[i + 1]))
        params["pi"] = _dense_init(keys[-2], dims[-1], self.num_actions,
                                   scale=0.01)
        params["vf"] = _dense_init(keys[-1], dims[-1], 1, scale=1.0)
        return params

    def _torso(self, params, obs):
        x = obs
        for layer in params["torso"]:
            x = jnp.tanh(_dense(layer, x))
        return x

    def forward(self, params, obs) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """-> (logits [B, A], value [B])"""
        x = self._torso(params, obs)
        return _dense(params["pi"], x), _dense(params["vf"], x)[..., 0]

    # -- RLModule API --------------------------------------------------------

    def forward_inference(self, params, batch: Dict[str, jnp.ndarray]):
        logits, _ = self.forward(params, batch["obs"])
        return {"actions": jnp.argmax(logits, axis=-1)}

    def forward_exploration(self, params, batch, key):
        logits, value = self.forward(params, batch["obs"])
        actions = jax.random.categorical(key, logits)
        logp = jax.nn.log_softmax(logits)[
            jnp.arange(logits.shape[0]), actions]
        return {"actions": actions, "logp": logp, "vf_preds": value}

    def forward_train(self, params, batch):
        logits, value = self.forward(params, batch["obs"])
        logp_all = jax.nn.log_softmax(logits)
        logp = logp_all[jnp.arange(logits.shape[0]), batch["actions"]]
        entropy = -jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1)
        return {"logp": logp, "vf_preds": value, "entropy": entropy,
                "logits": logits}


class QModule:
    """MLP Q-network for DQN (discrete actions)."""

    def __init__(self, obs_dim: int, num_actions: int,
                 hiddens: Sequence[int] = (64, 64)):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.hiddens = tuple(hiddens)

    def init(self, key) -> Dict[str, Any]:
        params: Dict[str, Any] = {"layers": []}
        dims = [self.obs_dim] + list(self.hiddens) + [self.num_actions]
        keys = jax.random.split(key, len(dims))
        for i in range(len(dims) - 1):
            params["layers"].append(_dense_init(keys[i], dims[i], dims[i + 1]))
        return params

    def forward(self, params, obs) -> jnp.ndarray:
        x = obs
        layers = params["layers"]
        for layer in layers[:-1]:
            x = jnp.tanh(_dense(layer, x))
        return _dense(layers[-1], x)
