"""RLlib callback API: user hooks into the training loop.

Reference: ray rllib/algorithms/callbacks.py (DefaultCallbacks, renamed
RLlibCallback on the new stack) — configured with
``config.callbacks(MyCallbacks)`` and invoked by the Algorithm around
init / train results / episode completion / checkpointing.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

__all__ = ["RLlibCallback", "DefaultCallbacks"]


class RLlibCallback:
    """Subclass and override any hook; all are optional no-ops."""

    def on_algorithm_init(self, *, algorithm, **kwargs) -> None:
        pass

    def on_train_result(self, *, algorithm,
                        result: Dict[str, Any], **kwargs) -> None:
        pass

    def on_episode_end(self, *, episode, algorithm=None, **kwargs) -> None:
        pass

    def on_checkpoint_saved(self, *, algorithm, checkpoint_dir: str,
                            **kwargs) -> None:
        pass

    def on_checkpoint_loaded(self, *, algorithm, checkpoint_dir: str,
                             **kwargs) -> None:
        pass


DefaultCallbacks = RLlibCallback  # legacy alias (reference keeps both)


def make_callbacks(spec) -> Optional[RLlibCallback]:
    """Instantiate the configured callbacks: a class, an instance, or
    None."""
    if spec is None:
        return None
    if isinstance(spec, RLlibCallback):
        return spec
    if isinstance(spec, type):
        return spec()
    raise TypeError(
        f"callbacks must be an RLlibCallback subclass or instance, "
        f"got {spec!r}")
