"""ConnectorV2 pipelines (reference: ray rllib/connectors/connector_v2.py:18
— composable transforms between env <-> module <-> learner; standard pieces
like observation preprocessing and batching live here rather than inside
algorithms)."""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np


class ConnectorV2:
    """One transform stage. Subclasses override __call__(batch) -> batch."""

    def __call__(self, batch: Dict[str, Any], **kwargs) -> Dict[str, Any]:
        raise NotImplementedError

    def get_state(self) -> Dict[str, Any]:
        return {}

    def set_state(self, state: Dict[str, Any]) -> None:
        pass


class ConnectorPipelineV2(ConnectorV2):
    def __init__(self, connectors: Optional[List[ConnectorV2]] = None):
        self.connectors = list(connectors or [])

    def __call__(self, batch, **kwargs):
        for c in self.connectors:
            batch = c(batch, **kwargs)
        return batch

    def append(self, connector: ConnectorV2) -> "ConnectorPipelineV2":
        self.connectors.append(connector)
        return self

    def prepend(self, connector: ConnectorV2) -> "ConnectorPipelineV2":
        self.connectors.insert(0, connector)
        return self

    def get_state(self):
        return {i: c.get_state() for i, c in enumerate(self.connectors)}

    def set_state(self, state):
        for i, c in enumerate(self.connectors):
            if i in state:
                c.set_state(state[i])


class FlattenObservations(ConnectorV2):
    """Flatten dict/nested observations into a single [B, D] array."""

    def __call__(self, batch, **kwargs):
        obs = batch.get("obs")
        if isinstance(obs, dict):
            parts = [np.asarray(obs[k], np.float32).reshape(
                len(next(iter(obs.values()))), -1) for k in sorted(obs)]
            batch["obs"] = np.concatenate(parts, axis=-1)
        elif obs is not None:
            arr = np.asarray(obs, np.float32)
            batch["obs"] = arr.reshape(arr.shape[0], -1)
        return batch


class NormalizeObservations(ConnectorV2):
    """Running mean/std normalization (Welford), the classic env-to-module
    connector for MuJoCo-style continuous control."""

    def __init__(self, clip: float = 10.0, eps: float = 1e-8):
        self.clip = clip
        self.eps = eps
        self._count = 0.0
        self._mean: Optional[np.ndarray] = None
        self._m2: Optional[np.ndarray] = None

    def __call__(self, batch, *, update_stats: bool = True, **kwargs):
        obs = np.asarray(batch["obs"], np.float64)
        if self._mean is None:
            self._mean = np.zeros(obs.shape[-1])
            self._m2 = np.zeros(obs.shape[-1])
        if update_stats:
            for row in obs.reshape(-1, obs.shape[-1]):
                self._count += 1.0
                delta = row - self._mean
                self._mean += delta / self._count
                self._m2 += delta * (row - self._mean)
        var = self._m2 / max(self._count, 1.0)
        norm = (obs - self._mean) / np.sqrt(var + self.eps)
        batch["obs"] = np.clip(norm, -self.clip, self.clip).astype(np.float32)
        return batch

    def get_state(self):
        return {"count": self._count,
                "mean": None if self._mean is None else self._mean.copy(),
                "m2": None if self._m2 is None else self._m2.copy()}

    def set_state(self, state):
        self._count = state["count"]
        self._mean = state["mean"]
        self._m2 = state["m2"]


class ClipRewards(ConnectorV2):
    """Learner connector: clip rewards into [-bound, bound] (Atari-style)."""

    def __init__(self, bound: float = 1.0):
        self.bound = bound

    def __call__(self, batch, **kwargs):
        if "rewards" in batch:
            batch["rewards"] = np.clip(
                np.asarray(batch["rewards"], np.float32),
                -self.bound, self.bound)
        return batch
