"""Exploration strategies (reference: ray rllib/utils/exploration/ —
EpsilonGreedy, GaussianNoise, OrnsteinUhlenbeckNoise, StochasticSampling;
configured via ``exploration_config={"type": ...}``).

Strategies are small stateful objects the sampling side consults per env
step: ``get_action(t, greedy_action_fn, action_space_n_or_shape, rng)``.
They hold schedules, not network state, so they stay picklable across
env-runner actors.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np


class Exploration:
    def select_discrete(self, t: int, greedy_fn, num_actions: int,
                        rng: np.random.Generator) -> int:
        """greedy_fn() -> int action; t = lifetime env steps."""
        raise NotImplementedError

    def perturb_continuous(self, t: int, action: np.ndarray,
                           rng: np.random.Generator) -> np.ndarray:
        raise NotImplementedError

    def get_state(self) -> Dict[str, Any]:
        return {}

    def set_state(self, state: Dict[str, Any]) -> None:
        pass


class EpsilonGreedy(Exploration):
    """Linear (or piecewise) epsilon schedule over env steps."""

    def __init__(self,
                 initial_epsilon: float = 1.0,
                 final_epsilon: float = 0.05,
                 epsilon_timesteps: int = 10_000,
                 schedule: Optional[Sequence[Tuple[int, float]]] = None):
        if schedule is not None:
            self.schedule = [(int(t), float(e)) for t, e in schedule]
        else:
            self.schedule = [(0, initial_epsilon),
                             (epsilon_timesteps, final_epsilon)]

    def epsilon(self, t: int) -> float:
        sched = self.schedule
        if t <= sched[0][0]:
            return sched[0][1]
        for (t0, e0), (t1, e1) in zip(sched, sched[1:]):
            if t < t1:
                frac = (t - t0) / max(1, t1 - t0)
                return e0 + frac * (e1 - e0)
        return sched[-1][1]

    def select_discrete(self, t, greedy_fn, num_actions, rng):
        if rng.random() < self.epsilon(t):
            return int(rng.integers(num_actions))
        return greedy_fn()


class StochasticSampling(Exploration):
    """Sample from the policy distribution (the PPO-family default): the
    module's forward_exploration already samples, so discrete selection
    just defers to it; provided for config parity."""

    def select_discrete(self, t, greedy_fn, num_actions, rng):
        return greedy_fn()

    def perturb_continuous(self, t, action, rng):
        return action


class GaussianNoise(Exploration):
    """Additive Gaussian action noise with linear stddev decay (continuous
    control)."""

    def __init__(self, initial_scale: float = 1.0,
                 final_scale: float = 0.02,
                 scale_timesteps: int = 10_000,
                 stddev: float = 0.1):
        self.initial_scale = initial_scale
        self.final_scale = final_scale
        self.scale_timesteps = scale_timesteps
        self.stddev = stddev

    def _scale(self, t: int) -> float:
        frac = min(1.0, t / max(1, self.scale_timesteps))
        return self.initial_scale + frac * (
            self.final_scale - self.initial_scale)

    def perturb_continuous(self, t, action, rng):
        noise = rng.normal(0.0, self.stddev, size=np.shape(action))
        return np.clip(action + self._scale(t) * noise, -1.0, 1.0)


class OrnsteinUhlenbeckNoise(Exploration):
    """Temporally-correlated OU noise (DDPG-style continuous
    exploration)."""

    def __init__(self, ou_theta: float = 0.15, ou_sigma: float = 0.2,
                 ou_base_scale: float = 0.1):
        self.theta = ou_theta
        self.sigma = ou_sigma
        self.base_scale = ou_base_scale
        self._state: Optional[np.ndarray] = None

    def perturb_continuous(self, t, action, rng):
        if self._state is None or self._state.shape != np.shape(action):
            self._state = np.zeros(np.shape(action))
        self._state = (self._state - self.theta * self._state
                       + self.sigma * rng.normal(size=np.shape(action)))
        return np.clip(action + self.base_scale * self._state, -1.0, 1.0)

    def get_state(self):
        return {"ou_state": self._state}

    def set_state(self, state):
        self._state = state.get("ou_state")


_TYPES = {
    "EpsilonGreedy": EpsilonGreedy,
    "StochasticSampling": StochasticSampling,
    "GaussianNoise": GaussianNoise,
    "OrnsteinUhlenbeckNoise": OrnsteinUhlenbeckNoise,
}


def make_exploration(config: Optional[Dict[str, Any]],
                     default: str = "StochasticSampling") -> Exploration:
    """Build from ``exploration_config`` ({"type": name, **kwargs}); the
    type may also be a class."""
    config = dict(config or {})
    typ = config.pop("type", default)
    if isinstance(typ, str):
        if typ not in _TYPES:
            raise ValueError(f"unknown exploration type {typ!r}; "
                             f"available: {sorted(_TYPES)}")
        typ = _TYPES[typ]
    return typ(**config)
