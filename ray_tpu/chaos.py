"""Public chaos-engineering API: deterministic, seeded fault injection.

Wraps `ray_tpu._private.fault_injection` (the layer wired into the RPC
chokepoint) for tests and operators:

    import ray_tpu.chaos as chaos

    plan = chaos.ChaosPlan(seed=7)
    plan.add_rule(chaos.ChaosRule(
        action="drop", site="after_reply", method="request_worker_lease",
        label="raylet", times=2))
    plan.partition("127.0.0.1:5001", "127.0.0.1:5002")
    chaos.install(plan)          # this process only (tests)
    ...
    chaos.uninstall()
    assert plan.fingerprint() == expected   # same seed => same sequence

Cluster-wide, either export ``RAY_TPU_CHAOS`` (inline JSON or a path)
before starting nodes — every process arms itself at import — or drive a
live cluster through the GCS (`ray-tpu chaos start|stop|status`, or
`chaos.start_cluster(...)` below).
"""

from __future__ import annotations

from typing import Optional

from ray_tpu._private.fault_injection import (  # noqa: F401
    ACTIONS,
    ENV_VAR,
    SITE_AFTER_REPLY,
    SITE_BEFORE_EXECUTE,
    SITE_CLIENT_REQUEST,
    SITE_MID_STREAM,
    ChaosError,
    ChaosPlan,
    ChaosRule,
    active_plan,
    install,
    load_env_plan,
    uninstall,
)

__all__ = [
    "ACTIONS", "ENV_VAR",
    "SITE_AFTER_REPLY", "SITE_BEFORE_EXECUTE", "SITE_CLIENT_REQUEST",
    "SITE_MID_STREAM",
    "ChaosError", "ChaosPlan", "ChaosRule",
    "active_plan", "install", "load_env_plan", "uninstall",
    "start_cluster", "stop_cluster", "cluster_status",
    "injection_history",
]


def _gcs_call(gcs_address: str, method: str, payload: dict, timeout: float):
    from ray_tpu._private.rpc import EventLoopThread, RpcClient

    lt = EventLoopThread("chaos-ctl")
    client = RpcClient(gcs_address, lt)
    try:
        return client.call(method, payload, timeout=timeout)
    finally:
        client.close()
        lt.stop()


def start_cluster(plan: "ChaosPlan | str", gcs_address: str,
                  timeout: float = 30.0) -> dict:
    """Install a plan on the GCS and every alive raylet of a live
    cluster. `plan` may be a ChaosPlan or its JSON."""
    plan_json = plan if isinstance(plan, str) else plan.to_json()
    ChaosPlan.from_json(plan_json)  # fail fast on malformed input
    return _gcs_call(gcs_address, "chaos_start", {"plan": plan_json}, timeout)


def stop_cluster(gcs_address: str, timeout: float = 30.0) -> dict:
    """Uninstall the plan cluster-wide; returns per-node stats."""
    return _gcs_call(gcs_address, "chaos_stop", {}, timeout)


def cluster_status(gcs_address: str, timeout: float = 30.0) -> dict:
    """Plan installation state + fired-injection stats per node."""
    return _gcs_call(gcs_address, "chaos_status", {}, timeout)


def status() -> Optional[dict]:
    """In-process plan stats (None when no plan is installed)."""
    plan = active_plan()
    return plan.stats() if plan is not None else None


def injection_history(gcs_address: str, timeout: float = 30.0,
                      limit: int = 100_000) -> dict:
    """A chaos run's ACTUAL injection history, sourced from the cluster
    lifecycle EVENT LOG rather than the in-memory plan: per-rule match
    counts stay auditable after `chaos stop` dropped the plan object (and
    they include firings from worker processes whose plan stats never
    reach the GCS)."""
    events = _gcs_call(gcs_address, "get_cluster_events",
                       {"type": "chaos.*", "limit": limit}, timeout)
    by_rule: dict = {}
    by_action: dict = {}
    recent = []
    for ev in reversed(events):  # chronological
        data = ev.get("data") or {}
        if ev.get("type") == "chaos.inject":
            rule = data.get("rule", -1)
            by_rule[rule] = by_rule.get(rule, 0) + 1
            action = data.get("action", "?")
        elif ev.get("type") == "chaos.partition":
            action = "partition"
        else:  # chaos.plan install/uninstall markers
            action = f"plan.{data.get('op', '?')}"
        by_action[action] = by_action.get(action, 0) + 1
        recent.append({"time": ev.get("time"), "proc": ev.get("proc"),
                       "type": ev.get("type"), **data})
    return {
        "injections": sum(by_rule.values()),
        "by_rule": {str(k): v for k, v in sorted(by_rule.items())},
        "by_action": by_action,
        "recent": recent[-20:],
    }
