"""Model multiplexing (reference: ray python/ray/serve/multiplex.py:22
_ModelMultiplexWrapper LRU + api.py:609 @serve.multiplexed +
get_multiplexed_model_id): one replica serves many models, loading on
demand and evicting least-recently-used beyond max_num_models_per_replica.

NOTE on structure: all runtime state (locks, LRU caches) lives at module
level and every helper is a module-level function — the wrapper closure is
pickled into replicas, and cloudpickle serializes dynamic closures' captured
globals by value (a captured lock would fail).
"""

from __future__ import annotations

import functools
import inspect
import threading
from collections import OrderedDict
from typing import Any, Callable, Optional

from ray_tpu.serve.context import (
    get_multiplexed_model_id,
    set_multiplexed_model_id,
)

_mux_lock = threading.Lock()
_mux_caches: dict = {}


def _cache_get(load_fn: Callable, instance, model_id: str):
    with _mux_lock:
        cache = _mux_caches.setdefault(
            (id(load_fn), id(instance)), OrderedDict())
        if model_id in cache:
            cache.move_to_end(model_id)
            return cache[model_id], True
    return None, False


def _cache_put(load_fn: Callable, instance, model_id: str, model: Any,
               max_models: int) -> None:
    with _mux_lock:
        cache = _mux_caches.setdefault(
            (id(load_fn), id(instance)), OrderedDict())
        cache[model_id] = model
        cache.move_to_end(model_id)
        while len(cache) > max_models:
            cache.popitem(last=False)


def multiplexed(_func: Optional[Callable] = None, *,
                max_num_models_per_replica: int = 3):
    def wrap(load_fn: Callable):
        params = list(inspect.signature(load_fn).parameters)
        is_method = bool(params) and params[0] == "self"
        is_async = inspect.iscoroutinefunction(load_fn)

        @functools.wraps(load_fn)
        def sync_wrapper(*args):
            instance, model_id = (args[0], args[1]) if is_method \
                else (None, args[0])
            set_multiplexed_model_id(model_id)
            model, hit = _cache_get(load_fn, instance, model_id)
            if hit:
                return model
            model = load_fn(*args)
            _cache_put(load_fn, instance, model_id, model,
                       max_num_models_per_replica)
            return model

        @functools.wraps(load_fn)
        async def async_wrapper(*args):
            instance, model_id = (args[0], args[1]) if is_method \
                else (None, args[0])
            set_multiplexed_model_id(model_id)
            model, hit = _cache_get(load_fn, instance, model_id)
            if hit:
                return model
            model = await load_fn(*args)
            _cache_put(load_fn, instance, model_id, model,
                       max_num_models_per_replica)
            return model

        return async_wrapper if is_async else sync_wrapper

    if _func is not None:
        return wrap(_func)
    return wrap


__all__ = ["multiplexed", "get_multiplexed_model_id"]
