"""DeploymentHandle / DeploymentResponse (reference: ray
python/ray/serve/handle.py:714 DeploymentHandle, .remote() :786 —
composition: handles passed into other deployments' constructors route
requests replica-to-replica without the proxy).
"""

from __future__ import annotations

from typing import Any, Optional

import ray_tpu


class DeploymentResponse:
    """Future for a deployment request (awaitable via .result())."""

    def __init__(self, ref, router=None):
        self._ref = ref
        self._router = router

    def _done(self):
        # releases the router's in-flight charge (probe-free load signal)
        if self._router is not None:
            self._router.notify_done(self._ref)
            self._router = None

    def result(self, timeout_s: Optional[float] = None) -> Any:
        try:
            return ray_tpu.get(self._ref, timeout=timeout_s)
        finally:
            self._done()

    def _to_object_ref(self):
        # composed into another deployment's args: the downstream replica
        # resolves it; release the charge here
        self._done()
        return self._ref

    def __await__(self):
        try:
            result = yield from self._ref.__await__()
        finally:
            self._done()
        return result


class DeploymentResponseGenerator:
    """Iterator over a streaming deployment call's chunks (reference:
    handle.py DeploymentResponseGenerator — .options(stream=True))."""

    def __init__(self, ref_gen):
        self._gen = ref_gen

    def __iter__(self):
        for ref in self._gen:
            yield ray_tpu.get(ref)

    def __next__(self):
        return ray_tpu.get(next(self._gen))

    def close(self):
        """Cancel the replica-side generator task (e.g. client went away)."""
        close = getattr(self._gen, "close", None)
        if close is not None:
            close()


class DeploymentHandle:
    def __init__(self, deployment_name: str, app_name: str = "",
                 method_name: str = "__call__", controller=None,
                 stream: bool = False):
        self.deployment_name = deployment_name
        self.app_name = app_name
        self._method_name = method_name
        self._controller = controller
        self._stream = stream
        self._router = None

    def _get_router(self):
        if self._router is None:
            from ray_tpu.serve._private.router import shared_router
            from ray_tpu.serve.context import get_controller

            controller = self._controller or get_controller()
            self._router = shared_router(
                controller, self.deployment_name, self.app_name)
        return self._router

    def options(self, *, method_name: Optional[str] = None,
                stream: Optional[bool] = None, **_kw) -> "DeploymentHandle":
        h = DeploymentHandle(
            self.deployment_name, self.app_name,
            method_name or self._method_name, self._controller,
            self._stream if stream is None else stream)
        h._router = self._router
        return h

    def __getattr__(self, name: str) -> "DeploymentHandle":
        if name.startswith("_"):
            raise AttributeError(name)
        return self.options(method_name=name)

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        # Unwrap nested DeploymentResponses so composed models pass values.
        args = tuple(a._to_object_ref() if isinstance(a, DeploymentResponse)
                     else a for a in args)
        kwargs = {k: v._to_object_ref() if isinstance(v, DeploymentResponse)
                  else v for k, v in kwargs.items()}
        if self._stream:
            gen = self._get_router().assign_request_streaming(
                self._method_name, args, kwargs)
            return DeploymentResponseGenerator(gen)
        router = self._get_router()
        ref = router.assign_request(self._method_name, args, kwargs)
        return DeploymentResponse(ref, router)

    def try_remote(self, *args, **kwargs) -> Optional[DeploymentResponse]:
        """Non-blocking remote(): None when no replica is available yet
        instead of waiting for one (the proxy's event-loop fast path;
        unary calls only)."""
        if self._stream:
            raise ValueError("try_remote does not support stream=True")
        args = tuple(a._to_object_ref() if isinstance(a, DeploymentResponse)
                     else a for a in args)
        kwargs = {k: v._to_object_ref() if isinstance(v, DeploymentResponse)
                  else v for k, v in kwargs.items()}
        router = self._get_router()
        ref = router.try_assign_request(self._method_name, args, kwargs)
        if ref is None:
            return None
        return DeploymentResponse(ref, router)

    def __reduce__(self):
        return (DeploymentHandle,
                (self.deployment_name, self.app_name, self._method_name,
                 None, self._stream))
