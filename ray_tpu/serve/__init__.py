"""Model serving library.

Reference counterpart: Ray Serve (ray: python/ray/serve — serve.run
api.py:544, ServeController _private/controller.py:86, pow-2 router
_private/replica_scheduler/pow_2_scheduler.py:49, ReplicaActor
replica.py:231, DeploymentHandle handle.py:714, @serve.batch batching.py:468,
@serve.multiplexed multiplex.py:22).
"""

from ray_tpu.serve.api import (  # noqa: F401
    Application,
    Deployment,
    delete,
    deployment,
    get_app_handle,
    get_deployment_handle,
    ingress,
    run,
    shutdown,
    start,
    status,
)
from ray_tpu.serve.batching import batch  # noqa: F401
from ray_tpu.serve.context import (  # noqa: F401
    ReplicaContext,
    get_multiplexed_model_id,
    get_replica_context,
)
from ray_tpu.serve.handle import (  # noqa: F401
    DeploymentHandle,
    DeploymentResponse,
    DeploymentResponseGenerator,
)
from ray_tpu.serve.multiplex import multiplexed  # noqa: F401

__all__ = [
    "ReplicaContext",
    "get_replica_context",
    "Application",
    "Deployment",
    "DeploymentHandle",
    "DeploymentResponse",
    "DeploymentResponseGenerator",
    "batch",
    "delete",
    "deployment",
    "get_app_handle",
    "get_deployment_handle",
    "get_multiplexed_model_id",
    "ingress",
    "multiplexed",
    "run",
    "shutdown",
    "start",
    "status",
]
