"""Serve config schema + declarative deploy.

Reference: ray python/ray/serve/schema.py (pydantic ServeDeploySchema /
ServeApplicationSchema / DeploymentSchema powering the REST API and
`serve deploy` CLI). Dataclass-based here (no pydantic dependency): a JSON
config names applications by import path plus per-deployment overrides, and
`deploy_config` builds + runs them.
"""

from __future__ import annotations

import dataclasses
import importlib
import json
from typing import Any, Dict, List, Optional


@dataclasses.dataclass
class DeploymentSchema:
    name: str
    num_replicas: Optional[int] = None
    max_ongoing_requests: Optional[int] = None
    autoscaling_config: Optional[Dict[str, Any]] = None
    user_config: Any = None
    ray_actor_options: Optional[Dict[str, Any]] = None

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "DeploymentSchema":
        known = {f.name for f in dataclasses.fields(DeploymentSchema)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(f"unknown deployment fields: {sorted(unknown)}")
        return DeploymentSchema(**d)

    def overrides(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for f in ("num_replicas", "max_ongoing_requests",
                  "autoscaling_config", "user_config", "ray_actor_options"):
            v = getattr(self, f)
            if v is not None:
                out[f] = v
        return out


@dataclasses.dataclass
class ServeApplicationSchema:
    import_path: str                      # "module.sub:app_or_builder"
    name: str = "default"
    route_prefix: str = "/"
    args: Dict[str, Any] = dataclasses.field(default_factory=dict)
    runtime_env: Dict[str, Any] = dataclasses.field(default_factory=dict)
    deployments: List[DeploymentSchema] = dataclasses.field(
        default_factory=list)

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "ServeApplicationSchema":
        d = dict(d)
        deps = [DeploymentSchema.from_dict(x)
                for x in d.pop("deployments", [])]
        known = {f.name for f in dataclasses.fields(ServeApplicationSchema)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(f"unknown application fields: {sorted(unknown)}")
        return ServeApplicationSchema(deployments=deps, **d)


@dataclasses.dataclass
class ServeDeploySchema:
    applications: List[ServeApplicationSchema]
    # Typed gRPC ingress (reference: schema.py gRPCOptions — port +
    # grpc_servicer_functions, dotted paths to protoc-generated
    # add_XServicer_to_server functions importable on the cluster).
    grpc_options: Optional[Dict[str, Any]] = None

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "ServeDeploySchema":
        return ServeDeploySchema(applications=[
            ServeApplicationSchema.from_dict(a)
            for a in d.get("applications", [])
        ], grpc_options=d.get("grpc_options"))

    @staticmethod
    def parse_file(path: str) -> "ServeDeploySchema":
        with open(path) as f:
            return ServeDeploySchema.from_dict(json.load(f))


def _import_app(import_path: str, args: Dict[str, Any]):
    module_name, _, attr = import_path.partition(":")
    if not attr:
        raise ValueError(
            f"import_path {import_path!r} must be 'module:attribute'")
    target = getattr(importlib.import_module(module_name), attr)
    from ray_tpu.serve.api import Application

    if isinstance(target, Application):
        return target
    if callable(target):  # app builder taking args
        return target(args) if args else target()
    raise TypeError(f"{import_path} is neither an Application nor a builder")


def deploy_config(config: ServeDeploySchema) -> Dict[str, Any]:
    """Build and run every application in the config (the REST/CLI deploy
    path). Returns {app_name: handle}."""
    from ray_tpu import serve

    handles = {}
    for app_schema in config.applications:
        app = _import_app(app_schema.import_path, app_schema.args)
        overrides = {d.name: d.overrides() for d in app_schema.deployments}
        if overrides:
            _apply_overrides(app, overrides)
        handles[app_schema.name] = serve.run(
            app, name=app_schema.name, route_prefix=app_schema.route_prefix)
    if config.grpc_options:
        from ray_tpu.serve.api import _ensure_grpc_proxy

        actor, _port = _ensure_grpc_proxy(config.grpc_options)
        import ray_tpu

        ray_tpu.get(actor.update_routes.remote())
    return handles


def _apply_overrides(app, overrides: Dict[str, Dict[str, Any]]) -> None:
    """Apply per-deployment config overrides to a built application graph."""
    from ray_tpu.serve.api import BoundDeployment

    seen = set()

    def visit(bound: BoundDeployment):
        if id(bound) in seen:
            return
        seen.add(id(bound))
        ov = overrides.get(bound.deployment.name)
        if ov:
            bound.deployment = bound.deployment.options(**ov)
        for arg in list(bound.init_args) + list(bound.init_kwargs.values()):
            from ray_tpu.serve.api import _as_bound

            child = _as_bound(arg)
            if child is not None:
                visit(child)

    visit(app.root)
