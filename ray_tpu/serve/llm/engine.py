"""LLM engine replica: admission queue + continuous-batching loop.

One replica hosts one inference engine. Requests stream in from the
router as actor calls; a single batcher thread drains the admission
queue through the engine:

  * With a `PagedInferenceEngine` the batcher runs ONE long-lived
    `serve_stream` service loop — requests are admitted between decode
    chunks, so a request arriving mid-generation joins the running batch
    instead of waiting behind it (true continuous batching).
  * With the dense `InferenceEngine` (no dynamic admission) the batcher
    falls back to wave mode: it coalesces whatever is queued into one
    `generate_stream` run per wave — concurrency within a wave, queueing
    between waves.

Tokens flow back per-request through a hand-off queue; the replica's
`generate_stream` method is a plain generator, which the Serve layer
streams to callers as a streaming-generator task
(`num_returns="streaming"` — worker/core_worker.py:1123). Cancelling the
consumer's ObjectRefGenerator cancels the task, which lands in the
generator as an exception; the finally-block marks the request cancelled
and the engine frees its slot and KV blocks at the next feed poll.

TTFT (arrival -> first token) and TPOT (mean inter-token gap) are
observed here — at the point tokens leave the engine — into the tagged
histograms in serve/llm/metrics.py, alongside queue-depth and
batch-occupancy gauges the batcher refreshes every poll.
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
import queue
import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu._private import tracing as _tracing
from ray_tpu._private.config import CONFIG
from ray_tpu.inference import GenerationConfig
from ray_tpu.serve.llm import metrics as llm_metrics

logger = logging.getLogger(__name__)

_DONE = object()


class LLMOverloadedError(Exception):
    """Request shed by admission control; HTTP ingress maps it to 429."""

    status_code = 429


class LLMReplicaUnavailableError(Exception):
    """The engine replica serving a stream died (or became unreachable)
    AFTER the first token was emitted, so the router cannot silently
    retry — replaying the prompt on another replica would re-emit tokens
    the client already consumed. HTTP ingress maps it to 503; clients
    retry idempotently at the request level. Pre-first-token failures
    never surface this: the router fails over to another replica."""

    status_code = 503


class _Abort:
    def __init__(self, reason: str):
        self.reason = reason


class _Request:
    __slots__ = ("req_id", "prompt", "max_new", "gen_override", "out",
                 "enqueued_at", "first_at", "last_at", "n_tokens",
                 "cancelled")

    def __init__(self, req_id: int, prompt: List[int], max_new: int,
                 gen_override: Optional[GenerationConfig] = None):
        self.req_id = req_id
        self.prompt = prompt
        self.max_new = max_new
        self.gen_override = gen_override
        # bounded by the request's own max_new token budget (one entry
        # per generated token, consumer-drained)
        self.out: "queue.SimpleQueue" = queue.SimpleQueue()  # raylint: disable=unbounded-queue
        self.enqueued_at = time.monotonic()
        self.first_at: Optional[float] = None
        self.last_at: Optional[float] = None
        self.n_tokens = 0
        self.cancelled = False


class LLMEngineReplica:
    """Deployment callable wrapping an inference engine for serving."""

    def __init__(self, build_engine, default_config: Optional[dict] = None,
                 max_queue_depth: int = 64):
        """build_engine() -> PagedInferenceEngine | InferenceEngine
        (constructed in the replica so params land on its device).
        `max_queue_depth` bounds requests waiting for engine admission;
        beyond it submissions fail with LLMOverloadedError (the router
        sheds earlier — this is the per-replica backstop)."""
        self.engine = build_engine()
        self.default = GenerationConfig(**(default_config or {}))
        self._continuous = hasattr(self.engine, "serve_stream")
        self._max_queue_depth = max_queue_depth
        # bounded by max_queue_depth at submit (LLMOverloadedError 429
        # past it) — the 429-shed half of the overload-protection story
        self._queue: "queue.Queue[_Request]" = queue.Queue()  # raylint: disable=unbounded-queue
        self._requests: Dict[int, _Request] = {}
        self._lock = threading.Lock()
        self._cancels: set = set()
        self._next_id = itertools.count()
        self._seen_preemptions = 0
        self._seen_prefix: Dict[str, int] = {}
        self._n_finished = 0
        self._shutdown = threading.Event()
        # metric tag values (stable for this replica's lifetime)
        from ray_tpu.serve import context as serve_ctx

        try:
            ctx = serve_ctx.get_replica_context()
            self._tags = {"deployment": ctx.deployment,
                          "replica": ctx.replica_tag}
        except RuntimeError:  # constructed outside serve (tests, bench)
            self._tags = {"deployment": "llm", "replica": "local"}
        self._thread = threading.Thread(
            target=self._run, name="llm-batcher", daemon=True)
        self._thread.start()

    # -- request path --------------------------------------------------------

    def _backlog(self) -> int:
        """Requests waiting for an engine slot. NOT _queue.qsize(): the
        batcher drains the hand-off queue into the engine's internal
        pending list every poll, so qsize() reads ~0 under any load.
        Submitted-minus-decoding is the real admission backlog."""
        eng = self.engine
        decoding = eng.max_batch - len(eng.free_slots)
        with self._lock:
            return max(0, len(self._requests) - decoding)

    def _submit(self, prompt: List[int], max_new_tokens: Optional[int],
                gen_override: Optional[GenerationConfig]) -> _Request:
        if self._shutdown.is_set():
            raise RuntimeError("replica is shutting down")
        if self._backlog() >= self._max_queue_depth:
            llm_metrics.requests_counter().inc(
                tags={**self._tags, "outcome": "shed"})
            ambient = _tracing.current_trace()
            if ambient is not None:
                _tracing.force_trace(ambient.trace_id, "llm_shed:engine")
            raise LLMOverloadedError(
                f"engine admission backlog full "
                f"({self._max_queue_depth} requests waiting)")
        rq = _Request(next(self._next_id), list(prompt),
                      max_new_tokens if max_new_tokens is not None
                      else self.default.max_new_tokens, gen_override)
        with self._lock:
            self._requests[rq.req_id] = rq
        self._queue.put(rq)
        return rq

    def _cancel(self, rq: _Request) -> None:
        rq.cancelled = True
        with self._lock:
            if self._requests.pop(rq.req_id, None) is not None:
                if self._continuous:
                    # only the serve_stream feed consumes cancel ids; the
                    # wave path checks rq.cancelled directly (adding here
                    # would grow the set forever)
                    self._cancels.add(rq.req_id)
                llm_metrics.requests_counter().inc(
                    tags={**self._tags, "outcome": "cancelled"})

    def generate_stream(self, prompt: List[int],
                        max_new_tokens: Optional[int] = None):
        """Yields token ids as the engine samples them. Closing the
        consumer side (client disconnect, ObjectRefGenerator.close())
        cancels the request and frees its engine slot."""
        trace_ctx = _tracing.current_trace()
        t_submit = time.monotonic()
        t_prev_wall = time.time()
        first_token = trace_ctx is not None
        span_cap = (CONFIG.trace_max_stream_spans
                    if trace_ctx is not None else 0)
        rq = self._submit(prompt, max_new_tokens, None)
        finished = False
        produced = 0
        try:
            while True:
                try:
                    item = rq.out.get(timeout=2.0)
                except queue.Empty:
                    if self._shutdown.is_set() or not self._thread.is_alive():
                        raise RuntimeError(
                            "engine batcher stopped mid-request")
                    continue
                if item is _DONE:
                    finished = True
                    return
                if isinstance(item, _Abort):
                    finished = True
                    raise RuntimeError(f"request aborted: {item.reason}")
                if isinstance(item, BaseException):
                    finished = True
                    raise item
                if first_token:
                    # admission span of a traced request: submit ->
                    # first sampled token (queue wait + prefill — the
                    # TTFT the engine is responsible for)
                    first_token = False
                    now = time.time()
                    _tracing.record_span(
                        "engine.admission", trace_ctx,
                        now - (time.monotonic() - t_submit), now,
                        attrs={"req_id": rq.req_id,
                               "prompt_tokens": len(prompt)})
                    t_prev_wall = now
                elif produced < span_cap:
                    now = time.time()
                    _tracing.record_span(
                        "engine.decode_chunk", trace_ctx, t_prev_wall, now,
                        attrs={"req_id": rq.req_id, "index": produced})
                    t_prev_wall = now
                produced += 1
                yield item
        finally:
            if not finished:
                self._cancel(rq)

    def generate_stream_sse(self, prompt: List[int],
                            max_new_tokens: Optional[int] = None):
        """generate_stream with each token PRE-ENCODED as a complete SSE
        frame at the source (zero-copy streaming, ISSUE 6): the router
        and the HTTP proxy forward these bytes untouched, so a token is
        serialized exactly once on its way to the client."""
        for tok in self.generate_stream(prompt, max_new_tokens):
            yield b'data: {"token": %d}\n\n' % tok

    def generate(self, prompt: List[int],
                 max_new_tokens: Optional[int] = None,
                 temperature: Optional[float] = None,
                 eos_token_id: Optional[int] = None) -> List[int]:
        """Unary path (and the llm_deployment compatibility surface).
        Sampling overrides ride only on the dense-engine wave path; the
        continuous loop compiles one sampling config per replica."""
        override = None
        if temperature is not None or eos_token_id is not None:
            override = dataclasses.replace(
                self.default,
                temperature=(self.default.temperature if temperature is None
                             else temperature),
                eos_token_id=(self.default.eos_token_id if eos_token_id
                              is None else eos_token_id))
            if self._continuous:
                raise ValueError(
                    "per-request sampling overrides are not supported by "
                    "the continuous-batching engine (sampling params are "
                    "compile-time constants); configure them per replica "
                    "via default_config")
        rq = self._submit(prompt, max_new_tokens, override)
        out: List[int] = []
        while True:
            try:
                item = rq.out.get(timeout=2.0)
            except queue.Empty:
                # first requests can sit behind minutes of XLA compiles;
                # keep waiting as long as the batcher is alive
                if self._shutdown.is_set() or not self._thread.is_alive():
                    self._cancel(rq)
                    raise RuntimeError(
                        "engine batcher stopped mid-request") from None
                continue
            if item is _DONE:
                return out
            if isinstance(item, _Abort):
                raise RuntimeError(f"request aborted: {item.reason}")
            if isinstance(item, BaseException):
                raise item
            out.append(item)

    # -- control / observability ---------------------------------------------

    def get_stats(self) -> Dict[str, Any]:
        stats = {
            "queue_depth": self._backlog(),
            "outstanding_requests": len(self._requests),
            "finished_requests": self._n_finished,
            "continuous_batching": self._continuous,
            "max_queue_depth": self._max_queue_depth,
        }
        eng_stats = getattr(self.engine, "stats", None)
        if callable(eng_stats):
            stats["engine"] = eng_stats()
        else:
            stats["engine"] = {
                "max_batch": self.engine.max_batch,
                "active_slots": (self.engine.max_batch
                                 - len(self.engine.free_slots)),
            }
        return stats

    def get_autoscaling_metrics(self) -> Dict[str, float]:
        """Engine-reported backlog for the controller's autoscaler (see
        controller._autoscale): requests waiting for admission, which
        ongoing-request counts alone cannot see."""
        return {"queue_depth": self._backlog()}

    def llm_metrics_snapshot(self) -> List[Dict]:
        return llm_metrics.snapshot()

    def check_health(self) -> bool:
        if not self._thread.is_alive() and not self._shutdown.is_set():
            raise RuntimeError("llm batcher thread died")
        return True

    def shutdown(self) -> None:
        self._shutdown.set()

    # -- batcher -------------------------------------------------------------

    def _run(self) -> None:
        run = (self._run_continuous if self._continuous
               else self._run_waves)
        while not self._shutdown.is_set():
            try:
                run()
            except Exception as e:  # noqa: BLE001 — fail waiters, recover
                logger.exception("llm batcher loop failed; restarting")
                self._fail_outstanding(e)

    def _fail_outstanding(self, e: BaseException) -> None:
        with self._lock:
            requests, self._requests = self._requests, {}
        for rq in requests.values():
            rq.out.put(e)
        while True:
            try:
                rq = self._queue.get_nowait()
            except queue.Empty:
                return
            with self._lock:
                # a _submit racing the swap above lands its entry in the
                # NEW dict; failing its queue entry without removing it
                # would pin phantom backlog (and 429s) forever
                self._requests.pop(rq.req_id, None)
            rq.out.put(e)

    def _update_gauges(self) -> None:
        llm_metrics.queue_depth_gauge().set(
            self._backlog(), tags=self._tags)
        eng = self.engine
        llm_metrics.occupancy_gauge().set(
            (eng.max_batch - len(eng.free_slots)) / max(1, eng.max_batch),
            tags=self._tags)
        preempt = getattr(eng, "preemptions", 0)
        if preempt > self._seen_preemptions:
            llm_metrics.preemptions_counter().inc(
                preempt - self._seen_preemptions, tags=self._tags)
            self._seen_preemptions = preempt
        prefix = getattr(eng, "prefix_stats", None)
        if prefix:
            # engine counters are cumulative; export only the delta
            for name, (_d, key) in \
                    llm_metrics.PREFIX_CACHE_COUNTERS.items():
                cur = prefix.get(key, 0)
                seen = self._seen_prefix.get(key, 0)
                if cur > seen:
                    llm_metrics.prefix_cache_counter(name).inc(
                        cur - seen, tags=self._tags)
                    self._seen_prefix[key] = cur

    def _feed(self, block: bool):
        new: List[_Request] = []
        try:
            if block:
                new.append(self._queue.get(timeout=0.2))
            while True:
                new.append(self._queue.get_nowait())
        except queue.Empty:
            pass
        with self._lock:
            cancelled, self._cancels = self._cancels, set()
        self._update_gauges()
        return ([(rq.req_id, rq.prompt, rq.max_new)
                 for rq in new if not rq.cancelled],
                cancelled, self._shutdown.is_set())

    def _deliver(self, req_id: int, token: Optional[int],
                 done: bool) -> None:
        with self._lock:
            rq = self._requests.get(req_id)
        if token is None:  # engine aborted the request
            # pop the reason even when the consumer is already gone, or
            # abort-vs-cancel races grow engine.abort_reasons forever
            reason = "aborted"
            reasons = getattr(self.engine, "abort_reasons", None)
            if reasons is not None:
                reason = reasons.pop(req_id, reason)
            if rq is None or rq.cancelled:
                return
            rq.out.put(_Abort(reason))
            llm_metrics.requests_counter().inc(
                tags={**self._tags, "outcome": "error"})
            with self._lock:
                self._requests.pop(req_id, None)
            return
        if rq is None or rq.cancelled:
            return
        now = time.monotonic()
        if rq.first_at is None:
            rq.first_at = now
            llm_metrics.ttft_histogram().observe(
                now - rq.enqueued_at, tags=self._tags)
        rq.n_tokens += 1
        rq.last_at = now
        llm_metrics.tokens_counter().inc(tags=self._tags)
        rq.out.put(token)
        if done:
            if rq.n_tokens >= 2:
                llm_metrics.tpot_histogram().observe(
                    (rq.last_at - rq.first_at) / (rq.n_tokens - 1),
                    tags=self._tags)
            llm_metrics.requests_counter().inc(
                tags={**self._tags, "outcome": "ok"})
            rq.out.put(_DONE)
            with self._lock:
                self._requests.pop(req_id, None)
                self._n_finished += 1

    def _run_continuous(self) -> None:
        """One serve_stream service loop for the replica's lifetime."""
        for req_id, token, done in self.engine.serve_stream(
                self._feed, self.default):
            self._deliver(req_id, token, done)

    def _run_waves(self) -> None:
        """Dense-engine fallback: coalesce queued requests into
        generate_stream waves (concurrency within a wave)."""
        try:
            first = self._queue.get(timeout=0.2)
        except queue.Empty:
            return
        wave = [first]
        while len(wave) < self.engine.max_batch * 4:
            try:
                wave.append(self._queue.get_nowait())
            except queue.Empty:
                break
        self._update_gauges()
        # group by generation config: the engine streams one config per run
        groups: Dict[Any, List[_Request]] = {}
        for rq in wave:
            if rq.cancelled:
                continue
            gen = dataclasses.replace(rq.gen_override or self.default,
                                      max_new_tokens=rq.max_new)
            groups.setdefault(gen, []).append(rq)
        for gen, items in groups.items():
            try:
                for idx, token in self.engine.generate_stream(
                        [rq.prompt for rq in items], gen):
                    self._deliver(items[idx].req_id, token, done=False)
            except Exception as e:  # noqa: BLE001 — report to this wave
                for rq in items:
                    rq.out.put(e)
                    with self._lock:
                        self._requests.pop(rq.req_id, None)
                continue
            # stream exhausted: everything this wave produced is out
            for rq in items:
                with self._lock:
                    alive = self._requests.pop(rq.req_id, None)
                if alive is not None and not rq.cancelled:
                    if rq.n_tokens >= 2:
                        llm_metrics.tpot_histogram().observe(
                            (rq.last_at - rq.first_at) / (rq.n_tokens - 1),
                            tags=self._tags)
                    llm_metrics.requests_counter().inc(
                        tags={**self._tags, "outcome": "ok"})
                    rq.out.put(_DONE)
                    self._n_finished += 1
