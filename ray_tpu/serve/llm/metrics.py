"""First-class LLM serving metrics (ISSUE 2 tentpole part 4).

The serving stack is distributed — engine replicas and the router run in
worker processes, but `prometheus_text()` / the dashboard ring buffers /
`ray-tpu llm status` read the LOCAL registry. The flow is therefore:

  * replicas observe into their own process registry (TTFT/TPOT
    histograms, queue-depth / batch-occupancy gauges, token/preemption
    counters — everything under the ``ray_tpu_llm_`` prefix);
  * `collect_llm_metrics()` pulls each replica's cumulative snapshot
    (`llm_metrics_snapshot` RPC) and merges the DELTA since that
    replica's previous scrape into the calling process's registry
    (util/metrics.py merge_metrics_snapshot), so repeated collection
    never double-counts;
  * the dashboard's time-series sampler and the CLI call the same
    collector, so one code path feeds /metrics, the Metrics page, and
    the terminal.

LLM applications are discoverable cluster-wide: build_llm_app stamps the
engine deployment's name into the app's ingress flags (``llm_engine``),
so a fresh process (the CLI) can find every serving app from the
controller alone.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from ray_tpu.util import metrics as um

METRIC_PREFIX = "ray_tpu_llm"
TTFT_NAME = "ray_tpu_llm_ttft_seconds"
TPOT_NAME = "ray_tpu_llm_tpot_seconds"
QUEUE_DEPTH_NAME = "ray_tpu_llm_queue_depth"
OCCUPANCY_NAME = "ray_tpu_llm_batch_occupancy"
TOKENS_NAME = "ray_tpu_llm_tokens_generated_total"
PREEMPTIONS_NAME = "ray_tpu_llm_preemptions_total"
REQUESTS_NAME = "ray_tpu_llm_requests_total"
SHED_NAME = "ray_tpu_llm_requests_shed_total"
PREFIX_HITS_NAME = "ray_tpu_llm_prefix_cache_hits_total"
PREFIX_MISSES_NAME = "ray_tpu_llm_prefix_cache_misses_total"
PREFIX_HIT_TOKENS_NAME = "ray_tpu_llm_prefix_cache_hit_tokens_total"
PREFIX_EVICTIONS_NAME = "ray_tpu_llm_prefix_cache_evictions_total"
PREFIX_BYTES_SAVED_NAME = "ray_tpu_llm_prefix_cache_bytes_saved_total"

_TAG_KEYS = ("deployment", "replica")

# Serving latencies live well under the control-plane 30s ceiling: sub-ms
# TPOT on small models up to tens of seconds of TTFT under queueing.
SERVING_BOUNDARIES = [0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                      0.5, 1.0, 2.5, 5.0, 10.0, 30.0]


def ttft_histogram() -> um.Histogram:
    return um.get_or_create_histogram(
        TTFT_NAME, "time from request arrival to its first streamed token",
        boundaries=SERVING_BOUNDARIES, tag_keys=_TAG_KEYS)


def tpot_histogram() -> um.Histogram:
    return um.get_or_create_histogram(
        TPOT_NAME, "mean time per output token after the first",
        boundaries=SERVING_BOUNDARIES, tag_keys=_TAG_KEYS)


def _get_or_create(cls, name: str, description: str,
                   tag_keys=_TAG_KEYS):
    m = um.get_metric(name)
    if isinstance(m, cls):
        return m
    return cls(name, description, tag_keys=tag_keys)


def queue_depth_gauge() -> um.Gauge:
    return _get_or_create(um.Gauge, QUEUE_DEPTH_NAME,
                          "requests queued ahead of engine admission")


def occupancy_gauge() -> um.Gauge:
    return _get_or_create(um.Gauge, OCCUPANCY_NAME,
                          "fraction of engine batch slots in use")


def tokens_counter() -> um.Counter:
    return _get_or_create(um.Counter, TOKENS_NAME,
                          "tokens streamed to clients")


def preemptions_counter() -> um.Counter:
    return _get_or_create(um.Counter, PREEMPTIONS_NAME,
                          "engine recompute-preemptions")


def requests_counter() -> um.Counter:
    return _get_or_create(
        um.Counter, REQUESTS_NAME, "serving requests by outcome",
        tag_keys=_TAG_KEYS + ("outcome",))


def shed_counter() -> um.Counter:
    return _get_or_create(um.Counter, SHED_NAME,
                          "requests rejected with 429 by the router",
                          tag_keys=("deployment",))


# engine prefix-cache counter name -> (metric factory args, engine
# prefix_stats key); the replica diffs the engine's cumulative stats into
# these each gauge refresh (engine.py _update_gauges)
PREFIX_CACHE_COUNTERS = {
    PREFIX_HITS_NAME: ("requests admitted with a prefix-cache hit",
                       "hit_requests"),
    PREFIX_MISSES_NAME: ("requests admitted with no cached prefix",
                         "miss_requests"),
    PREFIX_HIT_TOKENS_NAME: ("prompt tokens whose prefill was skipped "
                             "(KV served from cached blocks)",
                             "hit_tokens"),
    PREFIX_EVICTIONS_NAME: ("cached KV blocks evicted (LRU) to serve "
                            "new allocations", "evictions"),
    PREFIX_BYTES_SAVED_NAME: ("KV bytes not recomputed thanks to "
                              "prefix-cache hits", "bytes_saved"),
}


def prefix_cache_counter(name: str) -> um.Counter:
    return _get_or_create(um.Counter, name, PREFIX_CACHE_COUNTERS[name][0])


def snapshot() -> List[Dict]:
    """Cumulative snapshot of this process's llm metrics (RPC payload)."""
    return um.snapshot_metrics(METRIC_PREFIX)


# -- cluster collection ------------------------------------------------------

_collector_lock = threading.Lock()
_prev_snapshots: Dict[str, List[Dict]] = {}  # source id -> last snapshot


def find_llm_apps(controller=None) -> Dict[str, Dict[str, str]]:
    """{app_name: {"engine": engine_deployment, "ingress": router}} for
    every deployed LLM serving app (identified by the ``llm_engine``
    ingress flag build_llm_app stamps)."""
    import ray_tpu
    from ray_tpu.serve import context as serve_ctx

    controller = controller or serve_ctx.get_controller()
    apps = ray_tpu.get(controller.list_applications.remote())
    out: Dict[str, Dict[str, str]] = {}
    for app_name, info in apps.items():
        engine = (info.get("ingress_flags") or {}).get("llm_engine")
        if engine:
            out[app_name] = {"engine": engine, "ingress": info["ingress"]}
    return out


def collect_llm_metrics(app_name: Optional[str] = None,
                        timeout_s: float = 10.0) -> int:
    """Pull per-replica metric snapshots from every LLM serving app (or
    just `app_name`) and merge the deltas into THIS process's registry.
    Returns the number of replicas scraped. After this,
    prometheus_text() carries the ray_tpu_llm_* series."""
    import ray_tpu
    from ray_tpu.serve import context as serve_ctx

    # This process is about to become an AGGREGATOR of other processes'
    # serving series. Its own health-plane pusher must stop shipping the
    # merged ray_tpu_llm_* families or the GCS store would count every
    # replica's series twice (once from the replica that owns it, once
    # re-badged under this process).
    from ray_tpu.health import push as _health_push

    _health_push.exclude_prefix(METRIC_PREFIX)
    controller = serve_ctx.get_controller()
    apps = find_llm_apps(controller)
    if app_name is not None:
        apps = {k: v for k, v in apps.items() if k == app_name}
    probes = []  # (source_id, ref)
    for app, names in apps.items():
        for dep in (names["engine"], names["ingress"]):
            # listen_for_change with a mismatched version returns the
            # replica set immediately WITH stable replica ids — the delta
            # watermarks must be keyed by replica identity, not list
            # position, or any replica churn re-merges a survivor's full
            # cumulative history as a fresh delta (double-counting)
            snap = ray_tpu.get(controller.listen_for_change.remote(
                f"{app}#{dep}", -1, timeout=0))
            for rid, h in snap["replicas"]:
                probes.append((
                    rid,
                    h.handle_request.remote("llm_metrics_snapshot", (), {})))
    if apps:
        # proxy shards host per-shard embedded LLM routers whose shed
        # counters live in the shard process registries
        try:
            shards = ray_tpu.get(
                controller.get_http_proxy_handles.remote(), timeout=5)
        except Exception:  # noqa: BLE001 — older controller / no proxies
            shards = {}
        for idx, shard in shards.items():
            try:
                probes.append((f"proxy_shard:{idx}",
                               shard.llm_metrics_snapshot.remote()))
            except Exception:  # noqa: BLE001 — shard mid-restart
                pass
    # ONE bounded wait for the whole fan-out, then cheap gets: harvesting
    # serially at timeout_s each would stall the caller (the dashboard's
    # sampler tick) k*timeout_s when k replicas are mid-restart — same
    # pattern as controller._autoscale.
    done_set = set()
    if probes:
        refs = [ref for _, ref in probes]
        try:
            done, _ = ray_tpu.wait(refs, num_returns=len(refs),
                                   timeout=timeout_s)
            done_set = set(done)
        except Exception:  # noqa: BLE001
            pass
    scraped = 0
    for source, ref in probes:
        if ref not in done_set:
            continue
        try:
            snap = ray_tpu.get(ref, timeout=1.0)
        except Exception:  # noqa: BLE001 — replica mid-restart
            continue
        with _collector_lock:
            um.merge_metrics_snapshot(snap, _prev_snapshots.get(source))
            _prev_snapshots[source] = snap
        scraped += 1
    if app_name is None:
        # Replica ids are unique per incarnation: watermarks of replicas
        # no longer in any set can never be consulted again — drop them
        # (only on unfiltered sweeps: a filtered call must not forget
        # other apps' watermarks). Dead replicas' GAUGE samples are
        # pruned too: counters/histograms aggregate across lifetimes,
        # but a queue-depth reading for a replica that no longer exists
        # is stale forever.
        live = {source for source, _ in probes}
        with _collector_lock:
            for k in list(_prev_snapshots):
                if k not in live:
                    del _prev_snapshots[k]
        for name in (QUEUE_DEPTH_NAME, OCCUPANCY_NAME):
            g = um.get_metric(name)
            if isinstance(g, um.Gauge):
                with g._lock:
                    g._values = {
                        k: v for k, v in g._values.items()
                        if dict(k).get("replica") in live}
    return scraped


def maybe_collect_local(timeout_s: float = 2.0) -> int:
    """Best-effort collect for background samplers (the dashboard's
    time-series loop): no-op unless serve is already running and
    reachable from this process. Never raises."""
    try:
        from ray_tpu.serve import context as serve_ctx

        serve_ctx.get_controller()  # raises if serve isn't running
        return collect_llm_metrics(timeout_s=timeout_s)
    except Exception:  # noqa: BLE001 — serve down / ray not initialized
        return 0


def serving_summary() -> Dict[str, Any]:
    """Human-facing rollup of the locally-merged llm series (the CLI's
    data source; call collect_llm_metrics first)."""
    out: Dict[str, Any] = {}
    ttft = um.get_metric(TTFT_NAME)
    tpot = um.get_metric(TPOT_NAME)
    if isinstance(ttft, um.Histogram):
        out["ttft_s"] = ttft.quantiles_by("deployment")
    if isinstance(tpot, um.Histogram):
        out["tpot_s"] = tpot.quantiles_by("deployment")
    for key, name in (("queue_depth", QUEUE_DEPTH_NAME),
                      ("batch_occupancy", OCCUPANCY_NAME)):
        g = um.get_metric(name)
        if g is not None:
            out[key] = {"/".join(v for _, v in tags.items()): val
                        for _, tags, val in g._samples()}
    for key, name in (("tokens_generated", TOKENS_NAME),
                      ("preemptions", PREEMPTIONS_NAME),
                      ("requests_shed", SHED_NAME)):
        c = um.get_metric(name)
        if c is not None:
            out[key] = sum(v for _, _, v in c._samples())
    pc = {}
    for name, (_desc, key) in PREFIX_CACHE_COUNTERS.items():
        c = um.get_metric(name)
        if c is not None:
            pc[key] = sum(v for _, _, v in c._samples())
    if pc:
        out["prefix_cache"] = pc
    req = um.get_metric(REQUESTS_NAME)
    if req is not None:
        by_outcome: Dict[str, float] = {}
        for _, tags, v in req._samples():
            o = tags.get("outcome", "")
            by_outcome[o] = by_outcome.get(o, 0) + v
        out["requests"] = by_outcome
    return out
