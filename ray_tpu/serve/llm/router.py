"""Token-streaming LLM router (ISSUE 2 tentpole part 2).

The generic Serve router (serve/_private/router.py) balances by
request count, which is the wrong unit for LLM serving: a 4k-token
prompt with a 1k-token budget occupies an engine for orders of magnitude
longer than a chat ping. This router is the serving-aware ingress:

  * OUTSTANDING-TOKEN BALANCING — each assignment charges the replica
    with the request's expected token footprint (prompt + max_new);
    every streamed token pays one unit back. choose() picks the
    lighter of two random replicas by that score plus the
    controller-piggybacked ongoing/queue counts (other routers' load).
  * SESSION AFFINITY — requests carrying a session_id stick to their
    replica (KV reuse locality for follow-up turns) while it stays
    healthy; affinity falls back to pow-2 when the replica goes away.
  * LOAD SHEDDING — when the aggregate outstanding-request depth
    crosses `shed_queue_depth`, new requests fail fast with
    LLMOverloadedError (HTTP 429) instead of joining a queue whose
    latency has already collapsed.
  * The replica set arrives by controller long-poll push, like the
    generic router — scale-downs reach this router in one RPC.

Streaming is end-to-end: the router calls the engine replica's
`generate_stream` as a streaming-generator task and re-yields tokens as
they are reported, so the proxy's chunked/SSE path ships each token the
moment it is sampled. Closing the client connection closes the router
generator, which closes the engine-side generator, which frees the
engine slot.
"""

from __future__ import annotations

import json
import logging
import random
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import ray_tpu
from ray_tpu import exceptions as exc
from ray_tpu._private import tracing as _tracing
from ray_tpu._private.rpc import ConnectionLost
from ray_tpu.serve.llm import metrics as llm_metrics
from ray_tpu.serve.llm.engine import (
    LLMOverloadedError,
    LLMReplicaUnavailableError,
)

# Transport/liveness failures that mean "the replica (or its node) is
# gone", as opposed to an application error raised by the engine itself.
# Only these trigger failover/typed-error handling; everything else
# propagates untouched.
_REPLICA_FAILURES = (
    ConnectionLost,
    exc.RayActorError,          # ActorDiedError / ActorUnavailableError
    exc.WorkerCrashedError,
    exc.RaySystemError,
    exc.OwnerDiedError,
    exc.NodeDiedError,
    exc.ObjectLostError,
)

# Pre-first-token retries against OTHER replicas before giving up.
_MAX_FAILOVERS = 2

# Shorter than the generic router's 30s long-poll: the piggybacked load
# metrics feed the SHED decision here, and listen_for_change only returns
# on a replica-set change or timeout — a 30s bound would keep rejecting
# with 429 long after a burst drained. 3s caps load staleness at roughly
# the controller's own 2s metric refresh.
_LONG_POLL_TIMEOUT_S = 3.0

logger = logging.getLogger(__name__)


class BadRequestError(Exception):
    status_code = 400


class LLMRouter:
    __serve_sse__ = True  # proxy streams __call__ as text/event-stream

    def __init__(self, engine, *, shed_queue_depth: int = 64,
                 session_ttl_s: float = 600.0,
                 default_max_new_tokens: int = 64):
        """`engine`: the engine deployment's handle (injected by
        serve.run graph composition). The router resolves replicas
        itself — per-replica placement is the whole point.
        `default_max_new_tokens` mirrors the engine default_config so
        requests without an explicit budget are charged their REAL
        expected footprint."""
        self._deployment = engine.deployment_name
        self._app = engine.app_name
        self._default_max_new = default_max_new_tokens
        self._key = (f"{self._app}#{self._deployment}"
                     if self._app else self._deployment)
        self._shed_queue_depth = shed_queue_depth
        self._session_ttl_s = session_ttl_s
        from ray_tpu.serve import context as serve_ctx

        try:
            ctx = serve_ctx.get_replica_context()
            self._tags = {"deployment": ctx.deployment}
        except RuntimeError:
            self._tags = {"deployment": "llm_router"}
        self._controller = serve_ctx.get_controller()
        self._lock = threading.Lock()
        self._replicas: List[Tuple[str, Any]] = []
        self._base_load: Dict[str, int] = {}     # controller-piggybacked
        self._out_tokens: Dict[str, int] = {}    # this router's charges
        self._out_requests: Dict[str, int] = {}
        self._assigned_total: Dict[str, int] = {}
        self._sessions: Dict[str, Tuple[str, float]] = {}
        self._shed_total = 0
        self._rng = random.Random()
        self._version = -1
        self._have_replicas = threading.Event()
        self._stopped = threading.Event()
        threading.Thread(target=self._long_poll_loop, daemon=True,
                         name=f"llm-router-poll-{self._key}").start()

    # -- replica set ---------------------------------------------------------

    def _long_poll_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                update = ray_tpu.get(
                    self._controller.listen_for_change.remote(
                        self._key, self._version,
                        timeout=_LONG_POLL_TIMEOUT_S),
                    timeout=_LONG_POLL_TIMEOUT_S + 10.0)
            except Exception:  # noqa: BLE001 — controller restarting
                if self._stopped.wait(0.5):
                    return
                continue
            self._version = update["version"]
            self._apply_update(update)

    def _apply_update(self, update: dict) -> None:
        with self._lock:
            self._replicas = list(update["replicas"])
            live = {rid for rid, _ in self._replicas}
            metrics = update.get("metrics") or {}
            self._base_load = {rid: metrics.get(rid, 0) for rid in live}
            self._out_tokens = {r: self._out_tokens.get(r, 0)
                                for r in live}
            self._out_requests = {r: self._out_requests.get(r, 0)
                                  for r in live}
            self._sessions = {
                sid: (rid, exp)
                for sid, (rid, exp) in self._sessions.items()
                if rid in live}
            # Gate transitions under the SAME lock that _evict_replica
            # holds, and on the post-merge self._replicas: set outside
            # the lock raced the eviction of the last replica — the
            # stale update re-armed the event over an empty replica set,
            # and a FAILOVER waiter woke into an immediate typed 503
            # instead of waiting out the controller's replacement push.
            if self._replicas:
                self._have_replicas.set()
            else:
                self._have_replicas.clear()

    def _score(self, rid: str) -> float:
        return self._out_tokens.get(rid, 0) + 64 * self._base_load.get(rid, 0)

    def _choose(self, session_id: Optional[str], cost: int,
                excluded: frozenset = frozenset()) -> Tuple[str, Any]:
        if not self._have_replicas.is_set():
            # On a FAILOVER retry (the caller just watched a replica die)
            # an empty replica set is replica death, not slow startup:
            # give the controller one short beat to push a replacement,
            # then surface the typed 503 — never the 30s cold-start wait
            # plus a generic RuntimeError the retry path would otherwise
            # hit when the LAST replica died pre-first-token.
            if excluded:
                if not self._have_replicas.wait(timeout=5.0):
                    raise LLMReplicaUnavailableError(
                        f"all replicas of {self._deployment!r} are gone "
                        f"({len(excluded)} failed this request); retry "
                        "once replacements come up")
            elif not self._have_replicas.wait(timeout=30.0):
                raise RuntimeError(
                    f"no engine replicas for {self._deployment!r} after 30s")
        now = time.monotonic()
        with self._lock:
            # Shed BEFORE assignment, on the router's OWN outstanding
            # count only: this router is the ingress, so its accounting
            # covers every request it routed, exactly and freshly. The
            # controller-piggybacked base_load is deliberately excluded —
            # it lags by the long-poll + metric-refresh cadence, and a
            # shed decision on seconds-stale "ongoing" data returns 429s
            # on an idle service right after a burst drains (base_load
            # still steers replica CHOICE below, where staleness only
            # costs balance, not availability). With multiple router
            # replicas the bound is per-router.
            agg = sum(self._out_requests.values())
            if agg >= self._shed_queue_depth:
                self._shed_total += 1
                llm_metrics.shed_counter().inc(tags=self._tags)
                ambient = _tracing.current_trace()
                if ambient is not None:
                    # a shed is a tail-keep trigger: the 429 the client
                    # sees must be traceable at any sample rate
                    _tracing.force_trace(ambient.trace_id,
                                         "llm_shed:router")
                raise LLMOverloadedError(
                    f"serving queue depth {agg} >= bound "
                    f"{self._shed_queue_depth}; retry later")
            replicas = [r for r in self._replicas if r[0] not in excluded]
            if not replicas:
                raise LLMReplicaUnavailableError(
                    f"all {len(self._replicas)} replica(s) of "
                    f"{self._deployment!r} failed this request")
            by_id = dict(replicas)
            choice = None
            if session_id is not None:
                hit = self._sessions.get(session_id)
                # expiry checked on LOOKUP (the bulk prune below is only
                # an amortized size bound); each use slides the TTL
                if hit is not None and hit[0] in by_id and hit[1] > now:
                    choice = (hit[0], by_id[hit[0]])
            if choice is None:
                if len(replicas) == 1:
                    choice = replicas[0]
                else:
                    a, b = self._rng.sample(replicas, 2)
                    choice = (a if self._score(a[0]) <= self._score(b[0])
                              else b)
            rid = choice[0]
            if session_id is not None:
                self._sessions[session_id] = (rid, now + self._session_ttl_s)
                if len(self._sessions) > 4096:  # TTL prune, amortized
                    self._sessions = {
                        s: v for s, v in self._sessions.items()
                        if v[1] > now}
            self._out_tokens[rid] = self._out_tokens.get(rid, 0) + cost
            self._out_requests[rid] = self._out_requests.get(rid, 0) + 1
            self._assigned_total[rid] = self._assigned_total.get(rid, 0) + 1
            return choice

    def _release(self, rid: str, remaining_tokens: int) -> None:
        with self._lock:
            if rid in self._out_tokens:
                self._out_tokens[rid] = max(
                    0, self._out_tokens[rid] - max(0, remaining_tokens))
            if rid in self._out_requests:
                self._out_requests[rid] = max(
                    0, self._out_requests[rid] - 1)

    def _pay_token(self, rid: str) -> None:
        with self._lock:
            if rid in self._out_tokens and self._out_tokens[rid] > 0:
                self._out_tokens[rid] -= 1

    def _evict_replica(self, rid: str) -> None:
        """A stream to `rid` died: drop it from the local view NOW so new
        assignments (and session affinity) stop routing to it, instead of
        waiting a long-poll round for the controller to notice. If the
        failure was transient the next controller push re-adds it.

        The outstanding-token/request counters are deliberately KEPT:
        other streams to the same replica may still be in flight, and
        their _pay_token/_release on exit must settle against their own
        charges — popping here would let a survivor drain charges that
        belong to requests assigned after a re-add (under-counting the
        balance score and the 429 shed bound). A replica that never
        returns has its counters pruned by the long-poll update once the
        controller drops it from the live set."""
        with self._lock:
            self._replicas = [r for r in self._replicas if r[0] != rid]
            self._base_load.pop(rid, None)
            self._sessions = {sid: (r, exp)
                              for sid, (r, exp) in self._sessions.items()
                              if r != rid}
            if not self._replicas:
                self._have_replicas.clear()

    # -- request path --------------------------------------------------------

    @staticmethod
    def _parse(request: Any) -> Dict[str, Any]:
        if isinstance(request, (bytes, bytearray)):
            try:
                request = json.loads(request)
            except ValueError:
                raise BadRequestError("body must be JSON") from None
        if isinstance(request, list):
            request = {"prompt": request}
        if not isinstance(request, dict):
            raise BadRequestError(
                "expected {'prompt': [token ids], 'max_new_tokens': int?, "
                "'session_id': str?}")
        prompt = request.get("prompt")
        if (not isinstance(prompt, list) or not prompt
                or not all(isinstance(t, int) for t in prompt)):
            raise BadRequestError(
                "'prompt' must be a non-empty list of token ids")
        max_new = request.get("max_new_tokens")
        if max_new is not None:
            max_new = int(max_new)
            if max_new <= 0:
                raise BadRequestError("'max_new_tokens' must be positive")
        sid = request.get("session_id")
        return {"prompt": prompt, "max_new_tokens": max_new,
                "session_id": str(sid) if sid is not None else None}

    def _token_stream(self, rq: Dict[str, Any], sse: bool = False):
        """Assign + stream: yields token ids (or, with sse=True,
        replica-PRE-ENCODED SSE byte frames forwarded verbatim — the
        zero-copy path); releases charges on exit.

        Replica-death failover: a stream whose replica dies BEFORE the
        first token retries transparently on a different replica (the
        client observes nothing); one that dies AFTER the first token
        raises the typed LLMReplicaUnavailableError (503) — replaying on
        another replica would re-emit tokens the client already has.
        Either way the dead replica's outstanding-token accounting is
        released and it is evicted from the local replica view."""
        cost = len(rq["prompt"]) + (rq["max_new_tokens"]
                                    or self._default_max_new)
        method = "generate_stream_sse" if sse else "generate_stream"
        failed: set = set()
        for failover in range(_MAX_FAILOVERS + 1):
            trace_ctx = _tracing.current_trace()
            t_pick = time.time() if trace_ctx is not None else 0.0
            rid, handle = self._choose(rq["session_id"], cost,
                                       excluded=frozenset(failed))
            if trace_ctx is not None:
                _tracing.record_span(
                    "router.pick", trace_ctx, t_pick, time.time(),
                    attrs={"deployment": self._deployment, "replica": rid,
                           "failover": failover, "cost": cost})
            produced = 0
            gen = None
            try:
                try:
                    # .remote() itself raises ActorDiedError when the
                    # owner already learned of the death — same failover
                    # treatment as a mid-stream transport failure
                    gen = handle.handle_request_streaming.options(
                        num_returns="streaming").remote(
                            method, (rq["prompt"],),
                            {"max_new_tokens": rq["max_new_tokens"]})
                    for ref in gen:
                        token = ray_tpu.get(ref)
                        produced += 1
                        if produced <= cost:
                            # a request never pays back more than it was
                            # charged: the replica counter is shared, and
                            # over-paying would erase OTHER requests'
                            # outstanding charges
                            self._pay_token(rid)
                        yield token
                    return
                finally:
                    # Runs on success, failure, AND consumer abandonment
                    # (GeneratorExit): the outstanding charge is always
                    # released, dead replica or not.
                    if gen is not None:
                        try:
                            gen.close()  # no-op when exhausted; cancels
                        except Exception:  # noqa: BLE001 — teardown
                            pass
                    self._release(rid, cost - produced)
            except _REPLICA_FAILURES as e:
                failed.add(rid)
                self._evict_replica(rid)
                logger.warning(
                    "replica %s died serving a stream (%s tokens in, "
                    "attempt %d): %s", rid, produced, failover + 1, e)
                if produced > 0:
                    raise LLMReplicaUnavailableError(
                        f"engine replica {rid} became unavailable after "
                        f"{produced} streamed token(s); retry the request"
                    ) from e
                if failover >= _MAX_FAILOVERS:
                    raise LLMReplicaUnavailableError(
                        f"engine replica {rid} (and {failover} failover "
                        f"replica(s) before it) became unavailable before "
                        "the first token") from e
                # pre-first-token: silently fail over to another replica

    def stream_tokens(self, request: Any):
        """Raw token stream (handle callers / tests): yields ints."""
        yield from self._token_stream(self._parse(request))

    def __call__(self, request: Any = None):
        """HTTP ingress: streams Server-Sent Events, one per token, then
        a final usage event and `[DONE]` — each flushed through the
        proxy's chunked path as it is produced. Frames arrive from the
        engine replica PRE-ENCODED (generate_stream_sse) and pass through
        untouched — no per-token re-encoding on the router or proxy."""
        rq = self._parse(request)
        n = 0
        t0 = time.monotonic()
        for frame in self._token_stream(rq, sse=True):
            n += 1
            yield frame
        dt = time.monotonic() - t0
        usage = {"completion_tokens": n,
                 "prompt_tokens": len(rq["prompt"]),
                 "duration_s": round(dt, 4)}
        yield ("data: " + json.dumps({"usage": usage}) + "\n\n").encode()
        yield b"data: [DONE]\n\n"

    def generate(self, request: Any) -> Dict[str, Any]:
        """Unary path: full completion in one response."""
        rq = self._parse(request)
        tokens = list(self._token_stream(rq))
        return {"tokens": tokens, "n": len(tokens)}

    # -- control / observability ---------------------------------------------

    def get_router_stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "replicas": [rid for rid, _ in self._replicas],
                "assigned_total": dict(self._assigned_total),
                "outstanding_tokens": dict(self._out_tokens),
                "outstanding_requests": dict(self._out_requests),
                "base_load": dict(self._base_load),
                "sessions": len(self._sessions),
                "shed_total": self._shed_total,
                "shed_queue_depth": self._shed_queue_depth,
            }

    def llm_metrics_snapshot(self) -> List[Dict]:
        return llm_metrics.snapshot()

    def check_health(self) -> bool:
        return True

    def shutdown(self) -> None:
        self._stopped.set()
