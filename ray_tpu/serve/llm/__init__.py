"""serve.llm: distributed LLM serving on TPU (ISSUE 2 tentpole).

Composes the pieces the repo already had in isolation into an inference
service: continuous-batching engine replicas
(inference/paged_engine.py serve_stream) behind a token-streaming,
outstanding-token-balancing router with session affinity and 429 load
shedding, reached over streaming-generator actor calls
(num_returns="streaming") and the Serve proxy's chunked/SSE path, with
TTFT/TPOT/queue-depth/occupancy metrics flowing to prometheus_text(),
the dashboard, and `ray-tpu llm status`.

    from ray_tpu import serve
    from ray_tpu.serve.llm import build_llm_app

    app = build_llm_app(lambda: PagedInferenceEngine(params, cfg),
                        num_replicas=2, shed_queue_depth=32)
    handle = serve.run(app, name="llm", http_port=8000)
    for tok in handle.options(method_name="stream_tokens",
                              stream=True).remote({"prompt": [1, 2, 3]}):
        ...                       # or: curl -N http://.../llm  (SSE)
"""

from __future__ import annotations

from typing import Optional

from ray_tpu.serve.llm.engine import (  # noqa: F401
    LLMEngineReplica,
    LLMOverloadedError,
    LLMReplicaUnavailableError,
)
from ray_tpu.serve.llm.metrics import (  # noqa: F401
    collect_llm_metrics,
    find_llm_apps,
    serving_summary,
)
from ray_tpu.serve.llm.router import BadRequestError, LLMRouter  # noqa: F401


def build_llm_app(build_engine, *, name: str = "llm",
                  num_replicas: int = 2,
                  default_config: Optional[dict] = None,
                  max_queue_depth: int = 64,
                  shed_queue_depth: int = 64,
                  session_ttl_s: float = 600.0,
                  max_ongoing_requests: int = 32,
                  engine_actor_options: Optional[dict] = None,
                  autoscaling_config: Optional[dict] = None):
    """-> a bindable application: LLMRouter ingress over `num_replicas`
    LLMEngineReplica deployments.

    build_engine() -> PagedInferenceEngine (continuous batching) or
    InferenceEngine (wave batching); constructed inside each replica so
    params land on the replica's device. `shed_queue_depth` is the
    aggregate outstanding-request bound past which the router sheds with
    429; `max_queue_depth` is the per-replica admission backstop."""
    from ray_tpu.serve.api import Deployment

    engine_name = f"{name}_engine"
    engine_d = Deployment(
        LLMEngineReplica, name=engine_name, num_replicas=num_replicas,
        ray_actor_options=engine_actor_options,
        max_ongoing_requests=max_ongoing_requests,
        autoscaling_config=autoscaling_config)
    engine_app = engine_d.bind(build_engine, default_config,
                               max_queue_depth)
    # Stamp the engine deployment's name onto the ingress class: it rides
    # the app's ingress_flags to the controller, making LLM apps (and
    # their metric sources) discoverable from any process (CLI,
    # dashboard) — see metrics.find_llm_apps.
    default_max_new = (default_config or {}).get("max_new_tokens", 64)
    router_cls = type("LLMRouter", (LLMRouter,),
                      {"__serve_llm_engine__": engine_name,
                       # proxy shards rebuild this router config locally
                       # (per-shard embedded ingress; see _private/proxy)
                       "__serve_llm_config__": {
                           "shed_queue_depth": shed_queue_depth,
                           "session_ttl_s": session_ttl_s,
                           "default_max_new_tokens": default_max_new,
                       },
                       "__module__": LLMRouter.__module__})
    router_d = Deployment(router_cls, name=name, num_replicas=1,
                          max_ongoing_requests=128)
    return router_d.bind(engine_app, shed_queue_depth=shed_queue_depth,
                         session_ttl_s=session_ttl_s,
                         default_max_new_tokens=default_max_new)


def llm_deployment(build_engine, *, name: str = "llm",
                   default_config: Optional[dict] = None,
                   num_replicas: int = 1,
                   ray_actor_options: Optional[dict] = None):
    """Single-deployment engine app (no router): the original serve.llm
    surface, kept for handle-first users.

        app = llm_deployment(lambda: InferenceEngine(params, cfg)).bind()
        handle = serve.run(app)
        tokens = handle.generate.remote([1,2,3]).result()
    """
    from ray_tpu.serve.api import Deployment

    d = Deployment(LLMEngineReplica, name=name, num_replicas=num_replicas,
                   ray_actor_options=ray_actor_options,
                   max_ongoing_requests=64)
    return d.bind(build_engine, default_config)


__all__ = [
    "BadRequestError",
    "LLMEngineReplica",
    "LLMOverloadedError",
    "LLMReplicaUnavailableError",
    "LLMRouter",
    "build_llm_app",
    "collect_llm_metrics",
    "find_llm_apps",
    "llm_deployment",
    "serving_summary",
]
