"""LLM serving on TPU: a Serve deployment wrapping the inference engine.

Reference gap this fills: ray serve ships no TPU LLM path (LLM serving is
delegated to external engines); SURVEY §7 names "async serving on TPU:
batching + compiled-shape management (bucketing) in Serve replicas" a
required hard part. `LLMDeployment` runs a continuous-batching
InferenceEngine inside a replica: requests from the router are admitted
into engine slots as they free up, so concurrent requests share each
decode step instead of queueing serially.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Dict, List, Optional

from ray_tpu.inference import GenerationConfig, InferenceEngine


class _LLMServer:
    """One replica: a background generation thread drains a request queue
    through the engine's continuous-batching stream."""

    def __init__(self, build_engine, default_config: Optional[dict] = None):
        """build_engine() -> InferenceEngine (constructed in the replica so
        params land on the replica's device)."""
        self.engine: InferenceEngine = build_engine()
        self.default = GenerationConfig(**(default_config or {}))
        self._requests: "queue.Queue" = queue.Queue()
        self._thread = threading.Thread(
            target=self._loop, name="llm-batcher", daemon=True)
        self._thread.start()

    # -- request path -------------------------------------------------------

    def generate(self, prompt_tokens: List[int],
                 max_new_tokens: Optional[int] = None,
                 temperature: Optional[float] = None,
                 eos_token_id: Optional[int] = None) -> List[int]:
        gen = GenerationConfig(
            max_new_tokens=(self.default.max_new_tokens
                            if max_new_tokens is None else max_new_tokens),
            temperature=(self.default.temperature
                         if temperature is None else temperature),
            top_k=self.default.top_k,
            top_p=self.default.top_p,
            eos_token_id=(self.default.eos_token_id
                          if eos_token_id is None else eos_token_id),
        )
        done = threading.Event()
        result: Dict[str, Any] = {}
        self._requests.put((list(prompt_tokens), gen, done, result))
        done.wait()
        if "error" in result:
            raise result["error"]
        return result["tokens"]

    # -- batcher loop -------------------------------------------------------

    def _loop(self):
        while True:
            # Block for one request, then opportunistically grab more so a
            # burst shares the same continuous-batching run.
            batch = [self._requests.get()]
            while len(batch) < self.engine.max_batch * 4:
                try:
                    batch.append(self._requests.get_nowait())
                except queue.Empty:
                    break
            # Engine streams per generation config; group identical configs.
            by_cfg: Dict[Any, List] = {}
            for item in batch:
                by_cfg.setdefault(item[1], []).append(item)
            for gen, items in by_cfg.items():
                prompts = [it[0] for it in items]
                try:
                    outs = self.engine.generate(prompts, gen)
                except Exception as e:  # noqa: BLE001 — report to waiters
                    for _, _, done, result in items:
                        result["error"] = e
                        done.set()
                    continue
                for (_, _, done, result), toks in zip(items, outs):
                    result["tokens"] = toks
                    done.set()


def llm_deployment(build_engine, *, name: str = "llm",
                   default_config: Optional[dict] = None,
                   num_replicas: int = 1,
                   ray_actor_options: Optional[dict] = None):
    """-> a bindable Serve deployment hosting the engine.

        app = llm_deployment(lambda: InferenceEngine(params, cfg)).bind()
        handle = serve.run(app)
        tokens = handle.generate.remote([1,2,3]).result()
    """
    from ray_tpu.serve.api import Deployment

    d = Deployment(_LLMServer, name=name, num_replicas=num_replicas,
                   ray_actor_options=ray_actor_options,
                   max_ongoing_requests=64)
    return d.bind(build_engine, default_config)
