"""Serve data-plane microbenchmarks (VERDICT r1 #10, ISSUE 6 gate).

Measures what the reference's serve release benchmarks measure
(reference: python/ray/serve/_private/benchmarks/): end-to-end HTTP RPS +
latency percentiles through the proxy, handle-call RPS, and the
power-of-two router's queue-probe overhead vs a raw actor call.

ISSUE 6 adds the numbers the serving gate is judged on:

  * SUSTAINED mode — the max offered rps the HTTP data plane HOLDS at a
    target p99 (binary search over open-loop offered load, with a
    schedule-lag check so queueing collapse fails a load level even when
    the measured latencies look fine) — peak rps from closed-loop
    clients hides exactly that collapse.
  * PREFIX TTFT — client-observed TTFT on a shared-system-prompt
    serve.llm workload, prefix-cache hit vs cold, plus the engine's
    hit/evict counters.

Run: python -m ray_tpu.serve.benchmarks             # all of the above
     python -m ray_tpu.serve.benchmarks classic     # the r01 trio only
     python -m ray_tpu.serve.benchmarks sustained   # sustained only
     python -m ray_tpu.serve.benchmarks prefix      # prefix TTFT only
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, Optional


def _percentiles(samples_ms):
    xs = sorted(samples_ms)

    def pct(p):
        return round(xs[min(len(xs) - 1, int(p / 100 * len(xs)))], 2)

    return {"p50_ms": pct(50), "p90_ms": pct(90), "p99_ms": pct(99)}


def run_serve_benchmarks(n_requests: int = 200,
                         http_port: int = 0) -> Dict[str, dict]:
    import urllib.request

    import ray_tpu
    from ray_tpu import serve

    out: Dict[str, dict] = {}
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    http_port = http_port or 18431

    @serve.deployment
    def echo(body=None):
        return "ok"

    serve.run(echo.bind(), name="bench", http_port=http_port)
    handle = serve.get_deployment_handle("echo", "bench")

    # warm the replica + route
    assert handle.remote(None).result(timeout_s=30) == "ok"
    url = f"http://127.0.0.1:{http_port}/bench"
    with urllib.request.urlopen(url, timeout=10) as r:
        r.read()

    # -- handle path (router + replica actor call) --------------------------
    lat = []
    t0 = time.perf_counter()
    for _ in range(n_requests):
        s = time.perf_counter()
        handle.remote(None).result(timeout_s=30)
        lat.append((time.perf_counter() - s) * 1e3)
    dt = time.perf_counter() - t0
    out["serve_handle"] = {"rps": round(n_requests / dt, 1),
                           **_percentiles(lat)}

    # -- HTTP proxy path ----------------------------------------------------
    # persistent connections, like any real serving client: a fresh TCP
    # connect per request benchmarks the kernel's handshake, not the
    # proxy. Latency percentiles from one serial keep-alive connection;
    # throughput from 4 concurrent keep-alive clients.
    import http.client
    import threading as _threading

    host_port = f"127.0.0.1:{http_port}"
    conn = http.client.HTTPConnection(host_port, timeout=30)
    lat = []
    for _ in range(n_requests):
        s = time.perf_counter()
        conn.request("GET", "/bench")
        conn.getresponse().read()
        lat.append((time.perf_counter() - s) * 1e3)
    conn.close()

    counts = [0] * 4
    stop_at = time.perf_counter() + 3.0

    client_errors: list = []

    def _client(i: int):
        try:
            c = http.client.HTTPConnection(host_port, timeout=30)
            while time.perf_counter() < stop_at:
                c.request("GET", "/bench")
                c.getresponse().read()
                counts[i] += 1
            c.close()
        except Exception as e:  # noqa: BLE001 — surface after join
            client_errors.append(e)

    threads = [_threading.Thread(target=_client, args=(i,))
               for i in range(len(counts))]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0
    if client_errors:
        # a died client silently deflates rps; fail the run instead
        raise client_errors[0]
    out["serve_http"] = {"rps": round(sum(counts) / dt, 1),
                         "concurrency": len(counts),
                         **_percentiles(lat)}

    # -- router probe overhead ----------------------------------------------
    # the pow-2 router probes replica queue lengths before assignment
    # (reference: pow_2_scheduler.py:49); quantify it against a raw actor
    # round trip with no routing at all
    @ray_tpu.remote
    class Raw:
        def ping(self):
            return "ok"

    raw = Raw.remote()
    ray_tpu.get(raw.ping.remote())
    t0 = time.perf_counter()
    for _ in range(n_requests):
        ray_tpu.get(raw.ping.remote())
    raw_ms = (time.perf_counter() - t0) / n_requests * 1e3
    handle_ms = out["serve_handle"]["p50_ms"]
    out["router_probe_overhead"] = {
        "raw_actor_call_ms": round(raw_ms, 2),
        "handle_call_p50_ms": handle_ms,
        "overhead_ms": round(handle_ms - raw_ms, 2),
    }
    serve.shutdown()
    return out


# -- sustained-load mode (ISSUE 6 satellite) ---------------------------------


def _offered_load_trial(host_port: str, path: str, rate_hz: float,
                        duration_s: float, n_workers: int) -> Dict:
    """Open-loop load at `rate_hz` for `duration_s`: workers with
    persistent connections pull arrival slots off one shared schedule.
    Returns latencies + the worst schedule lag (send time minus the
    slot's nominal time) — sustained lag means the offered load exceeds
    what the plane drains, even before latencies blow up."""
    import http.client
    import itertools

    arrivals = itertools.count()
    t0 = time.perf_counter() + 0.05
    deadline_idx = int(rate_hz * duration_s)
    lat: list = []
    lags: list = []
    errors: list = []
    lock = threading.Lock()

    def worker():
        try:
            conn = http.client.HTTPConnection(host_port, timeout=30)
            my_lat, my_lags = [], []
            while True:
                i = next(arrivals)
                if i >= deadline_idx:
                    break
                target = t0 + i / rate_hz
                now = time.perf_counter()
                if now < target:
                    time.sleep(target - now)
                    now = time.perf_counter()
                my_lags.append(now - target)
                conn.request("GET", path)
                conn.getresponse().read()
                my_lat.append((time.perf_counter() - now) * 1e3)
            conn.close()
            with lock:
                lat.extend(my_lat)
                lags.extend(my_lags)
        except Exception as e:  # noqa: BLE001 — surfaced to the caller
            with lock:
                errors.append(e)

    threads = [threading.Thread(target=worker) for _ in range(n_workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        raise errors[0]
    return {"lat_ms": lat, "max_lag_s": max(lags) if lags else 0.0,
            "completed": len(lat)}


def run_sustained_benchmark(target_p99_ms: float = 5.0,
                            duration_s: float = 3.0,
                            num_shards: Optional[int] = None,
                            num_replicas: int = 2,
                            http_port: int = 0) -> Dict[str, dict]:
    """Binary-search the max offered HTTP rps holdable at
    p99 <= target_p99_ms through the sharded proxy. 'Holdable' = the
    p99 stays under target AND the arrival schedule never falls behind
    by more than 0.25s (otherwise the level is queueing, not serving)."""
    import ray_tpu
    from ray_tpu import serve

    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    http_port = http_port or 18437

    @serve.deployment(num_replicas=num_replicas)
    def echo(body=None):
        return "ok"

    serve.run(echo.bind(), name="sustained", http_port=http_port,
              http_shards=num_shards)
    handle = serve.get_deployment_handle("echo", "sustained")
    assert handle.remote(None).result(timeout_s=30) == "ok"
    host_port = f"127.0.0.1:{http_port}"
    # warm every shard's connection path
    _offered_load_trial(host_port, "/sustained", 50, 1.0, 4)

    def holds(rate_hz: float) -> Dict:
        n_workers = max(4, min(64, int(rate_hz * 0.04)))
        r = _offered_load_trial(host_port, "/sustained", rate_hz,
                                duration_s, n_workers)
        xs = sorted(r["lat_ms"])
        p99 = xs[min(len(xs) - 1, int(0.99 * len(xs)))] if xs else 1e9
        p50 = xs[len(xs) // 2] if xs else 1e9
        ok = (p99 <= target_p99_ms and r["max_lag_s"] < 0.25
              and r["completed"] >= 0.95 * rate_hz * duration_s)
        return {"ok": ok, "p50_ms": round(p50, 2), "p99_ms": round(p99, 2),
                "rate_hz": rate_hz, "max_lag_s": round(r["max_lag_s"], 3)}

    # geometric probe (up from 100, or down when even that fails — a
    # loaded CI host may hold only tens of rps at the target), then bisect
    lo, best = 0.0, None
    hi = None
    rate = 100.0
    for _ in range(8):
        r = holds(rate)
        if r["ok"]:
            lo, best = rate, r
            if hi is not None:
                break
            rate *= 2
        else:
            hi = rate
            if lo > 0 or rate <= 10.0:
                break
            rate /= 2
    if hi is not None and lo > 0:
        for _ in range(4):
            mid = (lo + hi) / 2
            if hi - lo < max(25.0, 0.1 * hi):
                break
            r = holds(mid)
            if r["ok"]:
                lo, best = mid, r
            else:
                hi = mid
    floor = None
    if best is None:
        # target unreachable on this host (a throttled CI share can have
        # a serial p50 above the whole p99 budget): report the floor
        # level's actual numbers so the artifact explains itself
        floor = holds(25.0)
    from ray_tpu.serve.context import get_controller

    shards = len(ray_tpu.get(
        get_controller().get_http_proxy_handles.remote()))
    serve.shutdown()
    out = best or {"ok": False, "p99_ms": None, "rate_hz": 0.0}
    result = {
        "rps": round(lo, 1),
        "target_p99_ms": target_p99_ms,
        "p50_ms": out.get("p50_ms"),
        "p99_ms": out.get("p99_ms"),
        "num_shards": shards,
        "num_replicas": num_replicas,
        "duration_s": duration_s,
        "note": ("max OFFERED open-loop rps held with p99 <= target and "
                 "no arrival-schedule backlog; binary search"),
    }
    if floor is not None:
        result["target_unreachable"] = True
        result["floor_25rps"] = {k: floor[k]
                                 for k in ("p50_ms", "p99_ms", "max_lag_s")}
    return {"serve_http_sustained": result}


# -- prefix-cache TTFT mode (ISSUE 6 satellite) ------------------------------


def run_prefix_ttft_benchmark(n_requests: int = 6,
                              shared_prefix_len: int = 448,
                              tail_len: int = 8) -> Dict[str, dict]:
    """Client-observed TTFT with a shared system prompt: every request
    carries the same `shared_prefix_len`-token prefix plus a unique
    tail. Cold = fresh prefixes of the SAME length (full prefill);
    hit = shared prefix already cached (tail-only prefill). Serial
    requests, so the delta is prefill compute, not queueing."""
    import random

    import jax

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.inference.paged_engine import PagedInferenceEngine
    from ray_tpu.models import llama
    from ray_tpu.serve.llm import build_llm_app

    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        config = llama.LlamaConfig.small_1b()
    else:
        # wider than tiny(): the benchmark separates prefill COMPUTE
        # from fixed routing/RPC overhead, so the shared-prefix prefill
        # must be the dominant term even on CPU
        config = llama.LlamaConfig(
            vocab_size=512, d_model=256, n_layers=4, n_heads=8,
            n_kv_heads=4, d_head=32, d_ff=512, max_seq_len=1024)
    params = llama.init(config, jax.random.PRNGKey(0))
    max_len = 2 * shared_prefix_len
    block = 16

    def build():
        return PagedInferenceEngine(params, config, max_batch=4,
                                    max_len=max_len, block_size=block,
                                    n_blocks=4 * (max_len // block),
                                    decode_chunk=4)

    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    app = build_llm_app(build, name="llm_prefix", num_replicas=1,
                        default_config={"max_new_tokens": 4},
                        shed_queue_depth=10_000)
    handle = serve.run(app, name="llm_prefix")
    stream = handle.options(method_name="stream_tokens", stream=True)
    rng = random.Random(0)

    def ttft(prompt) -> float:
        t0 = time.perf_counter()
        gen = stream.remote({"prompt": prompt, "max_new_tokens": 2})
        it = iter(gen)
        next(it)
        dt = (time.perf_counter() - t0) * 1e3
        gen.close()
        return dt

    def rand_tokens(n):
        return [1 + rng.randrange(30) for _ in range(n)]

    # compile both bucket programs (full-length + tail-length prefill)
    # out of the measurement
    ttft(rand_tokens(shared_prefix_len + tail_len))
    warm_prefix = rand_tokens(shared_prefix_len)
    ttft(warm_prefix + rand_tokens(tail_len))

    cold, hits = [], []
    for _ in range(n_requests):
        # fresh random prefix: a guaranteed cache miss at full length
        cold.append(ttft(rand_tokens(shared_prefix_len) +
                         rand_tokens(tail_len)))
        # shared prefix: tail-only prefill after the warmup request
        hits.append(ttft(warm_prefix + rand_tokens(tail_len)))

    controller = ray_tpu.get_actor("SERVE_CONTROLLER")
    replicas = ray_tpu.get(controller.get_replica_handles.remote(
        "llm_prefix", "llm_prefix_engine"))
    stats = ray_tpu.get(replicas[0].handle_request.remote(
        "get_stats", (), {}), timeout=30)
    pc = stats["engine"]["prefix_cache"]
    serve.shutdown()

    def p50(xs):
        return round(sorted(xs)[len(xs) // 2], 2)

    return {"llm_prefix_ttft": {
        "cold_p50_ms": p50(cold),
        "hit_p50_ms": p50(hits),
        "hit_over_cold": round(p50(hits) / max(p50(cold), 1e-9), 3),
        "shared_prefix_len": shared_prefix_len,
        "n_requests": n_requests,
        "cache": {k: pc.get(k) for k in
                  ("hit_requests", "miss_requests", "hit_tokens",
                   "evictions", "bytes_saved")},
        "note": ("serial client-observed TTFT through serve.llm; hit = "
                 "shared system prompt served from cached KV blocks"),
    }}


if __name__ == "__main__":
    import os
    import sys

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    modes = set(sys.argv[1:]) or {"classic", "sustained", "prefix"}
    out: Dict[str, dict] = {}
    if "classic" in modes:
        out.update(run_serve_benchmarks())
    if "sustained" in modes:
        out.update(run_sustained_benchmark())
    if "prefix" in modes:
        out.update(run_prefix_ttft_benchmark())
    print(json.dumps(out))
