"""Serve data-plane microbenchmarks (VERDICT r1 #10).

Measures what the reference's serve release benchmarks measure
(reference: python/ray/serve/_private/benchmarks/): end-to-end HTTP RPS +
latency percentiles through the proxy, handle-call RPS, and the
power-of-two router's queue-probe overhead vs a raw actor call.

Run: python -m ray_tpu.serve.benchmarks
"""

from __future__ import annotations

import json
import time
from typing import Dict


def _percentiles(samples_ms):
    xs = sorted(samples_ms)

    def pct(p):
        return round(xs[min(len(xs) - 1, int(p / 100 * len(xs)))], 2)

    return {"p50_ms": pct(50), "p90_ms": pct(90), "p99_ms": pct(99)}


def run_serve_benchmarks(n_requests: int = 200,
                         http_port: int = 0) -> Dict[str, dict]:
    import urllib.request

    import ray_tpu
    from ray_tpu import serve

    out: Dict[str, dict] = {}
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    http_port = http_port or 18431

    @serve.deployment
    def echo(body=None):
        return "ok"

    serve.run(echo.bind(), name="bench", http_port=http_port)
    handle = serve.get_deployment_handle("echo", "bench")

    # warm the replica + route
    assert handle.remote(None).result(timeout_s=30) == "ok"
    url = f"http://127.0.0.1:{http_port}/bench"
    with urllib.request.urlopen(url, timeout=10) as r:
        r.read()

    # -- handle path (router + replica actor call) --------------------------
    lat = []
    t0 = time.perf_counter()
    for _ in range(n_requests):
        s = time.perf_counter()
        handle.remote(None).result(timeout_s=30)
        lat.append((time.perf_counter() - s) * 1e3)
    dt = time.perf_counter() - t0
    out["serve_handle"] = {"rps": round(n_requests / dt, 1),
                           **_percentiles(lat)}

    # -- HTTP proxy path ----------------------------------------------------
    # persistent connections, like any real serving client: a fresh TCP
    # connect per request benchmarks the kernel's handshake, not the
    # proxy. Latency percentiles from one serial keep-alive connection;
    # throughput from 4 concurrent keep-alive clients.
    import http.client
    import threading as _threading

    host_port = f"127.0.0.1:{http_port}"
    conn = http.client.HTTPConnection(host_port, timeout=30)
    lat = []
    for _ in range(n_requests):
        s = time.perf_counter()
        conn.request("GET", "/bench")
        conn.getresponse().read()
        lat.append((time.perf_counter() - s) * 1e3)
    conn.close()

    counts = [0] * 4
    stop_at = time.perf_counter() + 3.0

    client_errors: list = []

    def _client(i: int):
        try:
            c = http.client.HTTPConnection(host_port, timeout=30)
            while time.perf_counter() < stop_at:
                c.request("GET", "/bench")
                c.getresponse().read()
                counts[i] += 1
            c.close()
        except Exception as e:  # noqa: BLE001 — surface after join
            client_errors.append(e)

    threads = [_threading.Thread(target=_client, args=(i,))
               for i in range(len(counts))]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0
    if client_errors:
        # a died client silently deflates rps; fail the run instead
        raise client_errors[0]
    out["serve_http"] = {"rps": round(sum(counts) / dt, 1),
                         "concurrency": len(counts),
                         **_percentiles(lat)}

    # -- router probe overhead ----------------------------------------------
    # the pow-2 router probes replica queue lengths before assignment
    # (reference: pow_2_scheduler.py:49); quantify it against a raw actor
    # round trip with no routing at all
    @ray_tpu.remote
    class Raw:
        def ping(self):
            return "ok"

    raw = Raw.remote()
    ray_tpu.get(raw.ping.remote())
    t0 = time.perf_counter()
    for _ in range(n_requests):
        ray_tpu.get(raw.ping.remote())
    raw_ms = (time.perf_counter() - t0) / n_requests * 1e3
    handle_ms = out["serve_handle"]["p50_ms"]
    out["router_probe_overhead"] = {
        "raw_actor_call_ms": round(raw_ms, 2),
        "handle_call_p50_ms": handle_ms,
        "overhead_ms": round(handle_ms - raw_ms, 2),
    }
    serve.shutdown()
    return out


if __name__ == "__main__":
    import os

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    print(json.dumps(run_serve_benchmarks()))
