"""Global serve client state (reference: ray python/ray/serve/context.py —
the per-driver handle to the controller, replica-internal context)."""

from __future__ import annotations

import threading
from typing import Any, Optional

_lock = threading.Lock()
_controller = None

CONTROLLER_NAME = "SERVE_CONTROLLER"


def get_controller(create: bool = False):
    """The ServeController detached actor (created on first use)."""
    global _controller
    import ray_tpu

    with _lock:
        if _controller is not None:
            return _controller
        try:
            _controller = ray_tpu.get_actor(CONTROLLER_NAME)
            return _controller
        except ValueError:
            if not create:
                raise RuntimeError(
                    "Serve is not running; call serve.start() or serve.run()"
                ) from None
        from ray_tpu.serve._private.controller import ServeController

        _controller = ray_tpu.remote(ServeController).options(
            name=CONTROLLER_NAME, lifetime="detached", num_cpus=0.1,
            max_concurrency=32,
        ).remote()
        ray_tpu.get(_controller.ping.remote())
        return _controller


def clear_controller_cache() -> None:
    global _controller
    with _lock:
        _controller = None


_replica_context = threading.local()


def get_multiplexed_model_id() -> str:
    return getattr(_replica_context, "multiplexed_model_id", "")


def set_multiplexed_model_id(model_id: str) -> None:
    _replica_context.multiplexed_model_id = model_id
