"""Global serve client state (reference: ray python/ray/serve/context.py —
the per-driver handle to the controller, replica-internal context)."""

from __future__ import annotations

import threading
from typing import Any, Optional

_lock = threading.Lock()
_controller = None

CONTROLLER_NAME = "SERVE_CONTROLLER"


def get_controller(create: bool = False):
    """The ServeController detached actor (created on first use)."""
    global _controller
    import ray_tpu

    with _lock:
        if _controller is not None:
            return _controller
        try:
            _controller = ray_tpu.get_actor(CONTROLLER_NAME)
            return _controller
        except ValueError:
            if not create:
                raise RuntimeError(
                    "Serve is not running; call serve.start() or serve.run()"
                ) from None
        from ray_tpu.serve._private.controller import ServeController

        # max_restarts=-1: an UNINTENDED controller death (crash, OOM,
        # node loss) restarts it in place — same actor id, same name —
        # and the fresh incarnation recovers from its GCS-KV checkpoint,
        # adopting live replicas/proxy shards instead of restarting them.
        # ray_tpu.kill() (serve.shutdown) stays terminal.
        _controller = ray_tpu.remote(ServeController).options(
            name=CONTROLLER_NAME, lifetime="detached", num_cpus=0.1,
            max_concurrency=256, max_restarts=-1,
        ).remote()
        ray_tpu.get(_controller.ping.remote())
        return _controller


def clear_controller_cache() -> None:
    global _controller
    with _lock:
        _controller = None


_replica_context = threading.local()


def get_multiplexed_model_id() -> str:
    return getattr(_replica_context, "multiplexed_model_id", "")


def set_multiplexed_model_id(model_id: str) -> None:
    _replica_context.multiplexed_model_id = model_id


class ReplicaContext:
    """What a deployment can learn about itself from inside a replica
    (reference: serve/context.py ReplicaContext + api.py:140
    get_replica_context)."""

    def __init__(self, app_name: str, deployment: str, replica_tag: str,
                 servable_object: Any):
        self.app_name = app_name
        self.deployment = deployment
        self.replica_tag = replica_tag
        self.servable_object = servable_object

    @property
    def replica_id(self) -> str:  # newer-API alias
        return self.replica_tag


_process_replica_context: Optional[ReplicaContext] = None


def get_replica_context() -> ReplicaContext:
    """Inside a replica: this replica's identity. Raises elsewhere
    (reference: api.py:164)."""
    if _process_replica_context is None:
        raise RuntimeError(
            "`serve.get_replica_context()` may only be called from within "
            "a Serve replica")
    return _process_replica_context


def set_replica_context(ctx: Optional[ReplicaContext]) -> None:
    global _process_replica_context
    _process_replica_context = ctx
