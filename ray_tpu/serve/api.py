"""Public Serve API: @deployment, run, start, shutdown, handles.

Reference: ray python/ray/serve/api.py — serve.run (:544) →
controller.deploy_application (controller.py:719); @serve.deployment
decorator builds Deployment objects; .bind() builds an application graph
whose non-ingress nodes become DeploymentHandles injected into the ingress
constructor (handle.py composition).
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Any, Callable, Dict, List, Optional, Union

from ray_tpu._private import serialization as ser
from ray_tpu.serve import context as serve_context
from ray_tpu.serve.handle import DeploymentHandle


@dataclasses.dataclass
class Application:
    """A bound deployment graph rooted at the ingress deployment."""

    root: "BoundDeployment"

    def _collect(self) -> List["BoundDeployment"]:
        seen: Dict[str, BoundDeployment] = {}

        def walk(node: BoundDeployment):
            if node.deployment.name in seen:
                return
            seen[node.deployment.name] = node
            for a in list(node.init_args) + list(node.init_kwargs.values()):
                child = _as_bound(a)
                if child is not None:
                    walk(child)

        walk(self.root)
        return list(seen.values())


@dataclasses.dataclass
class BoundDeployment:
    deployment: "Deployment"
    init_args: tuple
    init_kwargs: dict


def _as_bound(value: Any) -> Optional[BoundDeployment]:
    """bind() returns Application; nested graph args may be either form."""
    if isinstance(value, BoundDeployment):
        return value
    if isinstance(value, Application):
        return value.root
    return None


class Deployment:
    def __init__(self, func_or_class: Union[Callable, type],
                 name: Optional[str] = None,
                 num_replicas: Union[int, str, None] = None,
                 ray_actor_options: Optional[dict] = None,
                 user_config: Any = None,
                 max_ongoing_requests: int = 8,
                 autoscaling_config: Optional[dict] = None,
                 health_check_period_s: float = 2.0,
                 health_check_timeout_s: float = 5.0,
                 **_kw):
        self.func_or_class = func_or_class
        self.name = name or getattr(func_or_class, "__name__", "deployment")
        if num_replicas == "auto":
            autoscaling_config = autoscaling_config or {
                "min_replicas": 1, "max_replicas": 10,
                "target_ongoing_requests": 2}
            num_replicas = None
        self.num_replicas = num_replicas or 1
        self.ray_actor_options = ray_actor_options
        self.user_config = user_config
        self.max_ongoing_requests = max_ongoing_requests
        self.autoscaling_config = autoscaling_config
        self.health_check_period_s = health_check_period_s
        self.health_check_timeout_s = health_check_timeout_s

    def options(self, **overrides) -> "Deployment":
        merged = dict(
            name=self.name, num_replicas=self.num_replicas,
            ray_actor_options=self.ray_actor_options,
            user_config=self.user_config,
            max_ongoing_requests=self.max_ongoing_requests,
            autoscaling_config=self.autoscaling_config,
            health_check_period_s=self.health_check_period_s,
            health_check_timeout_s=self.health_check_timeout_s,
        )
        merged.update(overrides)
        return Deployment(self.func_or_class, **merged)

    def bind(self, *args, **kwargs) -> Application:
        return Application(BoundDeployment(self, args, kwargs))

    def __call__(self, *a, **kw):
        raise RuntimeError(
            "Deployments cannot be called directly; use handle.remote() or "
            "serve.run()")


def deployment(func_or_class=None, **options):
    """@serve.deployment decorator."""
    if func_or_class is not None and callable(func_or_class) and not options:
        return Deployment(func_or_class)

    def wrap(fc):
        return Deployment(fc, **options)

    return wrap


def ingress(asgi_app):
    """@serve.ingress(app) — host a FastAPI/Starlette/any-ASGI app inside
    the ingress deployment (reference: serve/api.py @serve.ingress +
    http_util.ASGIAppReplicaWrapper). The proxy forwards raw requests; the
    app runs in the replica. Routes are plain ASGI/FastAPI handlers (module
    functions or closures over the app) — self-injecting method routes are
    not supported.

        fastapi_app = FastAPI()

        @fastapi_app.get("/hello")
        def hello():
            return {"ok": True}

        @serve.deployment
        @serve.ingress(fastapi_app)
        class Api:
            pass
    """
    def wrap(cls):
        from ray_tpu.serve._private.asgi import run_asgi

        class ASGIIngress(cls):
            __serve_asgi__ = True

            async def __call__(self, request: dict):
                return await run_asgi(asgi_app, request or {})

        ASGIIngress.__name__ = getattr(cls, "__name__", "ASGIIngress")
        ASGIIngress.__qualname__ = ASGIIngress.__name__
        return ASGIIngress

    return wrap


def start(detached: bool = True, http_options: Optional[dict] = None,
          **_kw) -> None:
    serve_context.get_controller(create=True)
    if http_options and http_options.get("port"):
        _ensure_proxy(http_options)


_proxy = None
_grpc_proxy = None


def _ensure_grpc_proxy(grpc_options: Optional[dict] = None):
    """Per-cluster gRPC ingress (reference: proxy.py:540 gRPCProxy;
    `grpc_servicer_functions` from schema.py gRPCOptions)."""
    global _grpc_proxy
    import ray_tpu
    from ray_tpu.serve._private.grpc_proxy import GrpcProxyActor

    opts = grpc_options or {}
    servicers = opts.get("grpc_servicer_functions") or []
    if _grpc_proxy is None:
        actor = ray_tpu.remote(GrpcProxyActor).options(
            name="SERVE_GRPC_PROXY", lifetime="detached", num_cpus=0.1,
            get_if_exists=True, max_concurrency=256,
        ).remote(host=opts.get("host", "127.0.0.1"),
                 port=opts.get("port", 9000))
        port = ray_tpu.get(actor.ready.remote())
        _grpc_proxy = (actor, port)
    actor, _port = _grpc_proxy
    if servicers:
        # Registered out of band, never via ctor args: get_if_exists may
        # have attached to a proxy another driver already created (whose
        # ctor args would be silently discarded). The dispatch table is
        # mutable and registration idempotent, so this path covers fresh
        # and pre-existing proxies alike without a gRPC server restart.
        ray_tpu.get(actor.register_servicers.remote(servicers))
    return _grpc_proxy


def _ensure_proxy(http_options: Optional[dict] = None):
    """HTTP ingress = N proxy shard actors sharing one listen port; the
    CONTROLLER owns their lifecycle (spawn/health/restart/route pushes).
    `http_options`: host, port, num_shards (default min(4, cpus))."""
    global _proxy
    import ray_tpu

    opts = http_options or {}
    controller = serve_context.get_controller(create=True)
    ray_tpu.get(controller.ensure_http_proxies.remote(
        host=opts.get("host", "127.0.0.1"),
        port=opts.get("port", 8000),
        num_shards=opts.get("num_shards")), timeout=60)
    _proxy = controller
    return _proxy


def run(app: Application, *, name: str = "default", route_prefix: str = "/",
        _blocking: bool = False, http_port: Optional[int] = None,
        http_shards: Optional[int] = None,
        grpc_port: Optional[int] = None,
        grpc_servicer_functions: Optional[list] = None) -> DeploymentHandle:
    controller = serve_context.get_controller(create=True)
    import ray_tpu

    nodes = app._collect()
    deployments = []
    for node in nodes:
        d = node.deployment
        # Replace bound children with handles so replicas route directly.
        init_args = tuple(
            DeploymentHandle(_as_bound(a).deployment.name, name)
            if _as_bound(a) is not None else a
            for a in node.init_args)
        init_kwargs = {
            k: DeploymentHandle(_as_bound(v).deployment.name, name)
            if _as_bound(v) is not None else v
            for k, v in node.init_kwargs.items()}
        code_blob = ser.dumps_function(d.func_or_class)
        # code version (reference: deployment_state.py versioned replicas):
        # identifies WHAT a replica would be constructed from. A redeploy
        # with a different version rolls replicas; a user_config VALUE
        # change reconfigures in place — but removing user_config rolls
        # (live replicas can't be un-configured), hence the presence flag.
        # cloudpickle, not stdlib pickle: init args are routinely local
        # closures, and a repr() fallback would embed memory addresses,
        # making every redeploy look like a code change.
        extras = ser.dumps_function(
            (init_args, init_kwargs, d.ray_actor_options,
             d.max_ongoing_requests, d.user_config is None))
        version = hashlib.sha1(code_blob + extras).hexdigest()[:12]
        deployments.append({
            "name": d.name,
            "callable": code_blob,
            "version": version,
            "init_args": init_args,
            "init_kwargs": init_kwargs,
            "num_replicas": d.num_replicas,
            "ray_actor_options": d.ray_actor_options,
            "user_config": d.user_config,
            "max_ongoing_requests": d.max_ongoing_requests,
            "autoscaling_config": d.autoscaling_config,
            "health_check_period_s": d.health_check_period_s,
            "health_check_timeout_s": d.health_check_timeout_s,
        })
    import inspect as _inspect

    root_fc = app.root.deployment.func_or_class
    call_target = root_fc if not _inspect.isclass(root_fc) else getattr(
        root_fc, "__call__", None)
    ingress_flags = {
        "asgi": bool(getattr(root_fc, "__serve_asgi__", False)),
        "streaming": bool(
            call_target is not None
            and (_inspect.isgeneratorfunction(call_target)
                 or _inspect.isasyncgenfunction(call_target))),
        # streamed chunks are Server-Sent Events: the proxy sets
        # text/event-stream and anti-buffering headers
        "sse": bool(getattr(root_fc, "__serve_sse__", False)),
        # serve.llm apps: name of the engine deployment backing this
        # ingress, so any process can discover LLM apps (CLI/dashboard
        # metric collection) from the controller alone
        "llm_engine": getattr(root_fc, "__serve_llm_engine__", None),
        # router construction knobs: proxy shards build a PER-SHARD
        # embedded LLMRouter from these (shed bound / affinity TTL /
        # default token budget), so HTTP token streams skip the
        # router-deployment hop
        "llm_config": getattr(root_fc, "__serve_llm_config__", None),
    }
    ray_tpu.get(controller.deploy_application.remote(
        name, deployments, app.root.deployment.name, route_prefix,
        ingress_flags))
    if http_port is not None:
        # no explicit route push needed: shards that existed before this
        # deploy already got the push from deploy_application, and fresh
        # shards read the route table in ProxyActor.__init__ (which runs
        # after the deploy above committed)
        _ensure_proxy({"port": http_port, "num_shards": http_shards})
    if grpc_port is not None or grpc_servicer_functions:
        actor, _port = _ensure_grpc_proxy({
            "port": grpc_port if grpc_port is not None else 9000,
            "grpc_servicer_functions": grpc_servicer_functions})
        ray_tpu.get(actor.update_routes.remote())
    return DeploymentHandle(app.root.deployment.name, name)


def get_app_handle(name: str = "default") -> DeploymentHandle:
    controller = serve_context.get_controller()
    import ray_tpu

    info = ray_tpu.get(controller.get_app_info.remote(name))
    if info is None:
        raise ValueError(f"no application named {name!r}")
    return DeploymentHandle(info["ingress"], name)


def get_deployment_handle(deployment_name: str,
                          app_name: str = "default") -> DeploymentHandle:
    return DeploymentHandle(deployment_name, app_name)


def delete(name: str) -> None:
    controller = serve_context.get_controller()
    import ray_tpu

    ray_tpu.get(controller.delete_application.remote(name))


def status() -> Dict[str, Any]:
    controller = serve_context.get_controller()
    import ray_tpu

    apps = ray_tpu.get(controller.list_applications.remote())
    out = {}
    for app_name, info in apps.items():
        deps = {}
        for dep in info["deployments"]:
            deps[dep] = ray_tpu.get(
                controller.get_deployment_status.remote(app_name, dep))
        out[app_name] = {"deployments": deps,
                         "route_prefix": info["route_prefix"]}
    return out


def shutdown() -> None:
    global _proxy, _grpc_proxy
    import ray_tpu

    from ray_tpu.serve._private.router import shutdown_routers

    shutdown_routers()
    try:
        controller = serve_context.get_controller()
    except RuntimeError:
        return
    try:
        # controller.shutdown also kills the HTTP proxy shards it owns
        ray_tpu.get(controller.shutdown.remote(), timeout=30)
        ray_tpu.kill(controller)
    except Exception:  # noqa: BLE001 — best-effort teardown
        pass
    _proxy = None
    if _grpc_proxy is not None:
        actor, _port = _grpc_proxy
        try:
            ray_tpu.get(actor.stop.remote(), timeout=5)
            ray_tpu.kill(actor)
        except Exception:  # noqa: BLE001
            pass
        _grpc_proxy = None
    serve_context.clear_controller_cache()
