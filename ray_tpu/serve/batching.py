"""Dynamic request batching (reference: ray python/ray/serve/batching.py —
@serve.batch :468, queue :80: requests accumulate until max_batch_size or
batch_wait_timeout_s, then the wrapped method is called once with the list).

On TPU replicas this is the path to compiled-shape batched inference: the
batch handler pads to a bucketed batch size so XLA reuses a small set of
compiled programs (SURVEY §7 "async serving on TPU": batching + bucketing).
"""

from __future__ import annotations

import functools
import inspect
import queue
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, List, Optional


class _BatchQueue:
    """One batching thread per bound target (per replica instance)."""

    def __init__(self, handler: Callable, max_batch_size: int,
                 batch_wait_timeout_s: float):
        self._handler = handler
        self._max = max_batch_size
        self._timeout = batch_wait_timeout_s
        # fed only by this replica's in-flight requests: bounded upstream
        # by the deployment's max_ongoing_requests admission
        self._queue: "queue.Queue[tuple]" = queue.Queue()  # raylint: disable=unbounded-queue
        self._thread = threading.Thread(
            target=self._loop, name="serve-batch", daemon=True)
        self._thread.start()

    def submit(self, item: Any) -> Future:
        fut: Future = Future()
        self._queue.put((item, fut))
        return fut

    def _loop(self) -> None:
        while True:
            batch: List[tuple] = [self._queue.get()]
            deadline = time.monotonic() + self._timeout
            while len(batch) < self._max:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    batch.append(self._queue.get(timeout=remaining))
                except queue.Empty:
                    break
            items = [b[0] for b in batch]
            futures = [b[1] for b in batch]
            try:
                results = self._handler(items)
                if len(results) != len(items):
                    raise ValueError(
                        f"batch handler returned {len(results)} results for "
                        f"{len(items)} inputs")
                for fut, res in zip(futures, results):
                    fut.set_result(res)
            except Exception as e:  # noqa: BLE001 — propagate per-request
                for fut in futures:
                    fut.set_exception(e)


def batch(_func: Optional[Callable] = None, *, max_batch_size: int = 10,
          batch_wait_timeout_s: float = 0.01):
    """Decorator: the wrapped fn receives a LIST of requests and returns a
    list of responses of the same length."""

    def wrap(fn: Callable):
        params = list(inspect.signature(fn).parameters)
        is_method = bool(params) and params[0] == "self"
        # No locks/threads in the closure: the deployment class gets pickled
        # to replicas, so runtime state lives in a process-local registry
        # keyed by (wrapped fn, instance) and is created on first call.
        if is_method:
            @functools.wraps(fn)
            def wrapper(self, item):
                bq = _get_queue(fn, self, max_batch_size,
                                batch_wait_timeout_s)
                return bq.submit(item).result(timeout=60)
        else:
            @functools.wraps(fn)
            def wrapper(item):
                bq = _get_queue(fn, None, max_batch_size,
                                batch_wait_timeout_s)
                return bq.submit(item).result(timeout=60)

        wrapper._is_serve_batch = True  # type: ignore[attr-defined]
        return wrapper

    if _func is not None:
        return wrap(_func)
    return wrap


_queues_lock = threading.Lock()
_queues: dict = {}


def _get_queue(fn: Callable, instance, max_batch_size: int,
               batch_wait_timeout_s: float) -> _BatchQueue:
    key = (id(fn), id(instance))
    with _queues_lock:
        bq = _queues.get(key)
        if bq is None:
            handler = (lambda items: fn(instance, items)) \
                if instance is not None else fn
            bq = _BatchQueue(handler, max_batch_size, batch_wait_timeout_s)
            _queues[key] = bq
        return bq
